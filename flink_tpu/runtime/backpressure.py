"""Back-pressure sampling.

The reference samples task threads' stacks over REST and reports the
ratio blocked in `requestBufferBlocking`
(flink-runtime/.../rest/handler/legacy/backpressure/
StackTraceSampleCoordinator.java:52, BackPressureStatsTrackerImpl
.java:66 — ratio OK < 0.10 <= LOW < 0.50 <= HIGH).  The rebuild's
runnability condition is explicit rather than thread-stack-implicit:
a subtask is backpressured exactly when its router has no output
capacity (`_RouterOutput.has_capacity()` false — bounded downstream
queues full / remote credit exhausted).  So a "sample" here reads
that predicate directly, N times over a window, per subtask."""

from __future__ import annotations

import time as _time
from typing import Dict, List, Optional


#: the reference's thresholds (BackPressureStatsTrackerImpl)
OK_THRESHOLD = 0.10
LOW_THRESHOLD = 0.50

#: how long one observed out-of-capacity moment keeps a subtask
#: counting as blocked in the gauge read.  A truly blocked producer
#: thread briefly shows free capacity right after the consumer pops a
#: record and before the producer refills (its wait-loop poll needs
#: the GIL, switch interval 5 ms) — the producer stamps
#: `router.last_blocked_mono` while waiting, and the gauge honours
#: stamps this recent, so a point read cannot race the refill.  Kept
#: well under one alert window (5 samples) so a single transient
#: blockage cannot read as sustained.
BLOCKED_STICKY_WINDOW_S = 0.015


def classify(ratio: float) -> str:
    if ratio < OK_THRESHOLD:
        return "ok"
    if ratio < LOW_THRESHOLD:
        return "low"
    return "high"


def sample_backpressure(subtasks_by_vertex: Dict[int, List],
                        num_samples: int = 20,
                        delay_s: float = 0.005) -> Dict[int, dict]:
    """`subtasks_by_vertex` is the executor's live map (vertex_id ->
    [SubtaskInstance]).  Returns per-vertex ratios + levels (the
    OperatorBackPressureStats shape)."""
    from flink_tpu.runtime.profiler import sample_windowed
    counts: Dict[int, List[int]] = {
        vid: [0] * len(sts) for vid, sts in subtasks_by_vertex.items()}

    def probe(_s: int) -> None:
        for vid, sts in subtasks_by_vertex.items():
            for i, st in enumerate(sts):
                # reading queue lengths cross-thread is safe (len on
                # deques); a torn read only perturbs one sample
                if not st.router.has_capacity():
                    counts[vid][i] += 1

    # the profiler owns the tree's one windowed-sampling core; this
    # sampler only supplies the capacity-predicate probe
    sample_windowed(probe, num_samples, delay_s)
    out: Dict[int, dict] = {}
    for vid, per_subtask in counts.items():
        ratios = [c / num_samples for c in per_subtask]
        worst = max(ratios) if ratios else 0.0
        out[vid] = {"subtask_ratios": ratios, "max_ratio": worst,
                    "level": classify(worst)}
    return out


def sample_client(client, num_samples: int = 20,
                  delay_s: float = 0.005) -> Dict[int, dict]:
    """Sample a running job via its JobClient (executor_state)."""
    state = client.executor_state or {}
    subtasks = state.get("subtasks")
    if not subtasks:
        return {}
    return sample_backpressure(subtasks, num_samples, delay_s)


def router_blocked(router, now: Optional[float] = None) -> bool:
    """The sticky-window blocked predicate shared by the gauge read
    and time attribution: out of capacity right now, or a producer
    stamped ``last_blocked_mono`` within the sticky window (a point
    read cannot race the consumer's refill)."""
    if now is None:
        now = _time.monotonic()
    if not router.has_capacity():
        router.last_blocked_mono = now
        return True
    return (now - getattr(router, "last_blocked_mono", 0.0)
            < BLOCKED_STICKY_WINDOW_S)


def register_backpressure_gauges(vertex_group, subtasks: List) -> None:
    """Publish the vertex's backpressure classification as gauges
    (``backpressure.ratio`` numeric + ``backpressure.level`` string).
    Read-time sampling is a single pass over the capacity predicate
    plus the producers' recent-blockage stamps (the
    ``backPressuredTimeMsPerSecond`` idea: time-aware, not a racy
    instant) — cheap enough for every metrics dump; callers wanting
    the smoothed N-sample window keep using
    :func:`sample_backpressure`."""
    group = vertex_group.add_group("backpressure")

    def ratio() -> float:
        if not subtasks:
            return 0.0
        now = _time.monotonic()
        return (sum(1 for st in subtasks
                    if router_blocked(st.router, now))
                / len(subtasks))

    group.gauge("ratio", ratio)
    group.gauge("level", lambda: classify(ratio()))


def read_backpressure_gauges(dump: Dict[str, object],
                             job_name: str) -> Dict[int, dict]:
    """Serve backpressure from an already-collected registry dump (the
    ``<job>.<vid>_<vname>.backpressure.ratio`` sticky-window gauges)
    instead of re-sampling inline — a REST hit must not block its
    caller for the sampler's full num_samples × delay window.  Returns
    the :func:`sample_backpressure` shape so consumers cannot tell the
    difference (``subtask_ratios`` carries the single vertex-level
    read; the active sampler remains for per-subtask resolution)."""
    prefix = job_name + "."
    suffix = ".backpressure.ratio"
    out: Dict[int, dict] = {}
    for key, value in dump.items():
        if not (key.startswith(prefix) and key.endswith(suffix)):
            continue
        token = key[len(prefix):-len(suffix)]
        try:
            vid = int(token.split("_", 1)[0])
            ratio = float(value)  # type: ignore[arg-type]
        except (ValueError, TypeError):
            continue
        out[vid] = {"subtask_ratios": [ratio], "max_ratio": ratio,
                    "level": classify(ratio)}
    return out


# ---------------------------------------------------------------------
# time attribution (ref: busyTimeMsPerSecond / idleTimeMsPerSecond /
# backPressuredTimeMsPerSecond on TaskIOMetricGroup)
# ---------------------------------------------------------------------

class TimeAccounting:
    """Per-subtask wall-time attribution.  The executor loop observes
    each subtask once per pass; the interval since that subtask's
    previous observation is classified into EXACTLY one bucket —
    progress ⇒ busy, router-blocked ⇒ backpressured, otherwise idle —
    so the three cumulative counters tile elapsed time with no gap or
    double count, and the per-second rate gauges sum to ~1000 ms/s by
    construction (the invariant the tests pin)."""

    __slots__ = ("busy_ns", "idle_ns", "backpressured_ns", "_last_ns",
                 "_win_start_ns", "_win", "_rates", "last_class")

    #: refresh the windowed rate gauges at most this often (~5 Hz)
    WINDOW_NS = 200_000_000

    def __init__(self):
        self.busy_ns = 0
        self.idle_ns = 0
        self.backpressured_ns = 0
        self._last_ns: Optional[int] = None
        self._win_start_ns: Optional[int] = None
        self._win = [0, 0, 0]
        self._rates = (0.0, 0.0, 0.0)
        #: the class of the most recent observation in the sampling
        #: profiler's encoding (0 on-CPU/busy, 1 off-CPU/idle,
        #: 2 backpressured) — read cross-thread by the profiler to
        #: classify stack samples; None until the first interval
        self.last_class: Optional[int] = None

    def observe(self, made_progress: bool, blocked: bool,
                now_ns: Optional[int] = None) -> None:
        now = _time.perf_counter_ns() if now_ns is None else now_ns
        last = self._last_ns
        self._last_ns = now
        if last is None:
            self._win_start_ns = now
            return
        dt = now - last
        if dt <= 0:
            return
        if made_progress:
            self.busy_ns += dt
            self._win[0] += dt
            self.last_class = 0
        elif blocked:
            self.backpressured_ns += dt
            self._win[2] += dt
            self.last_class = 2
        else:
            self.idle_ns += dt
            self._win[1] += dt
            self.last_class = 1
        span = now - self._win_start_ns
        if span >= self.WINDOW_NS:
            # ns-in-bucket / ns-elapsed × 1000 ⇒ ms per second; the
            # tuple swap is atomic so gauge reads never tear
            scale = 1000.0 / span
            self._rates = (self._win[0] * scale, self._win[1] * scale,
                           self._win[2] * scale)
            self._win = [0, 0, 0]
            self._win_start_ns = now

    def rates(self) -> tuple:
        """(busy, idle, backPressured) in ms/s over the last completed
        window; zeros until the first window elapses."""
        return self._rates


def register_time_attribution_gauges(subtask_group, acct: TimeAccounting
                                     ) -> None:
    """Per-subtask attribution gauges, journaled with everything else
    the MetricsJournal samples."""
    subtask_group.gauge("busyTimeMsPerSecond", lambda: acct.rates()[0])
    subtask_group.gauge("idleTimeMsPerSecond", lambda: acct.rates()[1])
    subtask_group.gauge("backPressuredTimeMsPerSecond",
                        lambda: acct.rates()[2])


def observe_subtask(st, progressed) -> None:
    """One attribution observation for a stepped subtask (called by
    every executor loop after the subtask's step/source_step)."""
    acct = getattr(st, "time_accounting", None)
    if acct is None:
        return
    if progressed:
        acct.observe(True, False)
    else:
        acct.observe(False, router_blocked(st.router))


def observe_threaded_source(st) -> None:
    """Attribution for a threaded source: its emissions happen on the
    source thread, so the emit wait-loop's ``last_blocked_mono`` stamps
    take precedence — a blocked-but-trickling source spends the pass
    waiting on capacity, not working.  Otherwise progress is inferred
    from the router's records-out counter delta (falling back to
    queued output when metrics are off)."""
    acct = getattr(st, "time_accounting", None)
    if acct is None:
        return
    counter = getattr(st.router, "records_out_counter", None)
    if counter is not None:
        count = counter.count
        progressed = count != getattr(st, "_attribution_last_out", None)
        st._attribution_last_out = count
    else:
        progressed = st.router.has_queued_output()
    if router_blocked(st.router):
        acct.observe(False, True)
    else:
        acct.observe(progressed, False)


# ---------------------------------------------------------------------
# bottleneck localization
# ---------------------------------------------------------------------

#: a vertex counts as busy-saturated when its busiest subtask spends
#: at least this much of each second doing work
BUSY_SATURATION_MS_PER_S = 500.0


def derive_upstreams(job_graph) -> Dict[int, List[int]]:
    """vertex_id -> upstream vertex_ids, from the JobGraph's edges
    (feedback edges excluded: a cycle must not make a vertex its own
    upstream for the walk)."""
    ups: Dict[int, List[int]] = {vid: [] for vid in job_graph.vertices}
    for edge in job_graph.edges:
        if getattr(edge, "is_feedback", False):
            continue
        src, dst = edge.source_vertex_id, edge.target_vertex_id
        if src != dst and src not in ups.setdefault(dst, []):
            ups[dst].append(src)
    return ups


def read_vertex_stats(dump: Dict[str, object],
                      job_name: str) -> Dict[int, dict]:
    """Per-vertex bottleneck inputs from a registry dump: the
    sticky-window ``backpressure.ratio`` gauge and the max
    ``busyTimeMsPerSecond`` across the vertex's subtasks."""
    prefix = job_name + "."
    stats: Dict[int, dict] = {}

    def entry(token: str) -> Optional[dict]:
        head = token.split("_", 1)
        try:
            vid = int(head[0])
        except ValueError:
            return None
        e = stats.get(vid)
        if e is None:
            e = stats[vid] = {
                "vertex_id": vid,
                "name": head[1] if len(head) > 1 else token,
                "busy_ms_per_s": None, "backpressure_ratio": 0.0}
        return e

    bp_suffix = ".backpressure.ratio"
    busy_suffix = ".busyTimeMsPerSecond"
    for key, value in dump.items():
        if not key.startswith(prefix):
            continue
        rest = key[len(prefix):]
        if rest.endswith(bp_suffix):
            e = entry(rest[:-len(bp_suffix)])
            if e is not None:
                try:
                    e["backpressure_ratio"] = float(value)  # type: ignore
                except (ValueError, TypeError):
                    pass
        elif rest.endswith(busy_suffix):
            # <vid>_<vname>.<subtask>.busyTimeMsPerSecond
            e = entry(rest[:-len(busy_suffix)].rsplit(".", 1)[0])
            if e is not None:
                try:
                    v = float(value)  # type: ignore[arg-type]
                except (ValueError, TypeError):
                    continue
                e["busy_ms_per_s"] = (v if e["busy_ms_per_s"] is None
                                      else max(e["busy_ms_per_s"], v))
    return stats


def locate_bottleneck(upstreams: Dict[int, List[int]],
                      vertex_stats: Dict[int, dict],
                      busy_threshold: float = BUSY_SATURATION_MS_PER_S,
                      ratio_threshold: float = LOW_THRESHOLD
                      ) -> Optional[dict]:
    """Walk the graph downstream-first: the bottleneck is the MOST
    DOWNSTREAM busy-saturated vertex with at least one backpressured
    upstream — pressure propagates upstream from the slow consumer, so
    the deepest such vertex is where the capacity is actually missing
    (everything above it is a victim, everything below is starved)."""
    depth: Dict[int, int] = {}

    def _depth(v: int, seen: tuple = ()) -> int:
        if v in depth:
            return depth[v]
        if v in seen:
            return 0
        ups = upstreams.get(v) or []
        d = 1 + max((_depth(u, seen + (v,)) for u in ups), default=-1)
        depth[v] = d
        return d

    vids = set(upstreams) | set(vertex_stats)
    for v in vids:
        _depth(v)
    candidates = []
    for vid in vids:
        st = vertex_stats.get(vid) or {}
        busy = st.get("busy_ms_per_s")
        if busy is None or busy < busy_threshold:
            continue
        bp_ups = []
        for u in upstreams.get(vid) or []:
            ust = vertex_stats.get(u) or {}
            ratio = ust.get("backpressure_ratio") or 0.0
            if ratio >= ratio_threshold:
                bp_ups.append({"vertex_id": u, "name": ust.get("name"),
                               "ratio": ratio})
        if bp_ups:
            candidates.append((depth.get(vid, 0), vid, st, bp_ups))
    if not candidates:
        return None
    candidates.sort(key=lambda c: (c[0], c[1]))
    d, vid, st, bp_ups = candidates[-1]
    return {"vertex_id": vid, "name": st.get("name"),
            "busyMsPerSecond": st.get("busy_ms_per_s"),
            "backpressured_upstreams": bp_ups, "depth": d}
