"""Back-pressure sampling.

The reference samples task threads' stacks over REST and reports the
ratio blocked in `requestBufferBlocking`
(flink-runtime/.../rest/handler/legacy/backpressure/
StackTraceSampleCoordinator.java:52, BackPressureStatsTrackerImpl
.java:66 — ratio OK < 0.10 <= LOW < 0.50 <= HIGH).  The rebuild's
runnability condition is explicit rather than thread-stack-implicit:
a subtask is backpressured exactly when its router has no output
capacity (`_RouterOutput.has_capacity()` false — bounded downstream
queues full / remote credit exhausted).  So a "sample" here reads
that predicate directly, N times over a window, per subtask."""

from __future__ import annotations

import time as _time
from typing import Dict, List


#: the reference's thresholds (BackPressureStatsTrackerImpl)
OK_THRESHOLD = 0.10
LOW_THRESHOLD = 0.50

#: how long one observed out-of-capacity moment keeps a subtask
#: counting as blocked in the gauge read.  A truly blocked producer
#: thread briefly shows free capacity right after the consumer pops a
#: record and before the producer refills (its wait-loop poll needs
#: the GIL, switch interval 5 ms) — the producer stamps
#: `router.last_blocked_mono` while waiting, and the gauge honours
#: stamps this recent, so a point read cannot race the refill.  Kept
#: well under one alert window (5 samples) so a single transient
#: blockage cannot read as sustained.
BLOCKED_STICKY_WINDOW_S = 0.015


def classify(ratio: float) -> str:
    if ratio < OK_THRESHOLD:
        return "ok"
    if ratio < LOW_THRESHOLD:
        return "low"
    return "high"


def sample_backpressure(subtasks_by_vertex: Dict[int, List],
                        num_samples: int = 20,
                        delay_s: float = 0.005) -> Dict[int, dict]:
    """`subtasks_by_vertex` is the executor's live map (vertex_id ->
    [SubtaskInstance]).  Returns per-vertex ratios + levels (the
    OperatorBackPressureStats shape)."""
    counts: Dict[int, List[int]] = {
        vid: [0] * len(sts) for vid, sts in subtasks_by_vertex.items()}
    for s in range(num_samples):
        for vid, sts in subtasks_by_vertex.items():
            for i, st in enumerate(sts):
                # reading queue lengths cross-thread is safe (len on
                # deques); a torn read only perturbs one sample
                if not st.router.has_capacity():
                    counts[vid][i] += 1
        if s < num_samples - 1:
            _time.sleep(delay_s)
    out: Dict[int, dict] = {}
    for vid, per_subtask in counts.items():
        ratios = [c / num_samples for c in per_subtask]
        worst = max(ratios) if ratios else 0.0
        out[vid] = {"subtask_ratios": ratios, "max_ratio": worst,
                    "level": classify(worst)}
    return out


def sample_client(client, num_samples: int = 20,
                  delay_s: float = 0.005) -> Dict[int, dict]:
    """Sample a running job via its JobClient (executor_state)."""
    state = client.executor_state or {}
    subtasks = state.get("subtasks")
    if not subtasks:
        return {}
    return sample_backpressure(subtasks, num_samples, delay_s)


def register_backpressure_gauges(vertex_group, subtasks: List) -> None:
    """Publish the vertex's backpressure classification as gauges
    (``backpressure.ratio`` numeric + ``backpressure.level`` string).
    Read-time sampling is a single pass over the capacity predicate
    plus the producers' recent-blockage stamps (the
    ``backPressuredTimeMsPerSecond`` idea: time-aware, not a racy
    instant) — cheap enough for every metrics dump; callers wanting
    the smoothed N-sample window keep using
    :func:`sample_backpressure`."""
    group = vertex_group.add_group("backpressure")

    def ratio() -> float:
        if not subtasks:
            return 0.0
        now = _time.monotonic()
        blocked = 0
        for st in subtasks:
            router = st.router
            if not router.has_capacity():
                router.last_blocked_mono = now
                blocked += 1
            elif (now - getattr(router, "last_blocked_mono", 0.0)
                    < BLOCKED_STICKY_WINDOW_S):
                blocked += 1
        return blocked / len(subtasks)

    group.gauge("ratio", ratio)
    group.gauge("level", lambda: classify(ratio()))
