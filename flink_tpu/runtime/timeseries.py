"""Metrics time-series journal + health alerts — the job history plane.

`MetricsJournal` snapshots a `MetricRegistry` into fixed-size ring
buffers per metric on a configurable cadence
(`metrics.sample.interval.ms` / `metrics.history.size` in
`core/config.py`), so point-in-time gauges become queryable trends:
the REST route `/jobs/<name>/metrics/history` and the ROADMAP-3
reactive autoscaler both read from here.  `HealthEvaluator` runs
threshold rules over those trends and emits structured alert events
(`/jobs/<name>/alerts`, `health.*` gauges).

Reference analogues: the journal plays the role of Flink's metric
fetcher + store behind the web frontend
(flink-runtime/.../webmonitor/metrics/MetricStore.java), the alerts
are the trigger predicate a reactive-mode autoscaler consumes.

Design notes (single-owner loop): sampling is driven by the executor
loop (`maybe_sample` is a two-comparison no-op when disabled or not
yet due), while REST handler threads query concurrently — a plain
lock guards the ring buffers; sampling cadence is tens of ms so the
contention is negligible.  Cross-process TaskExecutors ship raw
registry dumps to the JobMaster over the RPC plane (`ingest`), which
re-stamps them with the master's monotonic clock — wall-clock is the
query axis, monotonic aligns samples with tracer spans.
"""

from __future__ import annotations

import fnmatch
import threading
import time as _time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

__all__ = [
    "MetricsJournal",
    "HealthEvaluator",
    "register_health_gauges",
    "rollup",
]

#: sample tuple layout: (t_mono_ms, t_wall_ms, value)
Sample = Tuple[float, float, float]


def _numeric_items(metrics: Dict[str, Any]):
    """Flatten a registry dump into (key, float) pairs: dict-valued
    metrics (histograms, meters) expand to `key.sub`; strings, bools
    and None are dropped — the journal stores numbers only."""
    for key, value in metrics.items():
        if isinstance(value, dict):
            for sub, v in value.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    yield f"{key}.{sub}", float(v)
        elif isinstance(value, (int, float)) and not isinstance(value, bool):
            yield key, float(value)


def rollup(values: List[float]) -> Dict[str, float]:
    """min/max/avg/p95 over a list of samples (empty -> count 0)."""
    if not values:
        return {"count": 0}
    ordered = sorted(values)
    n = len(ordered)
    return {
        "count": n,
        "min": ordered[0],
        "max": ordered[-1],
        "avg": sum(ordered) / n,
        "p95": ordered[min(n - 1, int(0.95 * n))],
    }


class MetricsJournal:
    """Fixed-size per-metric ring buffers over registry snapshots.

    Disabled (interval_ms None) the per-loop cost is one attribute
    read and one comparison in `maybe_sample`; enabled, a snapshot
    runs every `interval_ms` at most.
    """

    def __init__(self, registry=None, interval_ms: Optional[int] = None,
                 history_size: int = 1024,
                 clock: Callable[[], float] = None,
                 wall_clock: Callable[[], float] = None):
        self.registry = registry
        self.interval_ms = interval_ms
        self.history_size = max(2, int(history_size or 1024))
        self._clock = clock or (lambda: _time.monotonic() * 1000.0)
        self._wall = wall_clock or (lambda: _time.time() * 1000.0)
        self._lock = threading.Lock()
        self._series: Dict[str, Deque[Sample]] = {}
        self._next_due = 0.0
        self.samples_taken = 0

    @property
    def enabled(self) -> bool:
        return self.interval_ms is not None

    # ---- recording ---------------------------------------------------
    def maybe_sample(self, now_ms: Optional[float] = None) -> bool:
        """Called from the owning executor loop every iteration; takes
        a snapshot when one is due.  Returns True iff it sampled."""
        if self.interval_ms is None:
            return False
        now = self._clock() if now_ms is None else now_ms
        if now < self._next_due:
            return False
        self._next_due = now + self.interval_ms
        self.sample_now(now)
        return True

    def sample_now(self, now_ms: Optional[float] = None) -> None:
        """Take one snapshot of the attached registry immediately."""
        if self.registry is None:
            return
        t_mono = self._clock() if now_ms is None else now_ms
        self._record(t_mono, self._wall(), self.registry.dump())

    def ingest(self, t_wall_ms: float, metrics: Dict[str, Any]) -> None:
        """Record a snapshot shipped from another process (cluster
        TaskExecutors).  The remote monotonic clock is meaningless
        here, so samples are re-stamped with the local one."""
        self._record(self._clock(), t_wall_ms, metrics)

    def _record(self, t_mono: float, t_wall: float,
                metrics: Dict[str, Any]) -> None:
        with self._lock:
            for key, value in _numeric_items(metrics):
                series = self._series.get(key)
                if series is None:
                    series = deque(maxlen=self.history_size)
                    self._series[key] = series
                series.append((t_mono, t_wall, value))
            self.samples_taken += 1

    # ---- querying ----------------------------------------------------
    def keys(self, pattern: str = "*") -> List[str]:
        with self._lock:
            return sorted(k for k in self._series
                          if fnmatch.fnmatchcase(k, pattern))

    def series(self, key: str,
               since_wall_ms: Optional[float] = None) -> List[Sample]:
        with self._lock:
            samples = list(self._series.get(key, ()))
        if since_wall_ms is not None:
            samples = [s for s in samples if s[1] >= since_wall_ms]
        return samples

    def latest(self, key: str) -> Optional[float]:
        with self._lock:
            series = self._series.get(key)
            return series[-1][2] if series else None

    def query(self, pattern: str = "*",
              since_wall_ms: Optional[float] = None,
              buckets: Optional[int] = None) -> Dict[str, Any]:
        """The REST `/jobs/<name>/metrics/history` payload: per
        matching metric the raw (t_wall_ms, value) samples, an overall
        rollup, and — when `buckets` is given — per-time-bucket
        rollups of the covered window."""
        out: Dict[str, Any] = {}
        for key in self.keys(pattern):
            samples = self.series(key, since_wall_ms)
            if not samples:
                continue
            entry: Dict[str, Any] = {
                "samples": [[s[1], s[2]] for s in samples],
                "rollup": rollup([s[2] for s in samples]),
            }
            if buckets and buckets > 0 and len(samples) > 1:
                entry["buckets"] = self._bucketize(samples, buckets)
            out[key] = entry
        return {
            "metric": pattern,
            "since": since_wall_ms,
            "sample_interval_ms": self.interval_ms,
            "history_size": self.history_size,
            "series": out,
        }

    @staticmethod
    def _bucketize(samples: List[Sample], buckets: int) -> List[dict]:
        t0, t1 = samples[0][1], samples[-1][1]
        width = max((t1 - t0) / buckets, 1e-9)
        binned: List[List[float]] = [[] for _ in range(buckets)]
        for _, t_wall, value in samples:
            idx = min(buckets - 1, int((t_wall - t0) / width))
            binned[idx].append(value)
        return [dict(t_start_ms=t0 + i * width, t_end_ms=t0 + (i + 1) * width,
                     **rollup(vals))
                for i, vals in enumerate(binned)]

    # ---- archiving ---------------------------------------------------
    def to_payload(self) -> Dict[str, Any]:
        """JSON-safe dump for the FsJobArchivist bundle."""
        with self._lock:
            series = {k: [list(s) for s in v]
                      for k, v in self._series.items()}
        return {
            "interval_ms": self.interval_ms,
            "history_size": self.history_size,
            "samples_taken": self.samples_taken,
            "series": series,
        }

    @classmethod
    def from_payload(cls, payload: Dict[str, Any]) -> "MetricsJournal":
        """Rehydrate an archived journal so the HistoryServer can
        serve the same `/metrics/history` queries as live REST."""
        j = cls(registry=None,
                interval_ms=payload.get("interval_ms"),
                history_size=payload.get("history_size") or 1024)
        for key, samples in (payload.get("series") or {}).items():
            j._series[key] = deque(
                (tuple(s) for s in samples), maxlen=j.history_size)
        j.samples_taken = payload.get("samples_taken", 0)
        return j


# ---------------------------------------------------------------------------
# health rules
# ---------------------------------------------------------------------------

class HealthEvaluator:
    """Threshold rules over the journal, emitting structured alerts.

    Each rule has EPISODE semantics: it fires exactly once when its
    predicate first holds and re-arms only after the predicate clears
    — a sustained condition produces one alert, not one per sample.
    This predicate surface is what the ROADMAP-3 reactive autoscaler
    will consume.

    Rules:
      * ``backpressure-sustained`` — a ``*.backpressure.ratio`` series
        stayed above `bp_ratio_threshold` for `bp_consecutive`
        consecutive samples.
      * ``watermark-lag-growing`` — a ``*.watermarkLag`` series grew
        strictly monotonically over `lag_consecutive` samples.
      * ``checkpoint-duration-over-budget`` — the coordinator's
        completed-checkpoint duration p95 exceeds
        `checkpoint_p95_budget_ms` (rule disabled while the budget is
        None).
      * ``bottleneck-stable`` — bottleneck localization
        (`bottleneck_supplier`, runtime/backpressure.py
        `locate_bottleneck`) named the SAME vertex for
        `bottleneck_consecutive` consecutive evaluations — a stable
        localization, not a transient blip (the autoscaler's scale-up
        target signal).
      * ``transfer-tax`` — the device ledger's D2H-fire-reads per
        fired-window ratio (deltas of ``device.fireReads`` over
        ``device.windowsFired``) stayed above
        `transfer_tax_threshold` for `transfer_tax_consecutive`
        consecutive sample intervals: the job is paying a per-result
        device readback tax (docs/state.md's fire-path caveat) instead
        of amortizing fires over batched reads.
      * ``key-skew-sustained`` — the keyed-state introspection plane's
        ``state.keyGroupSkew`` gauge (max/mean occupied key-group
        load) stayed above `key_skew_threshold` for
        `key_skew_consecutive` consecutive samples: one or a few hot
        key groups carry the traffic.  The alert names the hottest key
        group (``state.hotKeyGroup``) — the rescale/partitioning input
        ROADMAP item 4 consumes.  Quiet while introspection is
        disabled (the gauge reads 0).
    """

    def __init__(self, journal: MetricsJournal,
                 bp_ratio_threshold: float = 0.5,
                 bp_consecutive: int = 5,
                 lag_consecutive: int = 8,
                 checkpoint_p95_budget_ms: Optional[float] = None,
                 coordinator_supplier: Optional[Callable[[], Any]] = None,
                 bottleneck_supplier: Optional[Callable[[], Any]] = None,
                 bottleneck_consecutive: int = 5,
                 transfer_tax_threshold: float = 4.0,
                 transfer_tax_consecutive: int = 5,
                 key_skew_threshold: float = 3.0,
                 key_skew_consecutive: int = 3,
                 max_alerts: int = 256,
                 wall_clock: Callable[[], float] = None):
        self.journal = journal
        self.bp_ratio_threshold = bp_ratio_threshold
        self.bp_consecutive = max(2, bp_consecutive)
        self.lag_consecutive = max(3, lag_consecutive)
        self.checkpoint_p95_budget_ms = checkpoint_p95_budget_ms
        self.coordinator_supplier = coordinator_supplier
        self.bottleneck_supplier = bottleneck_supplier
        self.bottleneck_consecutive = max(2, bottleneck_consecutive)
        self.transfer_tax_threshold = transfer_tax_threshold
        self.transfer_tax_consecutive = max(2, transfer_tax_consecutive)
        self.key_skew_threshold = key_skew_threshold
        self.key_skew_consecutive = max(2, key_skew_consecutive)
        self.max_alerts = max_alerts
        self._wall = wall_clock or (lambda: _time.time() * 1000.0)
        self._lock = threading.Lock()
        self.alerts: List[dict] = []
        self.alerts_total = 0
        #: rule-instance key -> currently-firing episode flag
        self._active: Dict[Tuple[str, str], bool] = {}
        #: bottleneck streak: (vertex_id, consecutive evaluations)
        self._bottleneck_streak: Tuple[Optional[Any], int] = (None, 0)
        #: the last stable localization (served on demand)
        self.last_bottleneck: Optional[dict] = None

    # ---- emission ----------------------------------------------------
    def _fire(self, rule: str, metric: str, message: str,
              value) -> None:
        with self._lock:
            self.alerts_total += 1
            self.alerts.append({
                "rule": rule,
                "metric": metric,
                "message": message,
                "value": value,
                "t_wall_ms": self._wall(),
                "seq": self.alerts_total,
            })
            if len(self.alerts) > self.max_alerts:
                del self.alerts[:len(self.alerts) - self.max_alerts]

    def _episode(self, rule: str, metric: str, firing: bool,
                 message: str, value) -> None:
        key = (rule, metric)
        was = self._active.get(key, False)
        if firing and not was:
            self._fire(rule, metric, message, value)
        self._active[key] = firing

    def snapshot_alerts(self) -> List[dict]:
        with self._lock:
            return list(self.alerts)

    @property
    def active_rules(self) -> List[str]:
        return sorted({r for (r, _m), on in self._active.items() if on})

    # ---- evaluation --------------------------------------------------
    def evaluate(self) -> None:
        """Run every rule once; call after each journal sample."""
        self._eval_backpressure()
        self._eval_watermark_lag()
        self._eval_checkpoint_budget()
        self._eval_bottleneck()
        self._eval_transfer_tax()
        self._eval_key_skew()

    def _tail(self, key: str, n: int) -> List[float]:
        samples = self.journal.series(key)
        return [s[2] for s in samples[-n:]]

    def _eval_backpressure(self) -> None:
        k = self.bp_consecutive
        for key in self.journal.keys("*.backpressure.ratio"):
            tail = self._tail(key, k)
            firing = (len(tail) == k
                      and all(v > self.bp_ratio_threshold for v in tail))
            self._episode(
                "backpressure-sustained", key, firing,
                f"backpressure ratio > {self.bp_ratio_threshold} for "
                f"{k} consecutive samples", tail[-1] if tail else None)

    def _eval_watermark_lag(self) -> None:
        k = self.lag_consecutive
        for key in self.journal.keys("*.watermarkLag"):
            tail = self._tail(key, k)
            firing = (len(tail) == k
                      and all(b > a for a, b in zip(tail, tail[1:])))
            self._episode(
                "watermark-lag-growing", key, firing,
                f"watermark lag grew monotonically over {k} samples",
                tail[-1] if tail else None)

    def _eval_checkpoint_budget(self) -> None:
        budget = self.checkpoint_p95_budget_ms
        if budget is None or self.coordinator_supplier is None:
            return
        coordinator = self.coordinator_supplier()
        if coordinator is None:
            return
        durations = [st.duration_ms for st in
                     getattr(coordinator, "stats", {}).values()
                     if getattr(st, "duration_ms", None) is not None]
        if not durations:
            return
        p95 = rollup(durations)["p95"]
        self._episode(
            "checkpoint-duration-over-budget", "checkpointing.duration",
            p95 > budget,
            f"completed-checkpoint duration p95 {p95:.1f} ms exceeds "
            f"budget {budget:.1f} ms", p95)

    def _eval_transfer_tax(self) -> None:
        thr = self.transfer_tax_threshold
        if thr is None:
            return
        k = self.transfer_tax_consecutive
        # both are cumulative counters: the rule runs on per-interval
        # deltas, so k firing intervals need k+1 samples of each
        reads = self._tail("device.fireReads", k + 1)
        fired = self._tail("device.windowsFired", k + 1)
        firing = False
        value = None
        if len(reads) == k + 1 and len(fired) == k + 1:
            d_reads = [b - a for a, b in zip(reads, reads[1:])]
            d_fired = [b - a for a, b in zip(fired, fired[1:])]
            ratios = [dr / df for dr, df in zip(d_reads, d_fired)
                      if df > 0]
            firing = len(ratios) == k and all(r > thr for r in ratios)
            value = ratios[-1] if ratios else None
        self._episode(
            "transfer-tax", "device.fireReads", firing,
            f"sustained device readback tax: > {thr} D2H fire reads "
            f"per fired window for {k} consecutive sample intervals "
            "(see docs/state.md, per-key fire path)", value)

    def _eval_key_skew(self) -> None:
        thr = self.key_skew_threshold
        if thr is None:
            return
        k = self.key_skew_consecutive
        tail = self._tail("state.keyGroupSkew", k)
        firing = (len(tail) == k and all(v > thr for v in tail))
        hot_kg = self.journal.latest("state.hotKeyGroup")
        hot_kg = int(hot_kg) if hot_kg is not None and hot_kg >= 0 else None
        self._episode(
            "key-skew-sustained", "state.keyGroupSkew", firing,
            f"keyed-state skew > {thr}x the mean occupied key-group "
            f"load for {k} consecutive samples (hot key group "
            f"{hot_kg}; see /jobs/<name>/state for the hot-key list)",
            tail[-1] if tail else None)

    def _eval_bottleneck(self) -> None:
        if self.bottleneck_supplier is None:
            return
        try:
            located = self.bottleneck_supplier()
        except Exception:  # noqa: BLE001 — localization must not kill
            return         # the evaluation pass
        vid = located.get("vertex_id") if located else None
        prev_vid, streak = self._bottleneck_streak
        streak = streak + 1 if (vid is not None and vid == prev_vid) \
            else (1 if vid is not None else 0)
        self._bottleneck_streak = (vid, streak)
        firing = streak >= self.bottleneck_consecutive
        if firing:
            self.last_bottleneck = located
        name = (located or {}).get("name") or vid
        self._episode(
            "bottleneck-stable", "bottleneck.vertex", firing,
            f"bottleneck stable at vertex {name} (id {vid}) for "
            f"{streak} consecutive evaluations "
            f"(busy {((located or {}).get('busyMsPerSecond') or 0):.0f} "
            f"ms/s, backpressured upstreams "
            f"{[u['vertex_id'] for u in (located or {}).get('backpressured_upstreams', [])]})",
            vid)


def register_health_gauges(metrics, job_name: str,
                           evaluator: HealthEvaluator) -> None:
    """Publish the `health.*` gauge surface for a job.  Re-registers
    per restart attempt like the checkpoint gauges — fresh suppliers
    close over the live evaluator."""
    g = metrics.job_group(job_name).add_group("health")
    g.gauge("alertsTotal", lambda: evaluator.alerts_total,
            description="total alerts emitted by the health evaluator")
    g.gauge("rulesFiring", lambda: len(evaluator.active_rules),
            description="health rules currently in a firing episode")
    g.gauge("lastAlertRule",
            lambda: (evaluator.alerts[-1]["rule"]
                     if evaluator.alerts else None),
            description="rule name of the most recent alert")
