"""Sampling profiler & flame-graph plane.

The reference exposes operator flame graphs from the web UI by
periodically collecting task-thread stack traces and merging them
per job vertex (FLIP-165, `JobVertexThreadInfoTracker` /
`VertexFlameGraphFactory`).  The rebuild keeps the same shape in one
process-wide singleton:

- a daemon thread walks ``sys._current_frames()`` at a configurable
  Hz and folds every attributed stack into a bounded collapsed-stack
  trie (Gregg's flame-graph folding — ``a;b;c count``);
- attribution rides the same per-thread labelling PR 8 introduced for
  trace lanes: executor loops register the subtask they are about to
  step (guarded on ``PROFILER.enabled`` so the disabled path stays a
  single attribute check, like ``DeviceTelemetry``), threaded sources
  register once at thread spawn;
- every sample is classified ON_CPU / OFF_CPU / BACKPRESSURED from
  the subtask's live ``TimeAccounting`` state (the busy / idle /
  backpressured attribution of PR 8) plus the sticky
  ``router_blocked`` predicate at sample time — the flame graph splits
  the same way Flink's does (full / on-CPU / off-CPU modes);
- tries are bounded: once ``max_nodes`` trie nodes exist, samples
  whose stacks would need new nodes are truncated at the deepest
  existing prefix and counted in ``profiler.dropped`` — memory never
  grows without bound no matter how long the profiler runs.

One payload shape (:meth:`SamplingProfiler.export`) feeds every
surface: the live REST ``/jobs/<name>/flamegraph`` route, the
HistoryServer twin frozen into the archive bundle, cluster increment
shipping (TaskExecutor → JobMaster via ``report_profile``), the
``flink_tpu top`` HOT column, ``flink_tpu profile --flame`` collapsed
text, and ``bench.py --flame``.  The d3-flame-graph JSON tree is
always built by :func:`flamegraph_payload` from such an export, so
live and archived responses cannot diverge.

This module is also the tree's single windowed-sampling core
(:func:`sample_windowed`): ``runtime.backpressure`` delegates its
N-samples-over-a-window loop here, so there is exactly one sampler
idiom (and one ``sys._current_frames`` walker) in the codebase.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "ON_CPU", "OFF_CPU", "BACKPRESSURED", "CLASS_NAMES", "MODES",
    "SamplingProfiler", "get_profiler", "PROFILER",
    "classify_subtask", "fold_stack", "sample_windowed",
    "empty_export", "merge_export", "flamegraph_payload",
    "collapsed_lines", "hottest_frame", "register_profiler_gauges",
]

#: sample classes — index into every counts triple in this module.
#: ``TimeAccounting.last_class`` uses the same encoding.
ON_CPU = 0
OFF_CPU = 1
BACKPRESSURED = 2
CLASS_NAMES = ("on_cpu", "off_cpu", "backpressured")

#: flame-graph modes: ``full`` sums all classes, ``on_cpu`` keeps
#: only ON_CPU samples, ``off_cpu`` keeps OFF_CPU + BACKPRESSURED
#: (a backpressured thread is off-CPU waiting for credit)
MODES = ("full", "on_cpu", "off_cpu")

#: frames kept per sampled stack, leaf-most first — deeper recursion
#: is truncated at the root end (the leaf is what makes a frame hot)
MAX_STACK_DEPTH = 128


def sample_windowed(probe: Callable[[int], None], num_samples: int = 20,
                    delay_s: float = 0.005) -> int:
    """The one N-samples-over-a-window loop in the tree: invoke
    ``probe(i)`` ``num_samples`` times, sleeping ``delay_s`` between
    consecutive samples (not after the last — the window is
    ``(num_samples - 1) * delay_s`` long).  ``sample_backpressure``
    drives its capacity-predicate reads through this; the profiler's
    own daemon loop is the continuous analogue."""
    for i in range(num_samples):
        probe(i)
        if delay_s and i < num_samples - 1:
            time.sleep(delay_s)
    return num_samples


def fold_stack(frame, limit: int = MAX_STACK_DEPTH) -> List[str]:
    """Collapse a frame chain into root-first ``file.py:function``
    labels (the collapsed-stack frame naming).  Works on any object
    exposing ``f_code``/``f_back`` so tests can fold fake frames."""
    leafward: List[str] = []
    f = frame
    while f is not None and len(leafward) < limit:
        code = f.f_code
        leafward.append("%s:%s" % (
            os.path.basename(code.co_filename), code.co_name))
        f = f.f_back
    leafward.reverse()
    return leafward


def classify_subtask(st) -> int:
    """Classify a sample for ``st`` at sample time.  Live
    ``router_blocked`` takes precedence (the subtask is waiting on
    downstream credit RIGHT NOW), then the last class its
    ``TimeAccounting`` assigned (busy ⇒ on-CPU, idle ⇒ off-CPU,
    backpressured ⇒ backpressured).  Unknown state reads as on-CPU —
    a thread we caught running Python is at least plausibly busy."""
    from flink_tpu.runtime.backpressure import router_blocked
    router = getattr(st, "router", None)
    if router is not None:
        try:
            if router_blocked(router):
                return BACKPRESSURED
        except Exception:
            pass
    acct = getattr(st, "time_accounting", None)
    last = getattr(acct, "last_class", None)
    if last == OFF_CPU:
        return OFF_CPU
    if last == BACKPRESSURED:
        return BACKPRESSURED
    return ON_CPU


class _Node:
    """One collapsed-stack trie node: cumulative per-class counts of
    samples that TERMINATED here (the flame-graph tree builder sums
    descendants at render time) plus the not-yet-shipped delta the
    cluster increment path drains."""

    __slots__ = ("children", "counts", "delta")

    def __init__(self):
        self.children: Dict[str, "_Node"] = {}
        self.counts = [0, 0, 0]
        self.delta = [0, 0, 0]


class SamplingProfiler:
    """Process-wide sampling profiler.  Off by default; the ONLY cost
    anywhere on the hot path while disabled is reading ``.enabled``
    (kept the first attribute set, same discipline as
    ``DeviceTelemetry``)."""

    DEFAULT_HZ = 50
    #: global trie-node budget across all jobs/vertices — beyond it,
    #: new stack shapes truncate at their deepest existing prefix and
    #: ``dropped`` counts them
    MAX_NODES = 50_000

    def __init__(self):
        self.enabled = False  # MUST stay the first attribute set
        self.hz = float(self.DEFAULT_HZ)
        self.max_nodes = self.MAX_NODES
        self.dropped = 0
        self.samples = [0, 0, 0]
        self._samples_delta = [0, 0, 0]
        self._lock = threading.Lock()
        #: thread ident -> subtask-like scope (survives reset(): the
        #: registrations belong to live threads, not to the data)
        self._scopes: Dict[int, Any] = {}
        #: job -> vertex label -> trie root
        self._tries: Dict[str, Dict[str, _Node]] = {}
        #: (job, vertex label, subtask index) -> per-class counts
        self._subtask_counts: Dict[Tuple[str, str, int], List[int]] = {}
        self._subtask_delta: Dict[Tuple[str, str, int], List[int]] = {}
        self._dropped_delta = 0
        self._node_count = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lifecycle ----------------------------------------------------

    def enable(self, hz: Optional[float] = None) -> None:
        """Start the daemon sampler (idempotent; ``hz`` updates the
        rate either way)."""
        if hz is not None:
            self.hz = float(hz)
        if self.enabled and self._thread is not None:
            return
        self._stop.clear()
        self.enabled = True
        t = threading.Thread(target=self._run, daemon=True,
                             name="stack-profiler")
        self._thread = t
        t.start()

    def disable(self) -> None:
        """Stop sampling; collected tries stay readable until
        :meth:`reset`."""
        self.enabled = False
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)

    def reset(self) -> None:
        """Drop all collected samples (thread scope registrations are
        kept — they describe live threads, not data)."""
        with self._lock:
            self._tries.clear()
            self._subtask_counts.clear()
            self._subtask_delta.clear()
            self.samples = [0, 0, 0]
            self._samples_delta = [0, 0, 0]
            self.dropped = 0
            self._dropped_delta = 0
            self._node_count = 0
            self.hz = float(self.DEFAULT_HZ)
            self.max_nodes = self.MAX_NODES

    # -- attribution --------------------------------------------------

    def set_scope(self, subtask) -> None:
        """Attribute the calling thread's samples to ``subtask`` until
        the next call.  Executor loops call this (guarded on
        ``.enabled``) right before stepping each subtask; threaded
        sources call it once at thread spawn."""
        self._scopes[threading.get_ident()] = subtask

    def clear_scope(self) -> None:
        self._scopes.pop(threading.get_ident(), None)

    @staticmethod
    def _scope_key(st) -> Tuple[str, str, int]:
        key = getattr(st, "profiler_scope", None)
        if key is not None:
            return key
        try:
            vid, idx = st.task_key
            vertex = "%s_%s" % (vid, st.vertex.name)
        except Exception:
            vertex, idx = "unknown", 0
        group = getattr(st, "metrics_group", None)
        scope = getattr(group, "scope", None) or ()
        job = scope[0] if scope else "unknown"
        key = (str(job), vertex, int(idx))
        try:
            st.profiler_scope = key
        except Exception:
            pass
        return key

    # -- sampling -----------------------------------------------------

    def _run(self) -> None:
        while self.enabled and not self._stop.is_set():
            t0 = time.perf_counter()
            try:
                self.sample_once()
            except Exception:
                pass
            period = 1.0 / max(1e-3, float(self.hz))
            self._stop.wait(max(0.0, period -
                                (time.perf_counter() - t0)))

    def sample_once(self) -> int:
        """One sampling pass: snapshot every thread's frame, fold the
        frames of threads with a registered scope, classify, ingest.
        Returns the number of samples recorded.  Scopes whose thread
        has exited are pruned here (``sys._current_frames`` is the
        authority on live threads)."""
        frames = sys._current_frames()
        try:
            own = threading.get_ident()
            recorded = 0
            for tid, st in list(self._scopes.items()):
                frame = frames.get(tid)
                if frame is None:
                    self._scopes.pop(tid, None)
                    continue
                if tid == own:
                    continue
                job, vertex, idx = self._scope_key(st)
                cls = classify_subtask(st)
                stack = fold_stack(frame)
                self.ingest(job, vertex, idx, stack, cls)
                recorded += 1
            return recorded
        finally:
            del frames

    def ingest(self, job: str, vertex: str, subtask_index: int,
               stack: List[str], cls: int) -> None:
        """Fold one (possibly fake — tests) stack into the trie."""
        with self._lock:
            self.samples[cls] += 1
            self._samples_delta[cls] += 1
            key = (job, vertex, subtask_index)
            counts = self._subtask_counts.get(key)
            if counts is None:
                counts = self._subtask_counts[key] = [0, 0, 0]
            counts[cls] += 1
            delta = self._subtask_delta.get(key)
            if delta is None:
                delta = self._subtask_delta[key] = [0, 0, 0]
            delta[cls] += 1
            vertices = self._tries.get(job)
            if vertices is None:
                vertices = self._tries[job] = {}
            node = vertices.get(vertex)
            if node is None:
                if self._node_count >= self.max_nodes:
                    self.dropped += 1
                    self._dropped_delta += 1
                    return
                node = vertices[vertex] = _Node()
                self._node_count += 1
            for name in stack:
                child = node.children.get(name)
                if child is None:
                    if self._node_count >= self.max_nodes:
                        # budget exhausted: truncate at the deepest
                        # existing prefix, remember we lied about depth
                        self.dropped += 1
                        self._dropped_delta += 1
                        break
                    child = node.children[name] = _Node()
                    self._node_count += 1
                node = child
            node.counts[cls] += 1
            node.delta[cls] += 1

    # -- export / merge ----------------------------------------------

    @staticmethod
    def _node_payload(node: _Node, delta: bool) -> Optional[dict]:
        if delta:
            counts = list(node.delta)
            node.delta[0] = node.delta[1] = node.delta[2] = 0
        else:
            counts = list(node.counts)
        children = {}
        for name, child in node.children.items():
            cp = SamplingProfiler._node_payload(child, delta)
            if cp is not None:
                children[name] = cp
        if delta and not any(counts) and not children:
            return None
        return {"counts": counts, "children": children}

    def export(self, job: Optional[str] = None,
               delta: bool = False) -> dict:
        """Serialize collected tries (one job, or all).  With
        ``delta=True`` only counts accrued since the previous delta
        export are returned AND those accumulators reset — the cluster
        increment-shipping primitive (each TaskExecutor ships its own
        deltas, the JobMaster merges)."""
        with self._lock:
            jobs: Dict[str, dict] = {}
            for jname, vertices in self._tries.items():
                if job is not None and jname != job:
                    continue
                vmap: Dict[str, dict] = {}
                for vlabel, root in vertices.items():
                    nd = self._node_payload(root, delta)
                    if nd is None:
                        continue
                    source = (self._subtask_delta if delta
                              else self._subtask_counts)
                    subtasks = {}
                    for (j, v, i), c in source.items():
                        if j == jname and v == vlabel and any(c):
                            subtasks[str(i)] = list(c)
                            if delta:
                                source[(j, v, i)] = [0, 0, 0]
                    vmap[vlabel] = {"root": nd, "subtasks": subtasks}
                if vmap:
                    jobs[jname] = vmap
            if delta:
                dropped = self._dropped_delta
                self._dropped_delta = 0
                counts = list(self._samples_delta)
                self._samples_delta = [0, 0, 0]
            else:
                dropped = self.dropped
                counts = list(self.samples)
            return {"version": 1, "enabled": self.enabled,
                    "hz": self.hz, "nodes": self._node_count,
                    "dropped": dropped,
                    "samples": {
                        "total": sum(counts),
                        "on_cpu": counts[ON_CPU],
                        "off_cpu": counts[OFF_CPU],
                        "backpressured": counts[BACKPRESSURED]},
                    "jobs": jobs}


def empty_export() -> dict:
    """A zero export — the JobMaster's merge seed."""
    return {"version": 1, "enabled": True,
            "hz": float(SamplingProfiler.DEFAULT_HZ), "nodes": 0,
            "dropped": 0,
            "samples": {"total": 0, "on_cpu": 0, "off_cpu": 0,
                        "backpressured": 0},
            "jobs": {}}


def _copy_node(nd: dict) -> dict:
    return {"counts": list(nd["counts"]),
            "children": {name: _copy_node(c)
                         for name, c in nd["children"].items()}}


def _merge_node(dst: dict, src: dict) -> None:
    for i in range(3):
        dst["counts"][i] += src["counts"][i]
    for name, child in src["children"].items():
        mine = dst["children"].get(name)
        if mine is None:
            dst["children"][name] = _copy_node(child)
        else:
            _merge_node(mine, child)


def merge_export(dst: dict, inc: dict) -> dict:
    """Merge one shipped increment (or full export) into an
    accumulating export in place (JobMaster side of
    ``report_profile``)."""
    dst["hz"] = inc.get("hz", dst.get("hz"))
    dst["dropped"] = dst.get("dropped", 0) + inc.get("dropped", 0)
    for jname, vertices in (inc.get("jobs") or {}).items():
        djob = dst["jobs"].setdefault(jname, {})
        for vlabel, ventry in vertices.items():
            mine = djob.get(vlabel)
            if mine is None:
                mine = djob[vlabel] = {"root": _copy_node(ventry["root"]),
                                      "subtasks": {}}
            else:
                _merge_node(mine["root"], ventry["root"])
            for idx, counts in (ventry.get("subtasks") or {}).items():
                have = mine["subtasks"].setdefault(idx, [0, 0, 0])
                for i in range(3):
                    have[i] += counts[i]
    samples = dst.get("samples") or {}
    inc_s = inc.get("samples") or {}
    for k in ("total", "on_cpu", "off_cpu", "backpressured"):
        samples[k] = samples.get(k, 0) + inc_s.get(k, 0)
    dst["samples"] = samples
    return dst


# ---------------------------------------------------------------------
# flame-graph rendering (shared by live REST, HistoryServer, CLI)
# ---------------------------------------------------------------------

def _mode_weight(counts: List[int], mode: str) -> int:
    if mode == "on_cpu":
        return counts[ON_CPU]
    if mode == "off_cpu":
        return counts[OFF_CPU] + counts[BACKPRESSURED]
    return counts[0] + counts[1] + counts[2]


def _tree_node(name: str, nd: dict, mode: str) -> Optional[dict]:
    self_w = _mode_weight(nd["counts"], mode)
    children = []
    value = self_w
    for cname in sorted(nd["children"]):
        child = _tree_node(cname, nd["children"][cname], mode)
        if child is not None:
            children.append(child)
            value += child["value"]
    if value == 0:
        return None
    return {"name": name, "value": value, "self": self_w,
            "children": children}


def _vertex_matches(vlabel: str, vertex: str) -> bool:
    if vlabel == vertex:
        return True
    vid, _, name = vlabel.partition("_")
    return vertex == vid or vertex == name


def _cumulative(nd: dict, into: List[int]) -> None:
    for i in range(3):
        into[i] += nd["counts"][i]
    for child in nd["children"].values():
        _cumulative(child, into)


def flamegraph_payload(export: dict, job: str,
                       vertex: Optional[str] = None,
                       mode: str = "full") -> dict:
    """Build the d3-flame-graph JSON payload the ``/flamegraph``
    routes serve from an export — ONE builder, so the live WebMonitor
    and the HistoryServer twin cannot drift apart.  ``vertex`` filters
    to one vertex (matched by full label, vertex id, or name);
    ``samples`` reports the per-class split of whatever matched
    regardless of ``mode``, so callers can see the on/off-CPU split
    even while rendering a filtered tree."""
    vertices = (export.get("jobs") or {}).get(job) or {}
    children = []
    split = [0, 0, 0]
    for vlabel in sorted(vertices):
        if vertex is not None and not _vertex_matches(vlabel, vertex):
            continue
        entry = vertices[vlabel]
        _cumulative(entry["root"], split)
        tree = _tree_node(vlabel, entry["root"], mode)
        if tree is not None:
            children.append(tree)
    value = sum(c["value"] for c in children)
    return {"job": job, "vertex": vertex, "mode": mode,
            "enabled": bool(export.get("enabled")),
            "hz": export.get("hz"),
            "dropped": export.get("dropped", 0),
            "samples": {"total": split[0] + split[1] + split[2],
                        "on_cpu": split[ON_CPU],
                        "off_cpu": split[OFF_CPU],
                        "backpressured": split[BACKPRESSURED]},
            "tree": {"name": job, "value": value, "self": 0,
                     "children": children}}


def collapsed_lines(export: dict, job: Optional[str] = None,
                    mode: str = "full") -> List[str]:
    """Render an export as collapsed-stack text (``flamegraph.pl`` /
    speedscope input): one ``vertex;frame;...;frame count`` line per
    trie node with terminal samples."""
    lines: List[str] = []

    def walk(prefix: str, nd: dict) -> None:
        w = _mode_weight(nd["counts"], mode)
        if w:
            lines.append("%s %d" % (prefix, w))
        for name in sorted(nd["children"]):
            walk(prefix + ";" + name, nd["children"][name])

    for jname in sorted(export.get("jobs") or {}):
        if job is not None and jname != job:
            continue
        for vlabel in sorted(export["jobs"][jname]):
            walk(vlabel, export["jobs"][jname][vlabel]["root"])
    return lines


def hottest_frame(tree: dict) -> Optional[Tuple[str, int]]:
    """The single hottest frame (max self-samples) in a flame-graph
    tree — the ``flink_tpu top`` HOT column."""
    best: Optional[Tuple[str, int]] = None

    def walk(node: dict) -> None:
        nonlocal best
        self_w = int(node.get("self") or 0)
        if self_w and (best is None or self_w > best[1]):
            best = (node["name"], self_w)
        for child in node.get("children") or ():
            walk(child)

    walk(tree)
    return best


# ---------------------------------------------------------------------
# process-wide singleton + gauges
# ---------------------------------------------------------------------

PROFILER = SamplingProfiler()


def get_profiler() -> SamplingProfiler:
    return PROFILER


def register_profiler_gauges(metrics) -> None:
    """Register process-wide ``profiler.*`` gauges on a registry —
    journaled by the MetricsJournal with everything else it samples.
    Safe to call repeatedly (gauges re-register)."""
    p = get_profiler()
    g = metrics.root.add_group("profiler")
    g.gauge("enabled", lambda: 1 if p.enabled else 0)
    g.gauge("hz", lambda: float(p.hz))
    g.gauge("samples", lambda: float(sum(p.samples)))
    g.gauge("on_cpu", lambda: float(p.samples[ON_CPU]))
    g.gauge("off_cpu", lambda: float(p.samples[OFF_CPU]))
    g.gauge("backpressured", lambda: float(p.samples[BACKPRESSURED]))
    g.gauge("dropped", lambda: float(p.dropped))
    g.gauge("nodes", lambda: float(p._node_count))
    g.gauge("threads", lambda: float(len(p._scopes)))
