"""Chaos harness: seeded fault schedules against a reference job.

Runs the same keyed windowed-aggregation job twice — once fault-free,
once under a deterministic `FaultInjector` schedule — and hands back
both output multisets plus the fault-tolerance counters, so callers
(tests/test_chaos.py, `bench.py --chaos-smoke`) can assert
exactly-once delivery: the chaos run's output must EQUAL the
fault-free run's, record for record, despite storage-write failures,
lost checkpoint acks, and induced task crashes (ref: Basiri et al.,
"Chaos Engineering", IEEE Software 2016; the reference's
StreamFaultToleranceTestBase family).

The job is event-time windowed, so injected delays never change the
expected output — only the schedule's failures do, and recovery must
erase them.  The source is checkpoint-GATED (the
StreamFaultToleranceTestBase idiom, tests/test_minicluster.py): it
trickles once `FREE` records are out until a checkpoint completes, so
a fault targeting a later record always has a restore point — without
one, a restart replays from scratch and re-fires windows the shared
sink already saw, which is at-least-once, not a runtime bug.
"""

from __future__ import annotations

import collections
import tempfile
import time as _time
from typing import Callable, Optional

from flink_tpu.core.functions import AggregateFunction
from flink_tpu.runtime import faults
from flink_tpu.runtime.faults import FaultInjector
from flink_tpu.streaming.sources import FromCollectionSource


class KeyedSumAgg(AggregateFunction):
    """Sum per key, carrying the key into the result so the output
    multiset is checkable per (key, sum) pair."""

    def create_accumulator(self):
        return (None, 0)

    def add(self, value, acc):
        return (value[0], acc[1] + value[1])

    def get_result(self, acc):
        return acc

    def merge(self, a, b):
        return (a[0] if a[0] is not None else b[0], a[1] + b[1])


class CheckpointGatedSource(FromCollectionSource):
    """Emits `FREE` records at full speed, then trickles one record
    per step until a checkpoint COMPLETES, then floods the rest.  Any
    injected fault aimed past the gate (e.g. `after=600` with
    FREE=400) is therefore guaranteed to land with a completed
    checkpoint to restore from, whatever the host load — on a starved
    box the checkpoint round trip can outlast many records, and a
    crash with no restore point replays from offset 0, duplicating
    already-fired windows into the non-transactional sink.  The flag
    rides on a class attribute because the source factory deep-copies
    the function per attempt."""

    FREE = 400          # records emitted before the gate closes
    completed = False   # class attr: reset per run by the harness

    def notify_checkpoint_complete(self, checkpoint_id):
        type(self).completed = True

    def emit_step(self, ctx, max_records):
        if not type(self).completed and self.offset >= self.FREE:
            _time.sleep(0.001)
            return super().emit_step(ctx, 1)
        return super().emit_step(ctx, max_records)


def windowed_records(n_keys: int = 6, per_key: int = 250):
    """(key, 1) records spread over event-time windows of 1000ms."""
    records = []
    for i in range(per_key):
        for k in range(n_keys):
            records.append(((f"k{k}", 1), i * 10))
    return records


def standard_schedule(inj: FaultInjector) -> FaultInjector:
    """The canonical chaos mix — one schedule of every supported kind
    across three distinct fault classes: storage-write failures
    (healed by backoff retry), lost checkpoint acks (healed by the
    checkpoint timeout re-trigger), an induced task crash (healed by
    restart-from-checkpoint), and a netchannel connect failure (healed
    by connect retry; inert on executors without a data plane)."""
    inj.fail_n_times("storage.persist", 2)
    # the first checkpoint's acks vanish; the pending holds the
    # max_concurrent slot until checkpoint_timeout_ms aborts it
    inj.fail_n_times("checkpoint.ack", 2)
    # crash past the source's FREE=400 gate, so the timeout re-trigger
    # has healed and a completed checkpoint exists to restore from
    inj.fail_n_times("task.process", 1, after=600)
    inj.fail_n_times("netchannel.connect", 1)
    # stretch per-record processing so the job outlives the checkpoint
    # timeout deterministically (event time: output is unaffected)
    inj.delay("task.process", 0.2)
    return inj


def run_windowed_job(executor: str = "local", *,
                     n_keys: int = 6, per_key: int = 250,
                     checkpoint_interval_ms: int = 10,
                     checkpoint_timeout_ms: Optional[int] = 40,
                     tolerable_failures: Optional[int] = 16,
                     restart_attempts: int = 5,
                     checkpoint_dir: Optional[str] = None,
                     job_name: str = "chaos-window"):
    """One run of the reference job; returns (sink values, result)."""
    from flink_tpu.streaming.datastream import StreamExecutionEnvironment
    from flink_tpu.streaming.sources import CollectSink
    from flink_tpu.streaming.windowing import Time

    if checkpoint_dir is None:
        checkpoint_dir = tempfile.mkdtemp(prefix="flink_tpu_chaos_")
    sink = CollectSink()
    env = StreamExecutionEnvironment()
    if executor == "minicluster":
        env.use_mini_cluster(2)
        env.set_parallelism(2)
    elif executor != "local":
        raise ValueError(f"unknown chaos executor '{executor}'")
    env.enable_checkpointing(checkpoint_interval_ms,
                             timeout_ms=checkpoint_timeout_ms,
                             tolerable_failures=tolerable_failures)
    env.set_checkpoint_storage("filesystem", directory=checkpoint_dir,
                               retain=2)
    env.set_restart_strategy("fixed_delay",
                             restart_attempts=restart_attempts,
                             delay_ms=0)
    CheckpointGatedSource.completed = False
    (env.add_source(CheckpointGatedSource(windowed_records(n_keys, per_key),
                                          timestamped=True),
                    name="from_collection")
        .key_by(lambda v: v[0])
        .time_window(Time.milliseconds_of(1000))
        .aggregate(KeyedSumAgg())
        .add_sink(sink))
    result = env.execute(job_name)
    return list(sink.values), result


def run_chaos_case(executor: str = "local", seed: int = 0,
                   schedule: Callable[[FaultInjector], FaultInjector]
                   = standard_schedule,
                   **job_kw) -> dict:
    """Fault-free run, then the same job under the seeded schedule.

    Returns a dict with `baseline`/`chaos` output multisets
    (collections.Counter), the chaos run's `restarts`, the
    `faulttolerance.*` counter snapshot, the per-point fire counts,
    and the injector itself for schedule-specific asserts.  The
    injector is always deactivated on exit, even when the chaos run
    fails.
    """
    faults.deactivate()
    faults.reset_counters()
    baseline_values, baseline_result = run_windowed_job(executor, **job_kw)

    inj = schedule(FaultInjector(seed=seed))
    faults.reset_counters()
    faults.install(inj)
    try:
        chaos_values, chaos_result = run_windowed_job(executor, **job_kw)
    finally:
        faults.deactivate()
    return {
        "baseline": collections.Counter(baseline_values),
        "chaos": collections.Counter(chaos_values),
        "baseline_restarts": baseline_result.restarts,
        "restarts": chaos_result.restarts,
        "checkpoints_completed": chaos_result.checkpoints_completed,
        "counters": faults.counter_snapshot(),
        "fire_counts": dict(inj.fire_counts),
        "injector": inj,
    }
