"""High availability: leader election + submitted-job-graph store.

Rebuilds the reference's HA services
(flink-runtime/.../highavailability/HighAvailabilityServices.java, the
ZooKeeper implementations — ZooKeeperLeaderElectionService.java,
ZooKeeperSubmittedJobGraphStore — and the Dispatcher's job recovery
path, Dispatcher.java:502 recoverJobs → createJobManagerRunner) on a
SHARED FILESYSTEM instead of ZooKeeper (this environment has no ZK;
a shared directory is the TPU-pod-appropriate coordination medium —
the same place checkpoints already live):

- **Leader election**: a lease file (`leader.lock`) acquired with
  O_EXCL; the leader refreshes its mtime every `lease_refresh_s`, and
  a standby steals the lease once the mtime is older than
  `lease_timeout_s` (the session-timeout analogue of the ZK ephemeral
  node).  The elected leader publishes its RPC address in
  `leader.addr` for clients and TaskManagers to resolve.
- **Job graph store**: submitted job graphs persist as files under
  `jobs/`; a newly elected dispatcher recovers and resubmits every
  stored job, which resumes from the latest completed checkpoint when
  the job uses filesystem checkpoint storage.
"""

from __future__ import annotations

import os
import pickle
import threading
import time as _time
import uuid
from typing import Callable, List, Optional


class FileLeaderElection:
    """Lease-file leader election (ref: LeaderElectionService +
    the ZK ephemeral-node semantics, approximated with mtime leases)."""

    def __init__(self, ha_dir: str, lease_timeout_s: float = 3.0,
                 lease_refresh_s: float = 0.5):
        self.ha_dir = ha_dir
        self.lease_timeout_s = lease_timeout_s
        self.lease_refresh_s = lease_refresh_s
        self.contender_id = uuid.uuid4().hex
        self._lock_path = os.path.join(ha_dir, "leader.lock")
        self._addr_path = os.path.join(ha_dir, "leader.addr")
        self.is_leader = False
        self._running = False
        self._on_leadership: Optional[Callable[[], None]] = None
        self._thread: Optional[threading.Thread] = None
        os.makedirs(ha_dir, exist_ok=True)

    # ---- campaign ---------------------------------------------------
    def start(self, address: str,
              on_leadership: Callable[[], None]) -> None:
        """Campaign in the background; `on_leadership` fires (once) on
        grant, after the address is published."""
        self._address = address
        self._on_leadership = on_leadership
        self._running = True
        self._thread = threading.Thread(target=self._campaign_loop,
                                        daemon=True, name="ha-campaign")
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self.is_leader:
            self.is_leader = False
            try:
                # release only OUR lease — never a successor's
                with open(self._lock_path) as f:
                    if f.read().strip() == self.contender_id:
                        os.remove(self._lock_path)
            except OSError:
                pass

    def _campaign_loop(self) -> None:
        while self._running:
            if self.is_leader:
                # refresh the lease — but only if it is still OURS (a
                # paused leader whose lease was stolen must demote, not
                # silently refresh the successor's lock)
                try:
                    with open(self._lock_path) as f:
                        owned = f.read().strip() == self.contender_id
                    if owned:
                        os.utime(self._lock_path)
                    else:
                        self.is_leader = False
                except OSError:
                    self.is_leader = False  # lease lost
                _time.sleep(self.lease_refresh_s)
                continue
            if self._try_acquire():
                self.is_leader = True
                with open(self._addr_path + ".part", "w") as f:
                    f.write(self._address)
                os.replace(self._addr_path + ".part", self._addr_path)
                if self._on_leadership is not None:
                    self._on_leadership()
            else:
                _time.sleep(self.lease_refresh_s)

    def _try_acquire(self) -> bool:
        try:
            fd = os.open(self._lock_path,
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, self.contender_id.encode())
            os.close(fd)
            return True
        except FileExistsError:
            # steal a stale lease (dead leader: mtime stopped moving).
            # The steal is an atomic RENAME: of several concurrent
            # stealers exactly one wins the rename; the losers' rename
            # raises and nobody can delete a successor's FRESH lock
            # (the remove-after-stat TOCTOU that causes split brain).
            try:
                age = _time.time() - os.path.getmtime(self._lock_path)
            except OSError:
                return False
            if age > self.lease_timeout_s:
                stale = (self._lock_path
                         + f".stale-{self.contender_id[:8]}")
                try:
                    os.rename(self._lock_path, stale)
                    os.remove(stale)
                except OSError:
                    pass  # another stealer won the rename
            return False

    # ---- discovery --------------------------------------------------
    @staticmethod
    def current_leader_address(ha_dir: str) -> Optional[str]:
        path = os.path.join(ha_dir, "leader.addr")
        try:
            with open(path) as f:
                return f.read().strip() or None
        except OSError:
            return None

    @staticmethod
    def wait_for_leader(ha_dir: str, timeout: float = 30.0) -> str:
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            addr = FileLeaderElection.current_leader_address(ha_dir)
            if addr:
                return addr
            _time.sleep(0.05)
        raise TimeoutError(f"no leader published in {ha_dir}")


class FsSubmittedJobGraphStore:
    """Durable submitted-job store (ref:
    ZooKeeperSubmittedJobGraphStore — put on submit, remove on
    terminal, recoverJobGraphs on leadership)."""

    def __init__(self, ha_dir: str):
        self.directory = os.path.join(ha_dir, "jobs")
        os.makedirs(self.directory, exist_ok=True)

    def put(self, job_id: str, graph_blob: bytes, job_config: dict) -> None:
        path = os.path.join(self.directory, job_id)
        with open(path + ".part", "wb") as f:
            pickle.dump({"job_id": job_id, "graph_blob": graph_blob,
                         "config": job_config}, f,
                        protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(path + ".part", path)

    def remove(self, job_id: str) -> None:
        try:
            os.remove(os.path.join(self.directory, job_id))
        except OSError:
            pass

    def recover_all(self) -> List[dict]:
        out = []
        for name in sorted(os.listdir(self.directory)):
            if name.endswith(".part"):
                continue
            try:
                with open(os.path.join(self.directory, name), "rb") as f:
                    out.append(pickle.load(f))
            except (OSError, pickle.PickleError):
                continue
        return out
