from flink_tpu.cli import main

raise SystemExit(main())
