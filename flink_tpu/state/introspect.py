"""Keyed-state introspection plane: per-key-group accounting, hot-key
skew detection, and the offline snapshot inspector.

One process-global singleton (`INTROSPECTION`, mirroring
`runtime.device_stats.TELEMETRY`): disabled by default, the hot-path
cost of the disabled state is ONE attribute check.  Three legs:

- **accounting** — authoritative per-(state, key-group) rows / bytes /
  namespace counts, pulled from the live backends' tables on demand
  (``accounting_breakdown()`` on both backends walks the SAME columnar
  blocks / slot tables a snapshot serializes, with the same key-group
  split and the same bytes definition, so live accounting and the
  offline inspector agree exactly).  A disposing backend freezes its
  breakdown here first, so a finished job's numbers survive into the
  HistoryServer archive.

- **skew** — per-state Count-Min sketch + top-k candidate ring fed from
  the batched ingest path's ONE splitmix64 hash pass (the host twin of
  ``ops/sketches.py::CountMinSketchAggregate`` — identical
  Kirsch–Mitzenmacher column derivation as ``ops/hashing.countmin_rows``,
  uint32 arithmetic and all), plus per-key-group ingest counts.  Derives
  ``state.keyGroupSkew`` (max / mean occupied key-group load) and the
  hot-key list the `key-skew-sustained` health rule names.

- **inspection** — `inspect_checkpoint` reads a v2 columnar checkpoint
  directory WITHOUT a running job (read-only: no orphan sweep, no
  chunk adoption) and reproduces the exact same per-state per-key-group
  rows/bytes, a component dtype breakdown, the top-N heaviest keys and
  a rescale preview (`flink_tpu state inspect`).
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core.keygroups import (
    assign_key_groups_np,
    compute_key_group_range_for_operator_index,
    murmur_hash,
    stable_hash64,
    stable_hashes_np,
)

#: skew verdict threshold (max/mean occupied key-group load); the
#: HealthEvaluator's `key_skew_threshold` defaults to the same value
SKEW_THRESHOLD = 3.0

#: Count-Min geometry — matches CountMinSketchAggregate's defaults
CM_DEPTH = 4
CM_WIDTH = 2048

#: hot-key candidate ring: prune back to CAND_KEEP once CAND_CAP hit
CAND_CAP = 64
CAND_KEEP = 32


def pickled_len(value) -> int:
    """THE bytes definition for boxed (per-row pickled) state values —
    shared by live accounting and the offline inspector so the two can
    never disagree."""
    return len(pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL))


class _SkewTracker:
    """Per-state ingest sketch: Count-Min over key hashes (host twin of
    the device CountMinSketchAggregate), per-key-group ingest counts,
    and a bounded hot-key candidate ring."""

    __slots__ = ("table", "kg_counts", "candidates", "total")

    def __init__(self):
        self.table = np.zeros((CM_DEPTH, CM_WIDTH), np.int64)
        #: key group -> rows ingested
        self.kg_counts: Dict[int, int] = {}
        #: candidate key -> Count-Min estimate at last sighting
        self.candidates: Dict[Any, int] = {}
        self.total = 0

    # -- Kirsch–Mitzenmacher columns, EXACTLY ops/hashing.countmin_rows:
    # idx = (lo + r*hi) % width in uint32 arithmetic -------------------
    def _columns(self, hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
        r = np.arange(CM_DEPTH, dtype=np.uint32)[:, None]
        with np.errstate(over="ignore"):
            return ((lo[None, :] + r * hi[None, :])
                    % np.uint32(CM_WIDTH)).astype(np.int64)

    def note(self, keys, hashes: np.ndarray, kgs: np.ndarray) -> None:
        n = len(keys)
        if n == 0:
            return
        self.total += n
        for kg, cnt in zip(*np.unique(kgs, return_counts=True)):
            kg = int(kg)
            self.kg_counts[kg] = self.kg_counts.get(kg, 0) + int(cnt)
        # dedupe to unique hashes: ONE sketch update per distinct key
        uh, first, counts = np.unique(hashes, return_index=True,
                                      return_counts=True)
        hi = (uh >> np.uint64(32)).astype(np.uint32)
        lo = (uh & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        cols = self._columns(hi, lo)
        rows = np.broadcast_to(
            np.arange(CM_DEPTH, dtype=np.int64)[:, None], cols.shape)
        np.add.at(self.table, (rows, cols),
                  np.broadcast_to(counts[None, :], cols.shape))
        est = self.table[rows, cols].min(axis=0)
        cand = self.candidates
        for i, e in zip(first, est):
            cand[keys[int(i)]] = int(e)
        if len(cand) > CAND_CAP:
            keep = sorted(cand.items(), key=lambda kv: -kv[1])[:CAND_KEEP]
            self.candidates = dict(keep)

    def note_one(self, key, h: int, kg: int) -> None:
        self.total += 1
        self.kg_counts[kg] = self.kg_counts.get(kg, 0) + 1
        hi = np.uint32(h >> 32)
        lo = np.uint32(h & 0xFFFFFFFF)
        est = None
        with np.errstate(over="ignore"):
            for r in range(CM_DEPTH):
                c = int((lo + np.uint32(r) * hi) % np.uint32(CM_WIDTH))
                self.table[r, c] += 1
                v = int(self.table[r, c])
                est = v if est is None or v < est else est
        cand = self.candidates
        cand[key] = est
        if len(cand) > CAND_CAP:
            keep = sorted(cand.items(), key=lambda kv: -kv[1])[:CAND_KEEP]
            self.candidates = dict(keep)

    def skew(self) -> Tuple[float, Optional[int], int]:
        """(max/mean occupied key-group load, hottest key group,
        occupied key-group count)."""
        if not self.kg_counts:
            return 0.0, None, 0
        occupied = len(self.kg_counts)
        hot_kg, hot = max(self.kg_counts.items(), key=lambda kv: kv[1])
        mean = self.total / occupied
        return (hot / mean if mean else 0.0), hot_kg, occupied


class StateIntrospection:
    """Process-global keyed-state introspection (the house singleton
    shape of runtime.device_stats.DeviceTelemetry).  Everything is a
    no-op until `enable()`; hot paths guard with ONE attribute check
    (`if INTROSPECTION.enabled:`)."""

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        import weakref
        #: live keyed backends (registered unconditionally at __init__;
        #: a WeakSet so leaked backends drop out without unregister)
        self._backends: "weakref.WeakSet" = weakref.WeakSet()
        #: accounting breakdowns frozen at backend dispose
        self._frozen: List[dict] = []
        #: state name -> skew tracker
        self._trackers: Dict[str, _SkewTracker] = {}

    # ---- lifecycle --------------------------------------------------
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._frozen.clear()
            self._trackers.clear()

    # ---- backend registry -------------------------------------------
    def register_backend(self, backend) -> None:
        with self._lock:
            self._backends.add(backend)

    def note_dispose(self, backend) -> None:
        """Called by KeyedStateBackend.dispose BEFORE tables clear:
        freeze the disposing backend's accounting so a finished job's
        numbers survive into the archive payload."""
        try:
            bd = backend.accounting_breakdown()
        except Exception:  # noqa: BLE001 — racing teardown
            bd = None
        with self._lock:
            self._backends.discard(backend)
            if bd:
                self._frozen.append(bd)

    # ---- ingest hooks (enabled path only) ---------------------------
    def _tracker(self, state_name: str) -> _SkewTracker:
        t = self._trackers.get(state_name)
        if t is None:
            with self._lock:
                t = self._trackers.setdefault(state_name, _SkewTracker())
        return t

    def note_ingest(self, state_name: str, keys,
                    max_parallelism: int) -> None:
        """Batched ingest: ONE vectorized splitmix64 pass over the key
        column feeds both the key-group counts and the Count-Min
        columns (hash halves are the CM's (hi, lo) pair, exactly like
        the device sketch)."""
        if not len(keys):
            return
        try:
            hashes = stable_hashes_np(keys)
            kgs = assign_key_groups_np(hashes, max_parallelism)
            self._tracker(state_name).note(list(keys), hashes, kgs)
        except Exception:  # noqa: BLE001 — observability never faults
            pass           # the ingest path

    def note_row(self, state_name: str, key, max_parallelism: int) -> None:
        """Scalar-path twin of note_ingest (per-element window adds)."""
        try:
            h = stable_hash64(key)
            kg = murmur_hash(h & 0xFFFFFFFF) % max_parallelism
            self._tracker(state_name).note_one(key, h, kg)
        except Exception:  # noqa: BLE001
            pass

    # ---- accounting (pull model) ------------------------------------
    def _merged_accounting(self) -> Dict[str, Dict[int, dict]]:
        with self._lock:
            sources = list(self._frozen)
            backends = list(self._backends)
        for b in backends:
            try:
                sources.append(b.accounting_breakdown())
            except Exception:  # noqa: BLE001 — racing mutation/dispose
                continue
        merged: Dict[str, Dict[int, dict]] = {}
        for bd in sources:
            for name, per_kg in bd.items():
                dst = merged.setdefault(name, {})
                for kg, e in per_kg.items():
                    d = dst.get(kg)
                    if d is None:
                        dst[kg] = dict(e)
                    else:
                        d["rows"] += e["rows"]
                        d["bytes"] += e["bytes"]
                        # key-group ranges are disjoint across subtask
                        # backends, so summing distinct-namespace counts
                        # is exact; frozen vs live never double-counts
                        # (dispose removes from the registry first)
                        d["namespaces"] += e["namespaces"]
        return merged

    # ---- gauge surface (cheap: trackers only, no accounting walk) ---
    def skew_summary(self) -> dict:
        """What the `state.keyGroupSkew` / `state.hotKey*` gauges read:
        worst per-state skew ratio, the hottest key group, occupied
        key-group count, the top hot-key share and the number of keys
        estimated at >= 5% of their state's ingest.  Zeros while
        disabled or idle (the health rule stays quiet)."""
        out = {"ratio": 0.0, "hot_key_group": -1,
               "occupied_key_groups": 0, "hot_key_share": 0.0,
               "hot_keys": 0}
        if not self.enabled:
            return out
        with self._lock:
            trackers = list(self._trackers.values())
        for t in trackers:
            r, kg, occ = t.skew()
            out["occupied_key_groups"] += occ
            if r > out["ratio"]:
                out["ratio"] = r
                out["hot_key_group"] = kg if kg is not None else -1
            if t.total:
                for cnt in t.candidates.values():
                    share = cnt / t.total
                    if share >= 0.05:
                        out["hot_keys"] += 1
                    if share > out["hot_key_share"]:
                        out["hot_key_share"] = share
        out["ratio"] = round(out["ratio"], 4)
        out["hot_key_share"] = round(out["hot_key_share"], 4)
        return out

    # ---- payload (live REST, archive, `top`) ------------------------
    def payload(self, top: Optional[int] = None) -> dict:
        if not self.enabled:
            return {"enabled": False, "accounting": {}, "ingest": {},
                    "skew": {"ratio": 0.0, "hot_key_group": None,
                             "occupied_key_groups": 0, "verdict": "disabled",
                             "per_state": {}},
                    "hot_keys": []}
        top = 10 if top is None else top
        merged = self._merged_accounting()
        accounting = {}
        for name in sorted(merged):
            per_kg = merged[name]
            rows = sum(e["rows"] for e in per_kg.values())
            nbytes = sum(e["bytes"] for e in per_kg.values())
            accounting[name] = {
                "rows": int(rows), "bytes": int(nbytes),
                "key_groups": {
                    str(kg): {"rows": int(e["rows"]),
                              "bytes": int(e["bytes"]),
                              "namespaces": int(e["namespaces"])}
                    for kg, e in sorted(per_kg.items())},
            }
        with self._lock:
            trackers = dict(self._trackers)
        ingest = {name: int(t.total)
                  for name, t in sorted(trackers.items())}
        per_state_skew = {}
        ratio, hot_kg = 0.0, None
        occupied = 0
        for name, t in sorted(trackers.items()):
            r, kg, occ = t.skew()
            per_state_skew[name] = {"ratio": round(r, 4),
                                    "hot_key_group": kg,
                                    "occupied_key_groups": occ,
                                    "rows": int(t.total)}
            occupied += occ
            if r > ratio:
                ratio, hot_kg = r, kg
        verdict = ("idle" if not trackers
                   else "skewed" if ratio >= SKEW_THRESHOLD
                   else "balanced")
        hot_keys = []
        for name, t in sorted(trackers.items()):
            for key, cnt in t.candidates.items():
                share = cnt / t.total if t.total else 0.0
                hot_keys.append({"state": name, "key": repr(key),
                                 "count": int(cnt),
                                 "share": round(share, 4)})
        hot_keys.sort(key=lambda e: (-e["count"], e["state"], e["key"]))
        return {
            "enabled": True,
            "accounting": accounting,
            "ingest": ingest,
            "skew": {"ratio": round(ratio, 4), "hot_key_group": hot_kg,
                     "occupied_key_groups": int(occupied),
                     "verdict": verdict, "per_state": per_state_skew},
            "hot_keys": hot_keys[:top],
        }


INTROSPECTION = StateIntrospection()


def get_introspection() -> StateIntrospection:
    return INTROSPECTION


# ====================================================================
# Offline snapshot inspector (`flink_tpu state inspect`)
# ====================================================================

def _read_checkpoint_entry(fs, path: str):
    from flink_tpu.runtime.checkpoints import _crc_unwrap
    with fs.open(path, "rb") as f:
        data = f.read()
    return pickle.loads(_crc_unwrap(data, path))


def load_checkpoint_readonly(directory: str,
                             checkpoint_id: Optional[int] = None) -> dict:
    """Read-only twin of FsCheckpointStorage.load: no orphan sweep, no
    chunk adoption, no registry — safe to point at a LIVE job's
    checkpoint directory.  Resolves ChunkRefs straight off
    `shared/<hash>` files."""
    from flink_tpu.core.fs import get_file_system
    from flink_tpu.state.shared_registry import ChunkRef, map_chunks
    fs, directory = get_file_system(directory)
    ids = []
    for name in fs.listdir(directory):
        if name.startswith("chk-") and not name.endswith(".part"):
            try:
                ids.append(int(name[4:]))
            except ValueError:
                pass
    if not ids:
        raise FileNotFoundError(
            f"no chk-N checkpoint files under {directory!r}")
    if checkpoint_id is None:
        checkpoint_id = max(ids)
    elif checkpoint_id not in ids:
        raise FileNotFoundError(
            f"checkpoint {checkpoint_id} not in {sorted(ids)}")
    entry = _read_checkpoint_entry(
        fs, f"{directory.rstrip('/')}/chk-{checkpoint_id}")
    shared = f"{directory.rstrip('/')}/shared"
    cache: Dict[str, Any] = {}

    def fetch(r):
        if not isinstance(r, ChunkRef):
            return r
        if r.hash not in cache:
            cache[r.hash] = _read_checkpoint_entry(fs, f"{shared}/{r.hash}")
        return cache[r.hash]

    return {**entry, "tasks": map_chunks(entry["tasks"], fetch)}


def _walk_keyed_snapshots(node, out: list) -> None:
    """Collect every KeyedStateSnapshot in a checkpoint's tasks tree
    (tolerant of the exact nesting — tasks → operators → snapshots)."""
    from flink_tpu.state.backend import KeyedStateSnapshot
    if isinstance(node, KeyedStateSnapshot):
        out.append(node)
    elif isinstance(node, dict):
        for v in node.values():
            _walk_keyed_snapshots(v, out)
    elif isinstance(node, (list, tuple)):
        for v in node:
            _walk_keyed_snapshots(v, out)


def _acct_entry(per_kg: Dict[int, dict], kg: int) -> dict:
    e = per_kg.get(kg)
    if e is None:
        e = per_kg[kg] = {"rows": 0, "bytes": 0, "_ns": set()}
    return e


def inspect_snapshot_chunks(snapshots) -> dict:
    """Decode v2 columnar chunks into the introspection accounting
    shape: per-state per-key-group rows/bytes/namespace counts, a
    component dtype breakdown, and per-key weights for the heaviest-key
    report.  Bytes definitions are EXACTLY the live accounting's:
    component ndarray nbytes for columnar rows, pickled length for
    boxed rows."""
    from flink_tpu.state.backend import decode_obj_column
    states: Dict[str, Dict[int, dict]] = {}
    dtypes: Dict[str, Dict[str, int]] = {}
    key_weights: Dict[Tuple[str, Any], List[int]] = {}
    backends: List[str] = []
    max_parallelism = None

    def _dt(name: str, dtype: str, nbytes: int) -> None:
        d = dtypes.setdefault(name, {})
        d[dtype] = d.get(dtype, 0) + nbytes

    def _key(name: str, key, rows: int, nbytes: int) -> None:
        w = key_weights.setdefault((name, key), [0, 0])
        w[0] += rows
        w[1] += nbytes

    for snap in snapshots:
        meta = snap.meta or {}
        if meta.get("backend") and meta["backend"] not in backends:
            backends.append(meta["backend"])
        if meta.get("max_parallelism"):
            max_parallelism = int(meta["max_parallelism"])
        for kg, blob in snap.blobs():
            chunk = pickle.loads(blob)
            if not (isinstance(chunk, dict) and chunk.get("v") == 2):
                raise ValueError(
                    f"key group {kg}: not a v2 columnar chunk "
                    f"(legacy snapshots are not inspectable offline)")
            for name, namespace, key, value in chunk["rows"]:
                e = _acct_entry(states.setdefault(name, {}), kg)
                nbytes = pickled_len(value)
                e["rows"] += 1
                e["bytes"] += nbytes
                e["_ns"].add(namespace)
                _dt(name, "pickled", nbytes)
                _key(name, key, 1, nbytes)
            for name, blocks in chunk["cols"].items():
                per_kg = states.setdefault(name, {})
                for block in blocks:
                    comps = block["comps"]
                    n = len(next(iter(comps.values()))) if comps else 0
                    e = _acct_entry(per_kg, kg)
                    block_bytes = 0
                    row_bytes = 0
                    for comp, arr in comps.items():
                        arr = np.asarray(arr)
                        block_bytes += arr.nbytes
                        row_bytes += arr.nbytes // max(n, 1)
                        _dt(name, str(arr.dtype), arr.nbytes)
                    e["rows"] += n
                    e["bytes"] += block_bytes
                    ns_field = block["ns"]
                    if ns_field[0] == "const":
                        e["_ns"].add(ns_field[1])
                    else:
                        e["_ns"].update(decode_obj_column(ns_field[1], n))
                    for key in decode_obj_column(block["keys"], n):
                        _key(name, key, 1, row_bytes)
    out_states = {}
    for name in sorted(states):
        per_kg = states[name]
        out_states[name] = {
            "rows": sum(e["rows"] for e in per_kg.values()),
            "bytes": sum(e["bytes"] for e in per_kg.values()),
            "dtypes": dict(sorted(dtypes.get(name, {}).items())),
            "key_groups": {
                kg: {"rows": e["rows"], "bytes": e["bytes"],
                     "namespaces": len(e["_ns"])}
                for kg, e in sorted(per_kg.items())},
        }
    return {"states": out_states, "backends": backends,
            "max_parallelism": max_parallelism,
            "_key_weights": key_weights}


def top_keys(report: dict, n: int = 10) -> List[dict]:
    """Top-N heaviest keys across all states, by bytes then rows."""
    weights = report.get("_key_weights", {})
    ranked = sorted(weights.items(),
                    key=lambda kv: (-kv[1][1], -kv[1][0],
                                    kv[0][0], repr(kv[0][1])))
    return [{"state": name, "key": repr(key),
             "rows": rows, "bytes": nbytes}
            for (name, key), (rows, nbytes) in ranked[:n]]


def rescale_preview(report: dict, parallelism: int,
                    max_parallelism: Optional[int] = None) -> dict:
    """Predicted per-subtask key-group ranges and load for a
    hypothetical rescale to `parallelism` — the exact input the
    autoscaler's rescale decision (ROADMAP item 4) needs."""
    from flink_tpu.core.keygroups import (
        DEFAULT_LOWER_BOUND_MAX_PARALLELISM)
    mp = (max_parallelism or report.get("max_parallelism")
          or DEFAULT_LOWER_BOUND_MAX_PARALLELISM)
    if parallelism < 1 or parallelism > mp:
        raise ValueError(
            f"parallelism must be in [1, {mp}] (max parallelism)")
    per_kg_rows: Dict[int, int] = {}
    per_kg_bytes: Dict[int, int] = {}
    for st in report["states"].values():
        for kg, e in st["key_groups"].items():
            kg = int(kg)
            per_kg_rows[kg] = per_kg_rows.get(kg, 0) + e["rows"]
            per_kg_bytes[kg] = per_kg_bytes.get(kg, 0) + e["bytes"]
    subtasks = []
    for i in range(parallelism):
        rng = compute_key_group_range_for_operator_index(
            mp, parallelism, i)
        rows = sum(per_kg_rows.get(kg, 0) for kg in rng)
        nbytes = sum(per_kg_bytes.get(kg, 0) for kg in rng)
        subtasks.append({
            "subtask": i,
            "key_group_range": [rng.start_key_group, rng.end_key_group],
            "rows": rows, "bytes": nbytes,
        })
    total_rows = sum(s["rows"] for s in subtasks)
    mean = total_rows / parallelism if parallelism else 0.0
    hottest = max(subtasks, key=lambda s: s["rows"]) if subtasks else None
    return {
        "parallelism": parallelism,
        "max_parallelism": mp,
        "subtasks": subtasks,
        "imbalance": round(hottest["rows"] / mean, 4)
        if hottest and mean else 0.0,
    }


def inspect_checkpoint(directory: str,
                       checkpoint_id: Optional[int] = None,
                       top: int = 10,
                       parallelism: Optional[int] = None) -> dict:
    """The `flink_tpu state inspect` engine: load a checkpoint
    read-only, decode every keyed snapshot's v2 chunks, and build the
    full report (accounting + dtypes + heaviest keys + optional rescale
    preview)."""
    entry = load_checkpoint_readonly(directory, checkpoint_id)
    snapshots: list = []
    _walk_keyed_snapshots(entry.get("tasks"), snapshots)
    report = inspect_snapshot_chunks(snapshots)
    report["checkpoint_id"] = entry.get("checkpoint_id")
    report["directory"] = directory
    report["top_keys"] = top_keys(report, top)
    if parallelism is not None:
        report["rescale"] = rescale_preview(report, parallelism)
    report.pop("_key_weights", None)
    return report
