"""Keyed-state backend contract.

Re-designs flink-runtime/.../state/AbstractKeyedStateBackend.java:64-453:
per-state-name factories (createValueState :159 … createMapState :229),
`setCurrentKey` :237 (computes the key group), `getOrCreateKeyedState`
:319 (binds a descriptor once and caches), namespace addressing
(window = namespace, WindowOperator.java:387) and snapshot/restore.

Differences from the reference, on purpose:
- No per-state serializer plumbing on the hot path; Python values go
  straight into the tables, serialization happens only at snapshot
  time (and for the TPU backend the hot path is numeric arrays).
- `snapshot()` returns a `KeyedStateSnapshot` of per-key-group chunks
  so restore can re-split ranges on rescale
  (ref: KeyGroupsStateHandle.java, StateAssignmentOperation.java).
"""

from __future__ import annotations

import abc
from typing import Any, Dict, Iterable, Optional

import numpy as np

from flink_tpu.core.keygroups import (
    KeyGroupRange,
    assign_key_groups_np,
    assign_to_key_group,
    stable_hashes_np,
)
from flink_tpu.core.state import (
    AggregatingStateDescriptor,
    FoldingStateDescriptor,
    ListStateDescriptor,
    MapStateDescriptor,
    ReducingStateDescriptor,
    StateDescriptor,
    ValueStateDescriptor,
)


#: default namespace used for non-windowed keyed state
#: (ref: VoidNamespace.java — a singleton namespace)
VOID_NAMESPACE = ()


class KeyedStateSnapshot:
    """Serialized keyed state, chunked per key group.

    `key_group_bytes[kg]` is an opaque blob for key group `kg`;
    restore feeds each chunk whose key group falls in the new backend's
    range (ref: KeyGroupsStateHandle.java + KeyGroupRangeOffsets.java —
    here chunks are explicit instead of offsets into one stream).

    Each key group's blob is wrapped as a content-addressed
    SharedChunk: checkpoint storage stores every distinct chunk ONCE
    across retained checkpoints, so an untouched key group contributes
    ~0 bytes to the next checkpoint (the incremental-checkpoint seam,
    ref: RocksDBKeyedStateBackend incremental snapshots +
    SharedStateRegistry.java).  Consumers read via ``blobs()``, which
    hands back raw bytes whether the snapshot is freshly taken
    (wrapped), storage-resolved (raw), or mixed (after intersect).
    """

    __slots__ = ("key_group_bytes", "meta")

    def __init__(self, key_group_bytes: Dict[int, bytes],
                 meta: Optional[dict] = None, wrap: bool = True):
        if wrap:
            from flink_tpu.state.shared_registry import SharedChunk
            key_group_bytes = {
                kg: b if isinstance(b, SharedChunk) else SharedChunk(b)
                for kg, b in key_group_bytes.items()}
        self.key_group_bytes = key_group_bytes
        self.meta = meta or {}

    def blobs(self):
        """Yields (key_group, raw_bytes)."""
        from flink_tpu.state.shared_registry import SharedChunk
        for kg, b in self.key_group_bytes.items():
            yield kg, (b.payload if isinstance(b, SharedChunk) else b)

    @property
    def total_bytes(self) -> int:
        return sum(len(b) for _, b in self.blobs() if b is not None)

    def intersect(self, key_group_range: KeyGroupRange) -> "KeyedStateSnapshot":
        return KeyedStateSnapshot(
            {kg: b for kg, b in self.key_group_bytes.items()
             if key_group_range.contains(kg)},
            dict(self.meta),
            wrap=False,
        )

    def _map_chunks_(self, fn):
        """shared_registry.map_chunks protocol: rebuild with every
        chunk node replaced (registration / resolution)."""
        from flink_tpu.state.shared_registry import ChunkRef, SharedChunk
        mapped = {}
        changed = False
        for kg, b in self.key_group_bytes.items():
            nb = fn(b) if isinstance(b, (SharedChunk, ChunkRef)) else b
            changed = changed or nb is not b
            mapped[kg] = nb
        if not changed:
            return self
        return KeyedStateSnapshot(mapped, dict(self.meta), wrap=False)


class KeyedStateBackend(abc.ABC):
    """The contract every keyed backend implements
    (ref: AbstractKeyedStateBackend.java:64)."""

    def __init__(self, key_group_range: KeyGroupRange, max_parallelism: int):
        self.key_group_range = key_group_range
        self.max_parallelism = max_parallelism
        self._current_key: Any = None
        self._current_key_group: int = -1
        # introspection registry (WeakSet — unconditional and free;
        # the plane only walks registered backends while enabled)
        from flink_tpu.state.introspect import INTROSPECTION
        INTROSPECTION.register_backend(self)
        #: name → bound state object (ref: keyValueStatesByName, :319)
        self._states: Dict[str, Any] = {}
        #: name → descriptor it was bound with (compatibility checks)
        self._descriptors: Dict[str, StateDescriptor] = {}
        #: serializer configs recorded by restored snapshots — checked
        #: at bind time for states registered after restore
        self._restored_serializer_cfgs: Dict[str, Any] = {}
        #: queryable-state registrations (ref: :382-389)
        self.queryable_states: Dict[str, Any] = {}

    # ---- key context (ref: setCurrentKey :237) ----------------------
    def set_current_key(self, key: Any) -> None:
        self._current_key = key
        self._current_key_group = assign_to_key_group(key, self.max_parallelism)

    @property
    def current_key(self) -> Any:
        return self._current_key

    @property
    def current_key_group(self) -> int:
        return self._current_key_group

    # ---- state binding (ref: getOrCreateKeyedState :319) ------------
    def get_or_create_keyed_state(self, descriptor: StateDescriptor):
        state = self._states.get(descriptor.name)
        if state is None:
            self._check_serializer_against_restored(descriptor)
            state = self._create_state(descriptor)
            self._states[descriptor.name] = state
            self._descriptors[descriptor.name] = descriptor
            if descriptor.is_queryable:
                self.queryable_states[descriptor.queryable_state_name] = state
        else:
            bound = self._descriptors[descriptor.name]
            if bound.TYPE != descriptor.TYPE:
                # (ref: StateDescriptor compatibility check in
                # AbstractKeyedStateBackend — same name, different kind
                # of state is a program error, not a cache hit)
                raise ValueError(
                    f"state {descriptor.name!r} already registered as "
                    f"{bound.TYPE!r}, cannot rebind as {descriptor.TYPE!r}")
        return state

    def get_partitioned_state(self, namespace, descriptor: StateDescriptor):
        """Bind + switch namespace in one call
        (ref: getPartitionedState :403)."""
        state = self.get_or_create_keyed_state(descriptor)
        state.set_current_namespace(namespace)
        return state

    def _create_state(self, descriptor: StateDescriptor):
        # ordered most-specific-first; isinstance covers subclasses
        for dtype, factory in [
            (MapStateDescriptor, self.create_map_state),
            (AggregatingStateDescriptor, self.create_aggregating_state),
            (ReducingStateDescriptor, self.create_reducing_state),
            (FoldingStateDescriptor, self.create_folding_state),
            (ListStateDescriptor, self.create_list_state),
            (ValueStateDescriptor, self.create_value_state),
        ]:
            if isinstance(descriptor, dtype):
                return factory(descriptor)
        raise TypeError(f"unsupported state descriptor {descriptor!r}")

    # ---- factories (ref: createValueState :159 … createMapState :229)
    @abc.abstractmethod
    def create_value_state(self, descriptor: ValueStateDescriptor):
        ...

    @abc.abstractmethod
    def create_list_state(self, descriptor: ListStateDescriptor):
        ...

    @abc.abstractmethod
    def create_reducing_state(self, descriptor: ReducingStateDescriptor):
        ...

    @abc.abstractmethod
    def create_aggregating_state(self, descriptor: AggregatingStateDescriptor):
        ...

    @abc.abstractmethod
    def create_folding_state(self, descriptor: FoldingStateDescriptor):
        ...

    @abc.abstractmethod
    def create_map_state(self, descriptor: MapStateDescriptor):
        ...

    # ---- batched ingest (the paper's core thesis: whole sub-batches
    # of (key, namespace, value) rows enter keyed state in one call,
    # key-group assignment done in ONE vectorized hash pass instead of
    # per-row setCurrentKey) ------------------------------------------
    def assign_key_groups_batch(self, keys) -> np.ndarray:
        """Vectorized key → key-group for a whole column of keys.
        Bit-identical to per-row ``assign_to_key_group`` (the splitmix64
        parity path shared with the batched router's split_batch)."""
        return assign_key_groups_np(stable_hashes_np(keys),
                                    self.max_parallelism)

    def add_batch(self, state, keys, namespace, values,
                  namespaces=None, pre_extracted: bool = False) -> str:
        """Append a whole column of values into `state`, one row per
        (keys[i], namespace-or-namespaces[i], values[i]).

        Dispatches to the state object's native ``add_batch`` when it
        has one (device SoA scatter on the TPU backend, grouped
        in-order fold on the heap column table); otherwise falls back
        to the exact per-row path (set_current_key +
        set_current_namespace + state.add) so opaque-object states keep
        bit-identical semantics.  Returns the path taken ("batch" or
        "rows") so callers/benches can assert zero boxed fallbacks.

        Leaves the backend's current key/namespace context undefined —
        callers in a row context must re-establish it.
        """
        from flink_tpu.state.introspect import INTROSPECTION
        from flink_tpu.state.stats import STATE_STATS
        n = len(keys)
        name = _state_name(state)
        if INTROSPECTION.enabled:
            INTROSPECTION.note_ingest(name, keys, self.max_parallelism)
        native = getattr(state, "add_batch", None)
        if native is not None:
            if pre_extracted:
                # caller already ran the aggregate's extract_value over
                # the whole column (device states only — heap states
                # don't take the kwarg)
                native(keys, namespace, values, namespaces=namespaces,
                       pre_extracted=True)
            else:
                native(keys, namespace, values, namespaces=namespaces)
            STATE_STATS.note_batch(name, n)
            return "batch"
        if namespaces is None:
            state.set_current_namespace(namespace)
            for i in range(n):
                self.set_current_key(keys[i])
                state.add(values[i])
        else:
            for i in range(n):
                self.set_current_key(keys[i])
                state.set_current_namespace(namespaces[i])
                state.add(values[i])
        STATE_STATS.note_fallback(name, n)
        return "rows"

    def get_batch(self, state, keys, namespace, namespaces=None):
        """Read a whole column of (keys[i], namespace-or-namespaces[i])
        contents out of `state` — the batched twin of ``state.get()``,
        the window FIRE path's one-gather read.

        Returns ``(results, found, path)``: `results` indexes per row
        (an ndarray for device states, a list for host states), `found`
        is a bool mask (False rows have no state — the scalar get()'s
        None), and `path` is ``"batch"`` or ``"rows"``.

        Dispatches to the state object's native ``get_batch`` when it
        has one (ONE flush + ONE fused gather + ONE D2H per component
        on the TPU backend, direct column reads on the heap tables);
        otherwise falls back to the exact per-row loop
        (set_current_key + set_current_namespace + state.get) so
        opaque-object states keep bit-identical semantics.

        Leaves the backend's current key/namespace context undefined —
        callers in a row context must re-establish it.
        """
        from flink_tpu.state.stats import STATE_STATS
        n = len(keys)
        name = _state_name(state)
        native = getattr(state, "get_batch", None)
        if native is not None:
            results, found = native(keys, namespace, namespaces=namespaces)
            STATE_STATS.note_batch(name, n)
            return results, found, "batch"
        results = []
        found = np.empty(n, bool)
        if namespaces is None:
            state.set_current_namespace(namespace)
        for i in range(n):
            self.set_current_key(keys[i])
            if namespaces is not None:
                state.set_current_namespace(namespaces[i])
            v = state.get()
            results.append(v)
            found[i] = v is not None
        STATE_STATS.note_fallback(name, n)
        return results, found, "rows"

    def clear_batch(self, state, keys, namespace, namespaces=None) -> str:
        """Drop a whole column of (keys[i], namespace-or-namespaces[i])
        slots from `state` — the batched twin of ``state.clear()``, the
        fire path's one-call cleanup.  Returns the path taken ("batch"
        or "rows"); fallback semantics per row are exactly
        set_current_key + set_current_namespace + state.clear().

        Leaves the backend's current key/namespace context undefined.
        """
        native = getattr(state, "clear_batch", None)
        if native is not None:
            native(keys, namespace, namespaces=namespaces)
            return "batch"
        if namespaces is None:
            state.set_current_namespace(namespace)
            for k in keys:
                self.set_current_key(k)
                state.clear()
        else:
            for i, k in enumerate(keys):
                self.set_current_key(k)
                state.set_current_namespace(namespaces[i])
                state.clear()
        return "rows"

    # ---- introspection ----------------------------------------------
    @abc.abstractmethod
    def get_keys(self, state_name: str, namespace) -> Iterable[Any]:
        """All keys having state under (state_name, namespace)
        (ref: KeyedStateBackend#getKeys)."""

    def num_registered_states(self) -> int:
        return len(self._states)

    # ---- serializer compatibility (ref: the
    # TypeSerializerConfigSnapshot contract — a snapshot records the
    # serializer configuration per state, and restore refuses a
    # serializer that cannot read it, StateMigrationException) --------
    def serializer_config_snapshots(self) -> dict:
        out = {}
        for name, d in self._descriptors.items():
            ser = getattr(d, "serializer", None)
            if ser is not None:
                out[name] = ser.snapshot_configuration()
        return out

    def check_serializer_compatibility(self, snapshots) -> None:
        for snap in snapshots:
            recorded = (snap.meta or {}).get("serializers", {})
            for name, cfg in recorded.items():
                # remembered for states bound AFTER restore (the
                # late-bind path restore-before-bind supports)
                self._restored_serializer_cfgs[name] = cfg
                d = self._descriptors.get(name)
                if d is not None:
                    # check only — values have not loaded yet; the
                    # restore's tail runs _apply_restored_migrations
                    self._check_serializer_against_restored(
                        d, migrate=False)

    def _check_serializer_against_restored(self,
                                           descriptor: StateDescriptor,
                                           migrate: bool = True
                                           ) -> None:
        from flink_tpu.core.serialization import StateMigrationException
        cfg = self._restored_serializer_cfgs.get(descriptor.name)
        ser = getattr(descriptor, "serializer", None)
        if cfg is not None and ser is not None \
                and not ser.ensure_compatibility(cfg):
            raise StateMigrationException(
                f"state '{descriptor.name}' was written with serializer "
                f"{cfg.serializer_name!r}; the registered serializer "
                f"{type(ser).__name__!r} cannot read it (ref: "
                f"TypeSerializerConfigSnapshot compatibility)")
        # COMPATIBLE_AFTER_MIGRATION: a changed-but-readable config
        # (e.g. an evolved record schema) migrates the state's values
        # once, at whichever comes later — bind or restore.  The
        # recorded config is then replaced so a re-bind can never
        # migrate twice (double resolution would overwrite real
        # values with defaults).
        if migrate and cfg is not None and ser is not None \
                and cfg != ser.snapshot_configuration():
            self._migrate_state_values(descriptor, ser, cfg)
            self._restored_serializer_cfgs[descriptor.name] = \
                ser.snapshot_configuration()

    def _migrate_state_values(self, descriptor: StateDescriptor,
                              serializer, restored_cfg) -> None:
        """Backend hook: rewrite the descriptor's restored values via
        serializer.migrate_value.  Backends that materialize restored
        values as live objects (the heap/tpu host tables) override;
        byte-oriented stores resolve lazily through the serializer
        itself and need nothing here.  (Takes the DESCRIPTOR, not the
        name: at bind time the registry entry does not exist yet.)"""

    def _apply_restored_migrations(self) -> None:
        """Called by restore() AFTER values load: migrate every
        already-bound state whose recorded config differs (the
        bind-before-restore order; restore-before-bind migrates at
        bind via _check_serializer_against_restored)."""
        for name, d in self._descriptors.items():
            cfg = self._restored_serializer_cfgs.get(name)
            ser = getattr(d, "serializer", None)
            if cfg is not None and ser is not None \
                    and cfg != ser.snapshot_configuration():
                self._migrate_state_values(d, ser, cfg)
                self._restored_serializer_cfgs[name] = \
                    ser.snapshot_configuration()

    # ---- snapshot / restore (ref: Snapshotable) ---------------------
    @abc.abstractmethod
    def snapshot(self) -> KeyedStateSnapshot:
        ...

    @abc.abstractmethod
    def restore(self, snapshots: Iterable[KeyedStateSnapshot]) -> None:
        """Restore from one or more snapshots' chunks that intersect
        this backend's key-group range (rescale = pass the snapshots of
        all old subtasks; chunks outside the range are skipped).
        Implementations call `check_serializer_compatibility` first."""

    # ---- keyed-state introspection ----------------------------------
    def accounting_breakdown(self) -> Dict[str, Dict[int, dict]]:
        """Per-(state, key-group) accounting:
        ``{state_name: {key_group: {"rows", "bytes", "namespaces"}}}``.
        Bytes follow the snapshot's serialization exactly — component
        ndarray nbytes for columnar rows, pickled length for boxed
        rows — so live accounting, the archive payload and the offline
        inspector always agree.  Backends with tables override."""
        return {}

    def dispose(self) -> None:
        # freeze accounting BEFORE subclasses clear their tables
        # (subclass disposes call super().dispose() first), so a
        # finished job's numbers survive into the archive payload
        from flink_tpu.state.introspect import INTROSPECTION
        if INTROSPECTION.enabled:
            INTROSPECTION.note_dispose(self)
        self._states.clear()


def _state_name(state) -> str:
    d = getattr(state, "_descriptor", None)
    return getattr(d, "name", "?") if d is not None else "?"


def encode_obj_column(values) -> tuple:
    """Encode a python value column through the wire codec's "col" tier
    (int64/float64/str/tuple columns, PR 5) — ``("pickle", list)`` when
    the column is not strictly typed.  Snapshot chunks carry these so
    key columns and namespace columns serialize without boxing."""
    values = list(values)
    if values:
        try:
            from flink_tpu.runtime.netchannel import _encode_value_column
            col = _encode_value_column(values)
        except (OverflowError, ValueError):
            col = None
        if col is not None:
            return col
    return ("pickle", values)


def decode_obj_column(col, n: int) -> list:
    """Inverse of encode_obj_column."""
    if col[0] == "pickle":
        return list(col[1])
    from flink_tpu.runtime.netchannel import _decode_value_column
    return _decode_value_column(col, n)


def migrate_table_values(table, descriptor, serializer,
                         restored_cfg) -> None:
    """Shared value-migration pass over a live StateTable: the
    descriptor's TYPE decides the stored shape — a LIST state stores a
    Python list of elements and a MAP state a dict of entries, so the
    ELEMENT serializer's migrate_value maps over them; everything else
    stores one value (the reference's per-element migration in
    StateTableByKeyGroupReaders)."""
    kind = getattr(descriptor, "TYPE", "value")
    if kind == "list":
        def mig(v):
            return [serializer.migrate_value(x, restored_cfg) for x in v]
    elif kind == "map":
        def mig(v):
            return {k: serializer.migrate_value(x, restored_cfg)
                    for k, x in v.items()}
    else:
        def mig(v):
            return serializer.migrate_value(v, restored_cfg)
    for namespace, key, value in list(table.entries()):
        table.put(key, namespace, mig(value))
