"""TPU-HBM keyed-state backend — the replacement for the reference's
native (RocksDB/JNI) backend.

The reference's RocksDB backend pays two JNI hops per record
(RocksDBAggregatingState.java:108-131: db.get → deserialize → add →
serialize → db.put).  Here, aggregation state for ALL keys of this
subtask's key-group range lives as struct-of-arrays in device HBM
(`{component: f32/u8/i32 [capacity, ...]}`), a host-side index maps
(key, namespace) → dense slot, and updates are micro-batched: records
accumulate in host ring buffers and one `jax.jit` scatter dispatch
applies the whole batch (donated buffers → in-place HBM update, no
reallocation).  Reads (window fires) flush pending writes then gather.

States whose values are arbitrary Python objects (ValueState, ListState,
MapState, reducing/aggregating with non-device functions) are kept in
host tables exactly like the heap backend — mirroring how RocksDB
stores opaque bytes for everything while the hot path here is the
numeric aggregation state (the north-star workload).

Key-group layout: every slot records its key group so snapshots chunk
per key group (rescale re-splits ranges, ref:
KeyGroupRangeAssignment.java:47-56, StateAssignmentOperation.java).
"""

from __future__ import annotations

import pickle
import threading
import time
from collections import defaultdict
from typing import Any, Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.core.keygroups import (
    KeyGroupRange,
    assign_to_key_group,
    stable_hash64,
)
from flink_tpu.core.state import (
    AggregatingState,
    AggregatingStateDescriptor,
    ListStateDescriptor,
    MapStateDescriptor,
    ReducingStateDescriptor,
    FoldingStateDescriptor,
    ValueStateDescriptor,
)
from flink_tpu.ops.device_agg import DeviceAggregateFunction
from flink_tpu.state.backend import (
    VOID_NAMESPACE,
    KeyedStateBackend,
    KeyedStateSnapshot,
)
from flink_tpu.state.heap_backend import (
    HeapAggregatingState,
    HeapFoldingState,
    HeapListState,
    HeapMapState,
    HeapReducingState,
    HeapValueState,
    StateTable,
    split_column_by_key_group,
)
from flink_tpu.runtime.device_stats import TELEMETRY
from flink_tpu.state.stats import STATE_STATS, register_device_state

_perf_ns = time.perf_counter_ns

DEFAULT_INITIAL_CAPACITY = 4096
DEFAULT_MICROBATCH = 16384


def _round_up_pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


class DeviceAggregatingState(AggregatingState):
    """Slot-indexed, micro-batched device aggregation state.

    The device twin of RocksDBAggregatingState / HeapAggregatingState:
    identical observable semantics through the AggregatingState
    interface, but `add` enqueues into a pending batch and `get`
    flushes + gathers, so the per-record cost is a few Python list ops
    and the per-batch cost is one XLA scatter over the whole key group.
    """

    def __init__(self, backend: "TpuKeyedStateBackend",
                 descriptor: AggregatingStateDescriptor,
                 initial_capacity: int = DEFAULT_INITIAL_CAPACITY,
                 microbatch: int = DEFAULT_MICROBATCH,
                 max_device_slots: Optional[int] = None):
        agg = descriptor.aggregate_function
        assert isinstance(agg, DeviceAggregateFunction)
        self._backend = backend
        self._descriptor = descriptor
        self.agg: DeviceAggregateFunction = agg
        self._namespace = VOID_NAMESPACE
        self.capacity = initial_capacity
        self.device_state: Dict[str, jnp.ndarray] = agg.init_state(initial_capacity)
        #: (key, namespace) → slot
        self.slot_index: Dict[Tuple[Any, Any], int] = {}
        #: slot → (key, namespace) (None = free)
        self.slot_meta: List[Optional[Tuple[Any, Any]]] = [None] * initial_capacity
        self._free: List[int] = list(range(initial_capacity - 1, -1, -1))
        self.microbatch = microbatch
        # ---- host-RAM spill tier (SURVEY §7 hard-part: state > HBM;
        # the role RocksDB's disk residency plays in the reference) ----
        #: device-slot budget; None = unbounded (grow-on-demand)
        self.max_device_slots = max_device_slots
        #: (key, namespace) → {component: numpy row} for entries
        #: evicted out of HBM; promoted back on access
        self.host_tier: Dict[Tuple[Any, Any], Dict[str, np.ndarray]] = {}
        #: per-slot last-access stamps (approximate LRU clock)
        self._access_stamp: List[int] = [0] * initial_capacity
        #: per-slot flag: some update has actually LANDED on device —
        #: queryable reads must not surface the init accumulator of a
        #: slot whose first adds are still pending (heap returns None)
        self._slot_flushed = bytearray(initial_capacity)
        self._clock = 0
        #: observability: spill/promotion counters
        self.evictions = 0
        self.promotions = 0
        self._pending_slots: List[int] = []
        self._pending_values: List[Any] = []
        self._pending_hi: List[int] = []
        self._pending_lo: List[int] = []
        # jit-compiled entry points (cached per state object; XLA caches
        # per padded batch shape)
        self._jit_update = jax.jit(self._update_fn, donate_argnums=0)
        self._jit_upload = jax.jit(
            lambda st, slot, row: {k: st[k].at[slot].set(row[k])
                                   for k in st},
            donate_argnums=0)
        self._jit_merge = jax.jit(self.agg.merge_slots, donate_argnums=0)
        #: the jit(vmap(merge)) pairwise kernel — unique-dst dispatches
        #: only (merge_namespaces_batch rounds multi-source merges)
        self._jit_merge_rows = jax.jit(self.agg.merge_rows,
                                       donate_argnums=0)
        self._jit_clear = jax.jit(self.agg.clear_slots, donate_argnums=0)
        self._jit_result = jax.jit(self.agg.result)
        # queryable-state reads come from foreign threads; every
        # device_state REPLACEMENT donates the old tree's buffers, so
        # a concurrent gather on the old tree would read freed memory.
        # This lock serializes state swaps against query gathers (the
        # owner thread's swap sites take it; cost is one uncontended
        # acquire per micro-batch)
        self._device_lock = threading.RLock()
        register_device_state(self)

    def _update_fn(self, state, slots, values, hi, lo, mask):
        return self.agg.update(state, slots, values, hi, lo, mask)

    # ---- namespace / key context ------------------------------------
    def set_current_namespace(self, namespace) -> None:
        self._namespace = namespace

    # ---- slot management --------------------------------------------
    def _slot_for(self, key, namespace, create: bool = True) -> Optional[int]:
        entry = (key, namespace)
        slot = self.slot_index.get(entry)
        if slot is None and entry in self.host_tier:
            slot = self._promote(entry)
        if slot is None and create:
            if not self._free:
                self._make_room()
            slot = self._free.pop()
            self.slot_index[entry] = slot
            self.slot_meta[slot] = entry
        if slot is not None:
            self._clock += 1
            self._access_stamp[slot] = self._clock
        return slot

    def _make_room(self) -> None:
        """No free slots: grow HBM state, or — at the device budget —
        spill the coldest quarter of slots to the host tier (the
        RocksDB-disk-residency role; SURVEY §7 'state larger than
        HBM')."""
        if (self.max_device_slots is None
                or self.capacity * 2 <= self.max_device_slots):
            self._grow(self.capacity * 2)
            return
        self._evict_cold(max(1, self.capacity // 4))

    def _evict_cold(self, n: int) -> None:
        self._flush()
        # never evict recently touched slots: a batch mid-assembly
        # references up to `microbatch` freshly assigned slots (the
        # chunked add_batch bound; get_batch never allocates), and a
        # merge mid-flight re-stamps its sources just before
        # allocating the target — the +16 margin covers the merge's
        # source set
        protected = self._clock - (2 * self.microbatch + 16)
        candidates = [(self._access_stamp[s], s)
                      for s, meta in enumerate(self.slot_meta)
                      if meta is not None
                      and self._access_stamp[s] < protected]
        if not candidates:
            # everything is hot: grow past the budget rather than
            # corrupt in-flight batches (soft cap)
            self._grow(self.capacity * 2)
            return
        candidates.sort()
        victims = [s for _, s in candidates[:n]]
        idx = np.array(victims, np.int32)
        if TELEMETRY.enabled:
            t0 = _perf_ns()
            host_rows = {name: np.asarray(arr[jnp.asarray(idx)])
                         for name, arr in self.device_state.items()}
            TELEMETRY.record_transfer(
                "d2h", sum(a.nbytes for a in host_rows.values()),
                t0, _perf_ns(), "state.evict")
        else:
            host_rows = {name: np.asarray(arr[jnp.asarray(idx)])
                         for name, arr in self.device_state.items()}
        for i, s in enumerate(victims):
            entry = self.slot_meta[s]
            self.host_tier[entry] = {name: host_rows[name][i]
                                     for name in host_rows}
            del self.slot_index[entry]
            self.slot_meta[s] = None
        with self._device_lock:
            self.device_state = self._jit_clear(self.device_state,
                                                jnp.asarray(idx))
            for s_ in victims:
                self._slot_flushed[s_] = 0
        self._free.extend(victims)
        self.evictions += len(victims)

    def _promote(self, entry) -> int:
        """Host-tier entry accessed: lift its row back into HBM
        (donated single-row upload — in-place, no full-array copy).
        The index entry publishes only AFTER the upload, inside the
        lock: a concurrent query must see either the spilled row or
        the uploaded slot, never a zeroed in-between slot."""
        if not self._free:
            self._make_room()
        slot = self._free.pop()
        row = self.host_tier[entry]
        with self._device_lock:
            if TELEMETRY.enabled:
                t0 = _perf_ns()
                self.device_state = self._jit_upload(
                    self.device_state, jnp.int32(slot),
                    {name: jnp.asarray(val) for name, val in row.items()})
                TELEMETRY.record_transfer(
                    "h2d",
                    sum(getattr(v, "nbytes", 0) for v in row.values()),
                    t0, _perf_ns(), "state.promote")
            else:
                self.device_state = self._jit_upload(
                    self.device_state, jnp.int32(slot),
                    {name: jnp.asarray(val) for name, val in row.items()})
            del self.host_tier[entry]
            self.slot_index[entry] = slot
            self._slot_flushed[slot] = 1
        self.slot_meta[slot] = entry
        # freshly promoted slots are HOT: stamp them or a later
        # promotion in the same batch could evict them right back
        self._clock += 1
        self._access_stamp[slot] = self._clock
        self.promotions += 1
        return slot

    def _grow(self, new_capacity: int) -> None:
        self._flush()
        with self._device_lock:
            self.device_state = self.agg.grow_state(self.device_state,
                                                    new_capacity)
        self._free.extend(range(new_capacity - 1, self.capacity - 1, -1))
        self._access_stamp.extend([0] * (new_capacity - self.capacity))
        self._slot_flushed.extend(bytes(new_capacity - self.capacity))
        self.slot_meta.extend([None] * (new_capacity - self.capacity))
        self.capacity = new_capacity

    # ---- write path -------------------------------------------------
    def add(self, value) -> None:
        slot = self._slot_for(self._backend.current_key, self._namespace)
        self._pending_slots.append(slot)
        value = self.agg.extract_value(value)
        if self.agg.needs_value:
            self._pending_values.append(value)
        if self.agg.needs_value_hash:
            h = stable_hash64(value)
            self._pending_hi.append(h >> 32)
            self._pending_lo.append(h & 0xFFFFFFFF)
        if len(self._pending_slots) >= self.microbatch:
            self._flush()

    def add_batch(self, keys: Iterable[Any], namespace, values,
                  namespaces=None, pre_extracted: bool = False) -> None:
        """Vectorized write: one slot lookup loop, no per-record method
        dispatch.  `namespace` is ONE namespace shared by the whole
        batch (a window tuple is a single namespace); pass a parallel
        sequence via `namespaces=` to override per record.  `values` is
        a sequence/ndarray parallel to keys; `pre_extracted=True` means
        the caller already ran extract_value/extract_column over it (a
        numeric column straight off a RecordBatch)."""
        keys = list(keys)
        if self.max_device_slots is not None \
                and len(keys) > self.microbatch:
            # capped backend: resolve slots in microbatch-sized chunks
            # so an eviction triggered late in the loop can never take
            # a slot resolved earlier in the SAME chunk (chunk size <=
            # the eviction-protected stamp window)
            for i in range(0, len(keys), self.microbatch):
                sl = slice(i, i + self.microbatch)
                self.add_batch(
                    keys[sl], namespace,
                    values[sl] if values is not None else None,
                    namespaces=None if namespaces is None
                    else namespaces[sl],
                    pre_extracted=pre_extracted)
            return
        slot_for = self._slot_for
        if namespaces is None:
            slots = [slot_for(k, namespace) for k in keys]
        else:
            slots = [slot_for(k, namespaces[i]) for i, k in enumerate(keys)]
        self._pending_slots.extend(slots)
        extract = self.agg.extract_value
        # overridden on the class or per-instance (an instance-attached
        # plain function has no __func__)
        if not pre_extracted and getattr(
                extract, "__func__",
                None) is not DeviceAggregateFunction.extract_value:
            values = [extract(v) for v in values]
        if self.agg.needs_value:
            self._pending_values.extend(values)
        if self.agg.needs_value_hash:
            hi = self._pending_hi
            lo = self._pending_lo
            for v in values:
                h = stable_hash64(v)
                hi.append(h >> 32)
                lo.append(h & 0xFFFFFFFF)
        if len(self._pending_slots) >= self.microbatch:
            self._flush()

    def _flush(self) -> None:
        n = len(self._pending_slots)
        if n == 0:
            return
        with self._device_lock:
            self._flush_locked(n)

    def _flush_locked(self, n: int) -> None:
        padded = _round_up_pow2(n)
        slots = np.zeros(padded, np.int32)
        slots[:n] = self._pending_slots
        mask = np.zeros(padded, bool)
        mask[:n] = True
        if self.agg.needs_value:
            values = np.zeros(padded, self.agg.value_dtype)
            values[:n] = np.asarray(self._pending_values, self.agg.value_dtype)
        else:
            values = np.zeros(padded, self.agg.value_dtype)
        if self.agg.needs_value_hash:
            hi = np.zeros(padded, np.uint32)
            lo = np.zeros(padded, np.uint32)
            hi[:n] = np.asarray(self._pending_hi, np.uint64).astype(np.uint32)
            lo[:n] = np.asarray(self._pending_lo, np.uint64).astype(np.uint32)
        else:
            hi = np.zeros(padded, np.uint32)
            lo = np.zeros(padded, np.uint32)
        if TELEMETRY.enabled:
            t0 = _perf_ns()
            self.device_state = self._jit_update(
                self.device_state, slots, values, hi, lo, mask)
            TELEMETRY.record_transfer(
                "h2d",
                slots.nbytes + mask.nbytes + values.nbytes
                + hi.nbytes + lo.nbytes,
                t0, _perf_ns(), "state.flush")
            TELEMETRY.note_flush(n)
        else:
            self.device_state = self._jit_update(
                self.device_state, slots, values, hi, lo, mask)
        STATE_STATS.note_flush(n)
        for s_ in self._pending_slots:
            self._slot_flushed[s_] = 1
        self._pending_slots.clear()
        self._pending_values.clear()
        self._pending_hi.clear()
        self._pending_lo.clear()

    # ---- read path --------------------------------------------------
    def get(self):
        slot = self._slot_for(self._backend.current_key, self._namespace,
                              create=False)
        if slot is None:
            return None
        self._flush()
        if TELEMETRY.enabled:
            t0 = _perf_ns()
            res = np.asarray(self._jit_result(
                self.device_state, jnp.asarray(np.array([slot], np.int32))))
            TELEMETRY.record_transfer("d2h", res.nbytes, t0, _perf_ns(),
                                      "state.fire")
            TELEMETRY.note_fire_read()
            out = res[0]
        else:
            out = np.asarray(self._jit_result(
                self.device_state,
                jnp.asarray(np.array([slot], np.int32))))[0]
        return out.item() if np.ndim(out) == 0 else out

    def get_batch(self, keys, namespace, namespaces=None) -> Tuple[np.ndarray, np.ndarray]:
        """Gather results for many (key, namespace) pairs in ONE device
        round-trip: one pending-ring flush, one fused jit gather, one
        D2H per component — the batched window-fire read.  Spill-tier
        rows are finalized from their host-resident accumulators
        WITHOUT promotion (a fire is a read; lifting cold rows into
        HBM per fired window would re-pay the per-row transfer tax
        this path exists to amortize).  No slot allocation or eviction
        can happen here, so no chunking is needed.  Returns
        (results, found_mask); namespace semantics as in `add_batch`."""
        keys = list(keys)
        n = len(keys)
        slot_index = self.slot_index
        host_tier = self.host_tier
        slots = np.zeros(n, np.int32)
        found = np.zeros(n, bool)
        spill_idx: List[int] = []
        spill_rows: List[Dict[str, np.ndarray]] = []
        for i, k in enumerate(keys):
            entry = (k, namespace if namespaces is None else namespaces[i])
            s = slot_index.get(entry)
            if s is not None:
                slots[i] = s
                found[i] = True
                # reads stamp the LRU clock exactly as scalar get()
                self._clock += 1
                self._access_stamp[s] = self._clock
                continue
            row = host_tier.get(entry)
            if row is not None:
                spill_idx.append(i)
                spill_rows.append(row)
                found[i] = True
        self._flush()  # ONE flush for the whole sweep
        if TELEMETRY.enabled:
            t0 = _perf_ns()
            res = np.asarray(self._jit_result(
                self.device_state, jnp.asarray(slots)))
            TELEMETRY.record_transfer("d2h", res.nbytes, t0, _perf_ns(),
                                      "state.fire")
            TELEMETRY.note_fire_read()
        else:
            res = np.asarray(self._jit_result(
                self.device_state, jnp.asarray(slots)))
        if spill_idx:
            res = np.array(res)  # the gather's output is read-only
            res[spill_idx] = self._finalize_spilled(spill_rows)
        return res, found

    def _finalize_spilled(self, rows: List[Dict[str, np.ndarray]]) -> np.ndarray:
        """Result extraction for spill-tier rows without promotion:
        stack the host-resident accumulator rows into a pow2-padded
        [m, ...] state and run the SAME jit result kernel over it —
        bit-identical finalization (query_by_key's single-row idiom,
        batched), zero HBM slot traffic."""
        m = len(rows)
        padded = _round_up_pow2(m)
        state = {}
        nbytes_in = 0
        for name in self.device_state:
            col = np.stack([r[name] for r in rows])
            if padded != m:
                pad = np.zeros((padded - m,) + col.shape[1:], col.dtype)
                col = np.concatenate([col, pad])
            nbytes_in += col.nbytes
            state[name] = jnp.asarray(col)
        idx = jnp.asarray(np.arange(padded, dtype=np.int32))
        if TELEMETRY.enabled:
            t0 = _perf_ns()
            out = np.asarray(self._jit_result(state, idx))
            TELEMETRY.record_transfer("h2d", nbytes_in, t0, t0,
                                      "state.fire.spill")
            TELEMETRY.record_transfer("d2h", out.nbytes, t0, _perf_ns(),
                                      "state.fire.spill")
        else:
            out = np.asarray(self._jit_result(state, idx))
        return out[:m]

    def query_by_key(self, key, namespace):
        """Queryable-state read from a FOREIGN thread (ref:
        AbstractKeyedStateBackend.java:382-389 getPartitionedState for
        queries + KvStateServerHandler).  Dirty-read semantics match
        the heap path: pending (unflushed) adds are invisible; no
        owner-side structures mutate (no promotion, no access-stamp
        touch).  The device gather serializes against state swaps via
        the device lock."""
        entry = (key, namespace)
        with self._device_lock:
            slot = self.slot_index.get(entry)
            if slot is not None and not self._slot_flushed[slot]:
                # the key's first adds are still pending: invisible
                # (matches the heap path's None-for-absent contract)
                slot = None
            if slot is not None:
                out = np.asarray(self._jit_result(
                    self.device_state,
                    jnp.asarray(np.array([slot], np.int32))))[0]
                return out.item() if np.ndim(out) == 0 else out
        row = self.host_tier.get(entry)
        if row is not None:
            # spilled entry: finalize its single row host-side (lift
            # to a 1-slot state; compiles once per aggregate)
            state1 = {name: jnp.asarray(val)[None]
                      for name, val in row.items()}
            out = np.asarray(self._jit_result(
                state1, jnp.asarray(np.zeros(1, np.int32))))[0]
            return out.item() if np.ndim(out) == 0 else out
        return None

    # ---- lifecycle --------------------------------------------------
    def clear(self) -> None:
        entry = (self._backend.current_key, self._namespace)
        self.host_tier.pop(entry, None)
        slot = self.slot_index.pop(entry, None)
        if slot is None:
            return
        self._flush()
        with self._device_lock:
            self.device_state = self._jit_clear(
                self.device_state, jnp.asarray(np.array([slot], np.int32)))
            self._slot_flushed[slot] = 0
        self.slot_meta[slot] = None
        self._free.append(slot)

    def clear_batch(self, keys, namespace, namespaces=None) -> None:
        slots = []
        for i, k in enumerate(keys):
            ns = namespace if namespaces is None else namespaces[i]
            self.host_tier.pop((k, ns), None)
            s = self.slot_index.pop((k, ns), None)
            if s is not None:
                slots.append(s)
                self.slot_meta[s] = None
        if not slots:
            return
        self._flush()
        n = len(slots)
        padded = _round_up_pow2(n)
        arr = np.full(padded, slots[0], np.int32)
        arr[:n] = slots
        with self._device_lock:
            self.device_state = self._jit_clear(self.device_state,
                                                jnp.asarray(arr))
            for s_ in slots:
                self._slot_flushed[s_] = 0
        self._free.extend(slots)

    def merge_namespaces(self, target, sources) -> None:
        """Session-window merge: device merge_slots(dst, src), then
        free source slots (ref: mergeNamespaces,
        WindowOperator.java:338 / MergingWindowSet.java:156)."""
        key = self._backend.current_key
        self._flush()
        # spilled sources participate in the merge: promote them first
        for src in sources:
            if (key, src) in self.host_tier:
                self._promote((key, src))
        if (key, target) in self.host_tier:
            self._promote((key, target))
        # touch every source slot BEFORE any allocation below: the
        # target slot allocation may need to make room, and eviction
        # must not take a slot this merge still references (fresh
        # stamps fall inside _evict_cold's protected window; slots
        # stay fully registered in slot_index/slot_meta until after
        # the allocation, so eviction bookkeeping stays consistent)
        live_sources = []
        for src in sources:
            s = self.slot_index.get((key, src))
            if s is not None:
                self._clock += 1
                self._access_stamp[s] = self._clock
                live_sources.append((src, s))
        # don't materialize a target slot unless some source has state
        # (matches heap: merging all-empty namespaces leaves no state)
        if not live_sources:
            return  # nothing to fold in; target (if any) stays as-is
        dst = self._slot_for(key, target)
        src_slots = []
        for src, s in live_sources:
            del self.slot_index[(key, src)]
            if s != dst:
                src_slots.append(s)
                self.slot_meta[s] = None
        if not src_slots:
            return
        dsts = np.full(len(src_slots), dst, np.int32)
        srcs = np.array(src_slots, np.int32)
        with self._device_lock:
            self.device_state = self._jit_merge(
                self.device_state, jnp.asarray(dsts), jnp.asarray(srcs))
            self.device_state = self._jit_clear(self.device_state,
                                                jnp.asarray(srcs))
            self._slot_flushed[dst] = 1
            for s_ in src_slots:
                self._slot_flushed[s_] = 0
        self._free.extend(src_slots)

    def merge_namespaces_batch(self, merges) -> None:
        """Batched session merge: `merges` is a list of
        (key, target_namespace, [source_namespaces]).  One flush up
        front, then the whole merge set runs in ROUNDS through the
        jit(vmap(agg.merge)) pairwise kernel — round r folds each
        target's r-th live source, so every dispatch has UNIQUE
        destination slots (distinct merges own distinct (key, target)
        slots) — and one clear frees every source slot at the end.
        Observable state after this call is identical to running
        merge_namespaces per (key, target)."""
        self._flush()
        plans = []  # (dst_slot, [src_slots])
        for key, target, sources in merges:
            for src in sources:
                if (key, src) in self.host_tier:
                    self._promote((key, src))
            if (key, target) in self.host_tier:
                self._promote((key, target))
            live = []
            for src in sources:
                s = self.slot_index.get((key, src))
                if s is not None:
                    self._clock += 1
                    self._access_stamp[s] = self._clock
                    live.append((src, s))
            if not live:
                continue
            dst = self._slot_for(key, target)
            srcs = []
            for src, s in live:
                del self.slot_index[(key, src)]
                if s != dst:
                    srcs.append(s)
                    self.slot_meta[s] = None
            if srcs:
                plans.append((dst, srcs))
        if not plans:
            return
        rounds = max(len(srcs) for _, srcs in plans)
        all_srcs: List[int] = []
        with self._device_lock:
            for r in range(rounds):
                dsts = [dst for dst, srcs in plans if len(srcs) > r]
                srcs = [srcs[r] for _, srcs in plans if len(srcs) > r]
                self.device_state = self._jit_merge_rows(
                    self.device_state,
                    jnp.asarray(np.array(dsts, np.int32)),
                    jnp.asarray(np.array(srcs, np.int32)))
                all_srcs.extend(srcs)
            self.device_state = self._jit_clear(
                self.device_state, jnp.asarray(np.array(all_srcs, np.int32)))
            for dst, _ in plans:
                self._slot_flushed[dst] = 1
            for s_ in all_srcs:
                self._slot_flushed[s_] = 0
        self._free.extend(all_srcs)

    # ---- snapshot ---------------------------------------------------
    def snapshot_entries(self) -> Dict[int, List[Tuple[Any, Any, Dict[str, np.ndarray]]]]:
        """Per key group: [(key, namespace, {component: row})]."""
        self._flush()
        if TELEMETRY.enabled:
            t0 = _perf_ns()
            host = {name: np.asarray(arr)
                    for name, arr in self.device_state.items()}
            TELEMETRY.record_transfer(
                "d2h", sum(a.nbytes for a in host.values()),
                t0, _perf_ns(), "state.snapshot")
        else:
            host = {name: np.asarray(arr)
                    for name, arr in self.device_state.items()}
        per_kg: Dict[int, List[Tuple[Any, Any, Dict[str, np.ndarray]]]] = defaultdict(list)
        mp = self._backend.max_parallelism
        for (key, namespace), slot in self.slot_index.items():
            kg = assign_to_key_group(key, mp)
            row = {name: host[name][slot] for name in host}
            per_kg[kg].append((key, namespace, row))
        # spilled entries are part of the state too
        for (key, namespace), row in self.host_tier.items():
            kg = assign_to_key_group(key, mp)
            per_kg[kg].append((key, namespace, dict(row)))
        return per_kg

    def restore_entries(self, entries: List[Tuple[Any, Any, Dict[str, np.ndarray]]]) -> None:
        if not entries:
            return
        needed = len(self.slot_index) + len(entries)
        if self.max_device_slots is not None \
                and needed > self.max_device_slots:
            # beyond the device budget: the overflow restores straight
            # into the host tier (promoted lazily on first access)
            budget = max(self.max_device_slots - len(self.slot_index), 0)
            for key, namespace, row in entries[budget:]:
                self.host_tier[(key, namespace)] = dict(row)
            entries = entries[:budget]
            if not entries:
                return
            needed = len(self.slot_index) + len(entries)
        if needed > self.capacity - len(self._pending_slots):
            self._grow(max(self.capacity * 2, _round_up_pow2(needed)))
        slots = []
        rows: Dict[str, List[np.ndarray]] = defaultdict(list)
        for key, namespace, row in entries:
            slot = self._slot_for(key, namespace)
            slots.append(slot)
            for name, val in row.items():
                rows[name].append(val)
        idx = jnp.asarray(np.array(slots, np.int32))
        with self._device_lock:
            new_state = dict(self.device_state)
            for name, vals in rows.items():
                new_state[name] = new_state[name].at[idx].set(
                    jnp.asarray(np.stack(vals)))
            self.device_state = new_state
            for s_ in slots:
                self._slot_flushed[s_] = 1

    def snapshot_columns(self) -> Dict[int, Tuple[list, list, Dict[str, np.ndarray]]]:
        """Columnar snapshot: per key group, (keys, namespaces,
        {component: stacked rows}) — ONE host transfer per component,
        ONE fancy-index gather, and the key-group split done in one
        vectorized hash pass (replaces snapshot_entries' per-row dict
        building + per-row assign_to_key_group)."""
        self._flush()
        keys: List[Any] = []
        nss: List[Any] = []
        slots: List[int] = []
        for (key, namespace), slot in self.slot_index.items():
            keys.append(key)
            nss.append(namespace)
            slots.append(slot)
        if TELEMETRY.enabled:
            t0 = _perf_ns()
            host = {name: np.asarray(arr)
                    for name, arr in self.device_state.items()}
            TELEMETRY.record_transfer(
                "d2h", sum(a.nbytes for a in host.values()),
                t0, _perf_ns(), "state.snapshot")
        else:
            host = {name: np.asarray(arr)
                    for name, arr in self.device_state.items()}
        idx = np.array(slots, np.int32)
        comps = {name: arr[idx] for name, arr in host.items()}
        if self.host_tier:
            spilled = list(self.host_tier.items())
            for (key, namespace), _ in spilled:
                keys.append(key)
                nss.append(namespace)
            spill_cols = {name: np.stack([row[name] for _, row in spilled])
                          for name in host}
            comps = {name: np.concatenate([comps[name], spill_cols[name]])
                     for name in host}
        out: Dict[int, Tuple[list, list, Dict[str, np.ndarray]]] = {}
        mp = self._backend.max_parallelism
        for kg, sel in split_column_by_key_group(keys, mp):
            out[kg] = ([keys[i] for i in sel], [nss[i] for i in sel],
                       {name: arr[sel] for name, arr in comps.items()})
        return out

    def restore_columns(self, keys: list, namespaces: list,
                        comps: Dict[str, np.ndarray]) -> None:
        """Columnar restore: one slot-resolve loop, ONE device upload
        per component (no per-row dict boxing)."""
        n = len(keys)
        if n == 0:
            return
        needed = len(self.slot_index) + n
        if self.max_device_slots is not None \
                and needed > self.max_device_slots:
            # beyond the device budget: the overflow restores straight
            # into the host tier (promoted lazily on first access)
            budget = max(self.max_device_slots - len(self.slot_index), 0)
            for i in range(budget, n):
                self.host_tier[(keys[i], namespaces[i])] = {
                    name: np.asarray(arr[i]) for name, arr in comps.items()}
            keys = keys[:budget]
            namespaces = namespaces[:budget]
            comps = {name: arr[:budget] for name, arr in comps.items()}
            n = budget
            if n == 0:
                return
            needed = len(self.slot_index) + n
        if needed > self.capacity - len(self._pending_slots):
            self._grow(max(self.capacity * 2, _round_up_pow2(needed)))
        slots = np.empty(n, np.int32)
        for i in range(n):
            slots[i] = self._slot_for(keys[i], namespaces[i])
        idx = jnp.asarray(slots)
        with self._device_lock:
            new_state = dict(self.device_state)
            for name, arr in comps.items():
                new_state[name] = new_state[name].at[idx].set(
                    jnp.asarray(np.ascontiguousarray(arr)))
            self.device_state = new_state
            for s_ in slots:
                self._slot_flushed[int(s_)] = 1

    def active_entries(self) -> Iterable[Tuple[Any, Any]]:
        yield from self.slot_index.keys()
        yield from self.host_tier.keys()


class TpuKeyedStateBackend(KeyedStateBackend):
    """Hybrid backend: device slots for DeviceAggregateFunction
    aggregation state, host tables for everything else."""

    name = "tpu"

    def __init__(self, key_group_range: KeyGroupRange, max_parallelism: int,
                 initial_capacity: int = DEFAULT_INITIAL_CAPACITY,
                 microbatch: int = DEFAULT_MICROBATCH,
                 max_device_slots: Optional[int] = None):
        super().__init__(key_group_range, max_parallelism)
        self._tables: Dict[str, StateTable] = {}
        self._device_states: Dict[str, DeviceAggregatingState] = {}
        self.initial_capacity = initial_capacity
        self.microbatch = microbatch
        #: per-state HBM slot budget; beyond it cold entries spill to
        #: host RAM (config key state.backend.tpu.max-device-slots)
        self.max_device_slots = max_device_slots

    def _table(self, name: str) -> StateTable:
        t = self._tables.get(name)
        if t is None:
            t = StateTable()
            self._tables[name] = t
        return t

    # ---- factories --------------------------------------------------
    def create_value_state(self, d: ValueStateDescriptor):
        return HeapValueState(self, d, self._table(d.name))

    def create_list_state(self, d: ListStateDescriptor):
        return HeapListState(self, d, self._table(d.name))

    def create_reducing_state(self, d: ReducingStateDescriptor):
        return HeapReducingState(self, d, self._table(d.name))

    def create_aggregating_state(self, d: AggregatingStateDescriptor):
        if isinstance(d.aggregate_function, DeviceAggregateFunction):
            st = DeviceAggregatingState(
                self, d, self.initial_capacity, self.microbatch,
                max_device_slots=self.max_device_slots)
            self._device_states[d.name] = st
            # a restore() that ran before this descriptor was bound
            # parked this state's accumulators in a host table (it had
            # no way to know they were device-resident) — migrate them
            leftover = self._tables.pop(d.name, None)
            if leftover is not None:
                specs = d.aggregate_function.state_specs()
                entries = []
                for namespace, key, value in leftover.entries():
                    row = {n: np.asarray(value[n]).reshape(specs[n].shape)
                           for n in specs}
                    entries.append((key, namespace, row))
                st.restore_entries(entries)
            return st
        return HeapAggregatingState(self, d, self._table(d.name))

    def create_folding_state(self, d: FoldingStateDescriptor):
        return HeapFoldingState(self, d, self._table(d.name))

    def create_map_state(self, d: MapStateDescriptor):
        return HeapMapState(self, d, self._table(d.name))

    # ---- introspection ----------------------------------------------
    def get_keys(self, state_name: str, namespace) -> Iterable[Any]:
        if state_name in self._device_states:
            return [k for (k, ns) in self._device_states[state_name].active_entries()
                    if ns == namespace]
        t = self._tables.get(state_name)
        return list(t.keys(namespace)) if t else []

    def accounting_breakdown(self) -> Dict[str, Dict[int, dict]]:
        """Per-(state, key-group) rows/bytes/namespaces: host tables
        count standalone pickled lengths (the snapshot's per-row tier);
        device states count active entries (HBM slots + host spill,
        INCLUDING rows still pending in the micro-batch ring — the
        slot index is updated at add time) at the per-row component
        width from ``agg.state_specs()``, which equals the snapshot's
        gathered column nbytes — no D2H transfer needed."""
        from flink_tpu.core.keygroups import assign_key_groups_np, \
            stable_hashes_np
        from flink_tpu.state.introspect import pickled_len
        out: Dict[str, Dict[int, dict]] = {}
        mp = self.max_parallelism

        def entry(per_kg, kg):
            e = per_kg.get(kg)
            if e is None:
                e = per_kg[kg] = {"rows": 0, "bytes": 0, "_ns": set()}
            return e

        for name, table in self._tables.items():
            per_kg = out.setdefault(name, {})
            for namespace, key, value in table.entries():
                kg = assign_to_key_group(key, mp)
                e = entry(per_kg, kg)
                e["rows"] += 1
                e["bytes"] += pickled_len(value)
                e["_ns"].add(namespace)
        for name, dstate in self._device_states.items():
            per_kg = out.setdefault(name, {})
            specs = dstate.agg.state_specs()
            row_bytes = sum(
                int(np.prod(spec.shape, dtype=np.int64))
                * np.dtype(spec.dtype).itemsize
                for spec in specs.values())
            entries = list(dstate.active_entries())
            if not entries:
                continue
            keys = [k for k, _ns in entries]
            kgs = assign_key_groups_np(stable_hashes_np(keys), mp)
            for (key, namespace), kg in zip(entries, kgs):
                e = entry(per_kg, int(kg))
                e["rows"] += 1
                e["bytes"] += row_bytes
                e["_ns"].add(namespace)
        return {name: {kg: {"rows": e["rows"], "bytes": e["bytes"],
                            "namespaces": len(e["_ns"])}
                       for kg, e in per_kg.items()}
                for name, per_kg in out.items()}

    # ---- snapshot / restore -----------------------------------------
    def snapshot(self) -> KeyedStateSnapshot:
        """v2 columnar chunk format: device states serialize as ONE
        gather + one column per component per key group (key and
        namespace columns through the wire codec), host-table entries
        stay per-row."""
        from flink_tpu.state.backend import encode_obj_column
        per_kg_rows: Dict[int, list] = defaultdict(list)
        per_kg_cols: Dict[int, Dict[str, list]] = defaultdict(dict)
        for name, table in self._tables.items():
            for namespace, key, value in table.entries():
                kg = assign_to_key_group(key, self.max_parallelism)
                per_kg_rows[kg].append((name, namespace, key, value))
                STATE_STATS.snapshot_rows += 1
        for name, dstate in self._device_states.items():
            for kg, (keys, nss, comps) in dstate.snapshot_columns().items():
                per_kg_cols[kg].setdefault(name, []).append({
                    "keys": encode_obj_column(keys),
                    "ns": ("col", encode_obj_column(nss)),
                    "comps": comps,
                    "kind": "acc",
                })
                STATE_STATS.snapshot_columns += len(keys)
        chunks = {}
        for kg in set(per_kg_rows) | set(per_kg_cols):
            chunks[kg] = pickle.dumps(
                {"v": 2, "rows": per_kg_rows.get(kg, []),
                 "cols": per_kg_cols.get(kg, {})},
                protocol=pickle.HIGHEST_PROTOCOL)
        return KeyedStateSnapshot(
            chunks,
            meta={"backend": self.name,
                  "max_parallelism": self.max_parallelism,
                  "serializers": self.serializer_config_snapshots()},
        )

    def _restore_norm_rows(self, rows, pending_device) -> None:
        """Per-row entries: values in the scalar-twin accumulator
        format (dict of per-component arrays, see
        DeviceAggregateFunction.create_accumulator) whose state is
        device-resident here normalize to device rows; everything else
        goes to host tables."""
        for name, namespace, key, value in rows:
            dstate = self._device_states.get(name)
            if dstate is not None and isinstance(value, dict):
                specs = dstate.agg.state_specs()
                row = {n: np.asarray(value[n]).reshape(specs[n].shape)
                       for n in specs}
                pending_device[name].append((key, namespace, row))
            else:
                self._table(name).put(key, namespace, value)

    def _restore_v2_cols(self, cols: dict, pending_device,
                         pending_cols) -> None:
        from flink_tpu.state.backend import decode_obj_column
        for name, blocks in cols.items():
            for block in blocks:
                comps = block["comps"]
                n = len(next(iter(comps.values()))) if comps else 0
                keys = decode_obj_column(block["keys"], n)
                ns_field = block["ns"]
                namespaces = ([ns_field[1]] * n if ns_field[0] == "const"
                              else decode_obj_column(ns_field[1], n))
                if block["kind"] == "scalar":
                    # heap column block: plain scalar values
                    table = self._table(name)
                    vals = comps["value"]
                    for k, ns, v in zip(keys, namespaces, vals):
                        table.put(k, ns, v.item())
                    continue
                pending_cols.setdefault(name, []).append(
                    (keys, namespaces, comps))

    def restore(self, snapshots) -> None:
        self.check_serializer_compatibility(snapshots)
        # clear in place: bound state objects hold table references
        for table in self._tables.values():
            table.clear_all()
        for dstate in self._device_states.values():
            # reset device state in place (descriptor bindings survive);
            # pending micro-batches are pre-failure writes — drop them,
            # the restored checkpoint supersedes them
            dstate.device_state = dstate.agg.init_state(dstate.capacity)
            dstate.slot_index.clear()
            dstate.slot_meta = [None] * dstate.capacity
            dstate._free = list(range(dstate.capacity - 1, -1, -1))
            dstate._slot_flushed = bytearray(dstate.capacity)
            dstate.host_tier.clear()
            dstate._pending_slots.clear()
            dstate._pending_values.clear()
            dstate._pending_hi.clear()
            dstate._pending_lo.clear()
        pending_device: Dict[str, list] = defaultdict(list)
        pending_cols: Dict[str, list] = {}
        for snap in snapshots:
            for kg, blob in snap.blobs():
                if not self.key_group_range.contains(kg):
                    continue
                chunk = pickle.loads(blob)
                if isinstance(chunk, list):
                    # chunk written by the legacy heap backend
                    self._restore_norm_rows(chunk, pending_device)
                    continue
                if chunk.get("v") == 2:
                    self._restore_norm_rows(chunk["rows"], pending_device)
                    self._restore_v2_cols(chunk["cols"], pending_device,
                                          pending_cols)
                    continue
                for name, namespace, key, value in chunk["host"]:
                    self._table(name).put(key, namespace, value)
                for name, entries in chunk["device"].items():
                    pending_device[name].extend(entries)
        for name, blocks in pending_cols.items():
            dstate = self._device_states.get(name)
            if dstate is not None:
                for keys, namespaces, comps in blocks:
                    dstate.restore_columns(keys, namespaces, comps)
            else:
                # descriptor not bound yet: park per-row accumulator
                # dicts in a host table; create_aggregating_state's
                # migration lifts them onto the device at bind time
                table = self._table(name)
                for keys, namespaces, comps in blocks:
                    for i in range(len(keys)):
                        row = {c: np.array(arr[i])
                               for c, arr in comps.items()}
                        table.put(keys[i], namespaces[i], row)
        for name, entries in pending_device.items():
            dstate = self._device_states.get(name)
            if dstate is not None:
                dstate.restore_entries(entries)
            else:
                # descriptor not bound yet (standard recovery order is
                # restore-then-open): park rows in a host table; the
                # migration in create_aggregating_state picks them up
                table = self._table(name)
                for key, namespace, row in entries:
                    table.put(key, namespace, row)
        self._apply_restored_migrations()

    def _migrate_state_values(self, descriptor, serializer,
                              restored_cfg) -> None:
        """Value migration for HOST-table states (the same pass as the
        heap backend); device-resident states are numeric accumulator
        rows the record serializers never apply to, so only live host
        tables migrate."""
        from flink_tpu.state.backend import migrate_table_values
        name = descriptor.name
        table = self._tables.get(name)
        if table is None or name in self._device_states:
            return
        migrate_table_values(table, descriptor, serializer,
                             restored_cfg)

    def flush_all(self) -> None:
        """Barrier hook: push all pending micro-batches to HBM before a
        snapshot is taken (SURVEY.md §7 hard-parts list)."""
        for dstate in self._device_states.values():
            dstate._flush()

    def dispose(self) -> None:
        super().dispose()
        self._tables.clear()
        self._device_states.clear()
