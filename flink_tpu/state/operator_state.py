"""Operator (non-keyed) state backend.

Re-designs flink-runtime/.../state/DefaultOperatorStateBackend.java:
per-operator named list states (Kafka offsets etc.) with two
redistribution modes on rescale, plus broadcast state
(ref: HeapBroadcastState.java).

Redistribution (ref: OperatorStateHandle.Mode):
  SPLIT_DISTRIBUTE — list items are round-robined across new subtasks
  UNION            — every subtask gets the full concatenated list
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, Iterable, List, Tuple


SPLIT_DISTRIBUTE = "split"
UNION = "union"


class OperatorListState:
    """(ref: PartitionableListState)"""

    def __init__(self, name: str, mode: str = SPLIT_DISTRIBUTE):
        self.name = name
        self.mode = mode
        self._items: List[Any] = []

    def get(self) -> List[Any]:
        return list(self._items)

    def add(self, value) -> None:
        self._items.append(value)

    def add_all(self, values: Iterable[Any]) -> None:
        self._items.extend(values)

    def update(self, values: Iterable[Any]) -> None:
        self._items = list(values)

    def clear(self) -> None:
        self._items.clear()


class BroadcastState:
    """Keyed map replicated identically on every subtask
    (ref: HeapBroadcastState.java)."""

    def __init__(self, name: str):
        self.name = name
        self._map: Dict[Any, Any] = {}

    def get(self, key):
        return self._map.get(key)

    def put(self, key, value) -> None:
        self._map[key] = value

    def put_all(self, mapping: dict) -> None:
        self._map.update(mapping)

    def remove(self, key) -> None:
        self._map.pop(key, None)

    def contains(self, key) -> bool:
        return key in self._map

    def entries(self):
        return list(self._map.items())

    def keys(self):
        return list(self._map.keys())

    def values(self):
        return list(self._map.values())

    def immutable_entries(self):
        return tuple(self._map.items())

    def clear(self) -> None:
        self._map.clear()


class OperatorStateSnapshot:
    __slots__ = ("list_states", "broadcast_states")

    def __init__(self, list_states: Dict[str, Tuple[str, bytes]],
                 broadcast_states: Dict[str, bytes]):
        #: name → (mode, pickled items)
        self.list_states = list_states
        self.broadcast_states = broadcast_states

    @staticmethod
    def redistribute(snapshots: List["OperatorStateSnapshot"],
                     new_parallelism: int) -> List["OperatorStateSnapshot"]:
        """Re-split all old subtasks' operator state across
        `new_parallelism` new subtasks (ref:
        RoundRobinOperatorStateRepartitioner.java)."""
        all_items: Dict[str, Tuple[str, List[Any]]] = {}
        bcast: Dict[str, bytes] = {}
        for snap in snapshots:
            for name, (mode, blob) in snap.list_states.items():
                items = pickle.loads(blob)
                if name not in all_items:
                    all_items[name] = (mode, [])
                all_items[name][1].extend(items)
            for name, blob in snap.broadcast_states.items():
                bcast[name] = blob  # identical on every subtask
        outs: List[OperatorStateSnapshot] = []
        for i in range(new_parallelism):
            lists: Dict[str, Tuple[str, bytes]] = {}
            for name, (mode, items) in all_items.items():
                if mode == UNION:
                    part = items
                else:
                    part = items[i::new_parallelism]
                lists[name] = (mode, pickle.dumps(part))
            outs.append(OperatorStateSnapshot(dict(lists), dict(bcast)))
        return outs


class OperatorStateBackend:
    def __init__(self):
        self._list_states: Dict[str, OperatorListState] = {}
        self._broadcast_states: Dict[str, BroadcastState] = {}

    def get_list_state(self, name: str) -> OperatorListState:
        return self._get_list(name, SPLIT_DISTRIBUTE)

    def get_union_list_state(self, name: str) -> OperatorListState:
        """(ref: getUnionListState — Kafka consumer offsets use this)"""
        return self._get_list(name, UNION)

    def _get_list(self, name: str, mode: str) -> OperatorListState:
        st = self._list_states.get(name)
        if st is None:
            st = OperatorListState(name, mode)
            self._list_states[name] = st
        elif st.mode != mode:
            raise ValueError(
                f"operator state {name!r} already registered with mode {st.mode}")
        return st

    def get_broadcast_state(self, name: str) -> BroadcastState:
        st = self._broadcast_states.get(name)
        if st is None:
            st = BroadcastState(name)
            self._broadcast_states[name] = st
        return st

    def snapshot(self) -> OperatorStateSnapshot:
        return OperatorStateSnapshot(
            {name: (st.mode, pickle.dumps(st.get()))
             for name, st in self._list_states.items()},
            {name: pickle.dumps(st.entries())
             for name, st in self._broadcast_states.items()},
        )

    def restore(self, snapshot: OperatorStateSnapshot) -> None:
        for name, (mode, blob) in snapshot.list_states.items():
            self._get_list(name, mode).update(pickle.loads(blob))
        for name, blob in snapshot.broadcast_states.items():
            st = self.get_broadcast_state(name)
            st.clear()
            st.put_all(dict(pickle.loads(blob)))

    def dispose(self) -> None:
        self._list_states.clear()
        self._broadcast_states.clear()
