"""Heap (host-dict) keyed-state backend — the reference semantics twin.

Re-designs flink-runtime/.../state/heap/HeapKeyedStateBackend.java:90
and the Heap*State family (HeapValueState, HeapListState,
HeapAggregatingState.java:80-89 …).  A `StateTable` here is
``{namespace: {key: value}}`` per registered state; the reference's
CopyOnWriteStateTable async-snapshot machinery is unnecessary because
snapshots serialize from a quiesced table (the streaming runtime
snapshots between micro-batches, under the task's single-owner loop —
see SURVEY.md §5 race-detection note).

This backend exists for (a) differential testing of the TPU backend,
(b) states whose values are arbitrary Python objects, and (c) the
`state.backend: heap` config (ref names `jobmanager`/`filesystem`,
StateBackendLoader.java:92-109).
"""

from __future__ import annotations

import pickle
from collections import defaultdict
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from flink_tpu.core.keygroups import (
    KeyGroupRange,
    assign_key_groups_np,
    assign_to_key_group,
    stable_hashes_np,
)
from flink_tpu.core.state import (
    AggregatingState,
    AggregatingStateDescriptor,
    FoldingState,
    FoldingStateDescriptor,
    ListState,
    ListStateDescriptor,
    MapState,
    MapStateDescriptor,
    ReducingState,
    ReducingStateDescriptor,
    StateDescriptor,
    ValueState,
    ValueStateDescriptor,
)
from flink_tpu.state.backend import (
    VOID_NAMESPACE,
    KeyedStateBackend,
    KeyedStateSnapshot,
)


class StateTable:
    """{namespace: {key: value}} (ref: heap/StateTable.java)."""

    __slots__ = ("by_namespace",)

    def __init__(self):
        self.by_namespace: Dict[Any, Dict[Any, Any]] = {}

    def get(self, key, namespace, default=None):
        ns = self.by_namespace.get(namespace)
        if ns is None:
            return default
        return ns.get(key, default)

    def put(self, key, namespace, value) -> None:
        self.by_namespace.setdefault(namespace, {})[key] = value

    def remove(self, key, namespace) -> None:
        ns = self.by_namespace.get(namespace)
        if ns is not None:
            ns.pop(key, None)
            if not ns:
                del self.by_namespace[namespace]

    def contains(self, key, namespace) -> bool:
        ns = self.by_namespace.get(namespace)
        return ns is not None and key in ns

    def keys(self, namespace) -> Iterable[Any]:
        return self.by_namespace.get(namespace, {}).keys()

    def entries(self) -> Iterable[Tuple[Any, Any, Any]]:
        for namespace, by_key in self.by_namespace.items():
            for key, value in by_key.items():
                yield namespace, key, value

    def is_empty(self) -> bool:
        return not self.by_namespace

    def clear_all(self) -> None:
        # in place: bound state objects hold table references
        self.by_namespace.clear()


class _ColumnBlock:
    """One namespace's rows in a ColumnStateTable: a key→slot index
    plus either a typed numpy value column (int64/float64, grown by
    doubling, swap-remove on delete) or — once any value fails the
    strict type check — a boxed python list holding the exact objects.
    Demotion converts losslessly (`.item()` round-trips int64→int and
    float64→float bit-exactly, the same conversion reads already did).
    """

    __slots__ = ("index", "keys", "vals", "boxed")

    def __init__(self):
        self.index: Dict[Any, int] = {}
        self.keys: List[Any] = []
        self.vals: Optional[np.ndarray] = None
        self.boxed: Optional[list] = None

    def demote(self) -> None:
        if self.boxed is None:
            n = len(self.keys)
            self.boxed = ([] if self.vals is None
                          else [v.item() for v in self.vals[:n]])
            self.vals = None

    def _coltype(self, value):
        if type(value) is int:
            return np.int64
        if type(value) is float:
            return np.float64
        return None

    def put(self, key, value) -> None:
        slot = self.index.get(key)
        if self.boxed is None:
            dtype = self._coltype(value)
            if dtype is None or (self.vals is not None
                                 and self.vals.dtype != dtype):
                self.demote()
            elif self.vals is None:
                self.vals = np.empty(8, dtype)
        if self.boxed is not None:
            if slot is None:
                self.index[key] = len(self.keys)
                self.keys.append(key)
                self.boxed.append(value)
            else:
                self.boxed[slot] = value
            return
        if slot is None:
            slot = len(self.keys)
            if slot == len(self.vals):
                grown = np.empty(slot * 2, self.vals.dtype)
                grown[:slot] = self.vals
                self.vals = grown
            self.index[key] = slot
            self.keys.append(key)
        try:
            self.vals[slot] = value
        except OverflowError:
            self.demote()
            self.boxed[slot] = value

    def get(self, key, default=None):
        slot = self.index.get(key)
        if slot is None:
            return default
        if self.boxed is not None:
            return self.boxed[slot]
        return self.vals[slot].item()

    def remove(self, key) -> None:
        slot = self.index.pop(key, None)
        if slot is None:
            return
        last = len(self.keys) - 1
        if slot != last:
            moved = self.keys[last]
            self.keys[slot] = moved
            self.index[moved] = slot
            if self.boxed is not None:
                self.boxed[slot] = self.boxed[last]
            else:
                self.vals[slot] = self.vals[last]
        self.keys.pop()
        if self.boxed is not None:
            self.boxed.pop()

    def values_list(self) -> list:
        n = len(self.keys)
        if self.boxed is not None:
            return list(self.boxed)
        return [] if self.vals is None else [v.item() for v in self.vals[:n]]


class ColumnStateTable:
    """Numpy-aware StateTable twin: `{namespace: _ColumnBlock}`.

    Same interface as StateTable (so bound Heap*State objects and the
    serializer-migration pass work unchanged) but scalar int/float
    values live in typed numpy columns — snapshots serialize them as
    ONE buffer per (state, namespace, key-group) with a vectorized
    key-group split, and restores bulk-load whole columns.  Opaque
    values transparently demote the affected namespace's block to a
    boxed list with identical semantics.
    """

    __slots__ = ("blocks",)

    def __init__(self):
        self.blocks: Dict[Any, _ColumnBlock] = {}

    def get(self, key, namespace, default=None):
        b = self.blocks.get(namespace)
        if b is None:
            return default
        return b.get(key, default)

    def put(self, key, namespace, value) -> None:
        b = self.blocks.get(namespace)
        if b is None:
            b = self.blocks[namespace] = _ColumnBlock()
        b.put(key, value)

    def remove(self, key, namespace) -> None:
        b = self.blocks.get(namespace)
        if b is not None:
            b.remove(key)
            if not b.keys:
                del self.blocks[namespace]

    def contains(self, key, namespace) -> bool:
        b = self.blocks.get(namespace)
        return b is not None and key in b.index

    def keys(self, namespace) -> Iterable[Any]:
        b = self.blocks.get(namespace)
        return list(b.keys) if b is not None else []

    def entries(self) -> Iterable[Tuple[Any, Any, Any]]:
        for namespace, b in self.blocks.items():
            for key, value in zip(list(b.keys), b.values_list()):
                yield namespace, key, value

    def is_empty(self) -> bool:
        return not self.blocks

    def clear_all(self) -> None:
        self.blocks.clear()

    def bulk_load(self, namespace, keys, vals: np.ndarray) -> None:
        """Restore fast path: append a whole decoded column."""
        b = self.blocks.get(namespace)
        if b is None and len(keys):
            b = self.blocks[namespace] = _ColumnBlock()
            b.keys = list(keys)
            b.index = {k: i for i, k in enumerate(b.keys)}
            b.vals = np.array(vals)
            return
        for k, v in zip(keys, vals):
            b.put(k, v.item())

    def column_blocks(self):
        """Snapshot view: yields (namespace, keys, vals_ndarray|None,
        boxed_list|None) per namespace block."""
        for namespace, b in self.blocks.items():
            n = len(b.keys)
            if b.boxed is not None:
                yield namespace, b.keys, None, b.boxed
            else:
                vals = b.vals[:n] if b.vals is not None else np.empty(0)
                yield namespace, b.keys, vals, None


def split_column_by_key_group(keys, max_parallelism: int):
    """ONE vectorized hash pass: key column → ordered per-key-group
    index segments.  Yields (key_group, row_index_array); row order
    within a group preserves column order (stable sort)."""
    n = len(keys)
    if n == 0:
        return
    kgs = assign_key_groups_np(stable_hashes_np(keys), max_parallelism)
    order = np.argsort(kgs, kind="stable")
    sorted_kgs = kgs[order]
    bounds = np.nonzero(np.diff(sorted_kgs))[0] + 1
    start = 0
    for end in list(bounds) + [n]:
        yield int(sorted_kgs[start]), order[start:end]
        start = end


#: sentinel for "no namespace seen yet" in the batched read's
#: last-block cache (None and () are both real namespaces)
_NO_NAMESPACE = object()


class _AbstractHeapState:
    def __init__(self, backend: "HeapKeyedStateBackend", descriptor: StateDescriptor,
                 table: StateTable):
        self._backend = backend
        self._descriptor = descriptor
        self._table = table
        self._namespace = VOID_NAMESPACE

    def set_current_namespace(self, namespace) -> None:
        self._namespace = namespace

    @property
    def _key(self):
        return self._backend.current_key

    def clear(self) -> None:
        self._table.remove(self._key, self._namespace)

    def clear_batch(self, keys, namespace, namespaces=None) -> None:
        """Batched twin of clear(): one table.remove per row, no
        backend key-context churn (the fire path's one-call cleanup)."""
        remove = self._table.remove
        if namespaces is None:
            for k in keys:
                remove(k, namespace)
        else:
            for i, k in enumerate(keys):
                remove(k, namespaces[i])

    def _get_rows_batch(self, keys, namespace, namespaces) -> list:
        """Raw stored values for many (key, namespace) rows — COLUMN-
        DIRECT when the table is a ColumnStateTable: one block fetch
        per distinct namespace, values read straight out of the typed
        numpy column (the identical .item() boxing scalar reads
        perform).  Absent rows are None."""
        n = len(keys)
        out: list = [None] * n
        blocks = getattr(self._table, "blocks", None)
        if blocks is None:
            get = self._table.get
            if namespaces is None:
                for i in range(n):
                    out[i] = get(keys[i], namespace)
            else:
                for i in range(n):
                    out[i] = get(keys[i], namespaces[i])
            return out
        if namespaces is None:
            b = blocks.get(namespace)
            if b is None:
                return out
            idx, boxed, vals = b.index, b.boxed, b.vals
            for i in range(n):
                slot = idx.get(keys[i])
                if slot is not None:
                    out[i] = (boxed[slot] if boxed is not None
                              else vals[slot].item())
            return out
        # per-row namespaces arrive grouped-by-window from the timer
        # sweep, so caching the last block makes this one dict fetch
        # per distinct window, not per row
        cur: Any = _NO_NAMESPACE
        b = None
        for i in range(n):
            ns = namespaces[i]
            if ns != cur:
                cur = ns
                b = blocks.get(ns)
            if b is None:
                continue
            slot = b.index.get(keys[i])
            if slot is not None:
                out[i] = (b.boxed[slot] if b.boxed is not None
                          else b.vals[slot].item())
        return out

    @staticmethod
    def _group_rows(keys, namespace, namespaces):
        """Group row indices by (key, namespace), preserving row order
        within each group — the invariant that keeps a batched fold
        bit-identical to the scalar add loop for ANY fold function
        (float reduction order included)."""
        groups: Dict[Any, List[int]] = {}
        if namespaces is None:
            for i, k in enumerate(keys):
                groups.setdefault((k, namespace), []).append(i)
        else:
            for i, k in enumerate(keys):
                groups.setdefault((k, namespaces[i]), []).append(i)
        return groups


class HeapValueState(_AbstractHeapState, ValueState):
    def value(self):
        v = self._table.get(self._key, self._namespace)
        if v is None:
            return self._descriptor.get_default_value()
        return v

    def update(self, value) -> None:
        if value is None:
            self.clear()
        else:
            self._table.put(self._key, self._namespace, value)


class HeapListState(_AbstractHeapState, ListState):
    def get(self):
        v = self._table.get(self._key, self._namespace)
        return list(v) if v else None

    def add(self, value) -> None:
        v = self._table.get(self._key, self._namespace)
        if v is None:
            self._table.put(self._key, self._namespace, [value])
        else:
            v.append(value)

    def add_all(self, values) -> None:
        values = list(values)
        if not values:
            return
        v = self._table.get(self._key, self._namespace)
        if v is None:
            self._table.put(self._key, self._namespace, values)
        else:
            v.extend(values)

    def update(self, values) -> None:
        values = list(values)
        if values:
            self._table.put(self._key, self._namespace, values)
        else:
            self.clear()

    def add_batch(self, keys, namespace, values, namespaces=None) -> None:
        """Batched twin of add(): one table get/put per (key, ns)
        group, elements appended in row order."""
        for (k, ns), idxs in self._group_rows(keys, namespace,
                                              namespaces).items():
            cur = self._table.get(k, ns)
            rows = [values[i] for i in idxs]
            if cur is None:
                self._table.put(k, ns, rows)
            else:
                cur.extend(rows)

    def get_batch(self, keys, namespace, namespaces=None):
        """Batched twin of get(): one table read per row, contents
        copied exactly as get() does (empty lists read as absent)."""
        rows = self._get_rows_batch(keys, namespace, namespaces)
        found = np.fromiter((bool(v) for v in rows), bool, len(rows))
        return [list(v) if v else None for v in rows], found

    def merge_namespaces(self, target, sources) -> None:
        """(ref: InternalMergingState#mergeNamespaces via
        HeapListState — concatenation)."""
        merged = self._table.get(self._key, target) or []
        for src in sources:
            v = self._table.get(self._key, src)
            if v:
                merged.extend(v)
            self._table.remove(self._key, src)
        if merged:
            self._table.put(self._key, target, merged)


class HeapReducingState(_AbstractHeapState, ReducingState):
    def __init__(self, backend, descriptor: ReducingStateDescriptor, table):
        super().__init__(backend, descriptor, table)
        self._reduce = descriptor.reduce_function.reduce

    def get(self):
        return self._table.get(self._key, self._namespace)

    def add(self, value) -> None:
        cur = self._table.get(self._key, self._namespace)
        self._table.put(self._key, self._namespace,
                        value if cur is None else self._reduce(cur, value))

    def add_batch(self, keys, namespace, values, namespaces=None) -> None:
        """Batched twin of add(): grouped in-order fold — bit-equal to
        the scalar loop for any reduce function."""
        reduce = self._reduce
        for (k, ns), idxs in self._group_rows(keys, namespace,
                                              namespaces).items():
            cur = self._table.get(k, ns)
            for i in idxs:
                v = values[i]
                cur = v if cur is None else reduce(cur, v)
            self._table.put(k, ns, cur)

    def get_batch(self, keys, namespace, namespaces=None):
        """Batched twin of get(): direct column reads (the reduced
        value IS the stored value), no key-context churn."""
        rows = self._get_rows_batch(keys, namespace, namespaces)
        found = np.fromiter((v is not None for v in rows), bool,
                            len(rows))
        return rows, found

    def merge_namespaces(self, target, sources) -> None:
        merged = self._table.get(self._key, target)
        for src in sources:
            v = self._table.get(self._key, src)
            self._table.remove(self._key, src)
            if v is not None:
                merged = v if merged is None else self._reduce(merged, v)
        if merged is not None:
            self._table.put(self._key, target, merged)


class HeapAggregatingState(_AbstractHeapState, AggregatingState):
    """add → agg.add(value, acc) (ref: HeapAggregatingState.java:80-89)."""

    def __init__(self, backend, descriptor: AggregatingStateDescriptor, table):
        super().__init__(backend, descriptor, table)
        self._agg = descriptor.aggregate_function

    def get(self):
        acc = self._table.get(self._key, self._namespace)
        if acc is None:
            return None
        return self._agg.get_result(acc)

    def get_accumulator(self):
        return self._table.get(self._key, self._namespace)

    def add(self, value) -> None:
        acc = self._table.get(self._key, self._namespace)
        if acc is None:
            acc = self._agg.create_accumulator()
        acc = self._agg.add(value, acc)
        self._table.put(self._key, self._namespace, acc)

    def add_batch(self, keys, namespace, values, namespaces=None) -> None:
        """Batched twin of add(): grouped in-order accumulator fold."""
        agg = self._agg
        for (k, ns), idxs in self._group_rows(keys, namespace,
                                              namespaces).items():
            acc = self._table.get(k, ns)
            for i in idxs:
                if acc is None:
                    acc = agg.create_accumulator()
                acc = agg.add(values[i], acc)
            self._table.put(k, ns, acc)

    def get_batch(self, keys, namespace, namespaces=None):
        """Batched twin of get(): accumulators read column-direct,
        finalized per row through agg.get_result in row order — the
        exact scalar result for any aggregate function."""
        accs = self._get_rows_batch(keys, namespace, namespaces)
        get_result = self._agg.get_result
        found = np.fromiter((a is not None for a in accs), bool,
                            len(accs))
        return [None if a is None else get_result(a) for a in accs], found

    def merge_namespaces(self, target, sources) -> None:
        merged = self._table.get(self._key, target)
        for src in sources:
            v = self._table.get(self._key, src)
            self._table.remove(self._key, src)
            if v is not None:
                merged = v if merged is None else self._agg.merge(merged, v)
        if merged is not None:
            self._table.put(self._key, target, merged)


class HeapFoldingState(_AbstractHeapState, FoldingState):
    def __init__(self, backend, descriptor: FoldingStateDescriptor, table):
        super().__init__(backend, descriptor, table)
        self._fold = descriptor.fold_function

    def get(self):
        return self._table.get(self._key, self._namespace)

    def add(self, value) -> None:
        acc = self._table.get(self._key, self._namespace)
        if acc is None:
            acc = self._descriptor.get_default_value()
        self._table.put(self._key, self._namespace, self._fold(acc, value))


class HeapMapState(_AbstractHeapState, MapState):
    def _map(self, create=False) -> Optional[dict]:
        m = self._table.get(self._key, self._namespace)
        if m is None and create:
            m = {}
            self._table.put(self._key, self._namespace, m)
        return m

    def get(self, key):
        m = self._map()
        return None if m is None else m.get(key)

    def put(self, key, value) -> None:
        self._map(create=True)[key] = value

    def put_all(self, mapping: dict) -> None:
        if mapping:
            self._map(create=True).update(mapping)

    def remove(self, key) -> None:
        m = self._map()
        if m is not None:
            m.pop(key, None)
            if not m:
                self.clear()

    def contains(self, key) -> bool:
        m = self._map()
        return m is not None and key in m

    def entries(self):
        m = self._map()
        return list(m.items()) if m else []

    def keys(self):
        m = self._map()
        return list(m.keys()) if m else []

    def values(self):
        m = self._map()
        return list(m.values()) if m else []

    def is_empty(self) -> bool:
        m = self._map()
        return not m


class HeapKeyedStateBackend(KeyedStateBackend):
    """All registered states as host dict tables."""

    name = "heap"

    def __init__(self, key_group_range: KeyGroupRange, max_parallelism: int):
        super().__init__(key_group_range, max_parallelism)
        self._tables: Dict[str, Any] = {}

    def _table(self, name: str, columnar: bool = False):
        """A name's table; `columnar=True` requests the numpy-aware
        column table for scalar-valued states (reducing/aggregating) —
        an existing table of either kind is always reused (bound state
        objects and restores may have created it first; the interfaces
        are identical)."""
        t = self._tables.get(name)
        if t is None:
            t = ColumnStateTable() if columnar else StateTable()
            self._tables[name] = t
        return t

    # ---- factories --------------------------------------------------
    def create_value_state(self, d: ValueStateDescriptor):
        return HeapValueState(self, d, self._table(d.name))

    def create_list_state(self, d: ListStateDescriptor):
        return HeapListState(self, d, self._table(d.name))

    def create_reducing_state(self, d: ReducingStateDescriptor):
        return HeapReducingState(self, d, self._table(d.name, columnar=True))

    def create_aggregating_state(self, d: AggregatingStateDescriptor):
        return HeapAggregatingState(self, d,
                                    self._table(d.name, columnar=True))

    def create_folding_state(self, d: FoldingStateDescriptor):
        return HeapFoldingState(self, d, self._table(d.name))

    def create_map_state(self, d: MapStateDescriptor):
        return HeapMapState(self, d, self._table(d.name))

    # ---- introspection ----------------------------------------------
    def get_keys(self, state_name: str, namespace) -> Iterable[Any]:
        t = self._tables.get(state_name)
        return list(t.keys(namespace)) if t else []

    def accounting_breakdown(self) -> Dict[str, Dict[int, dict]]:
        """Per-(state, key-group) rows/bytes/namespaces over the live
        tables, via the SAME key-group split and bytes definition the
        snapshot serializer uses: typed column segments count
        rows × itemsize (== the chunk's value buffer nbytes), boxed and
        plain-table rows count their standalone pickled length."""
        from flink_tpu.state.introspect import pickled_len
        out: Dict[str, Dict[int, dict]] = {}
        mp = self.max_parallelism

        def entry(per_kg, kg):
            e = per_kg.get(kg)
            if e is None:
                e = per_kg[kg] = {"rows": 0, "bytes": 0, "_ns": set()}
            return e

        for name, table in self._tables.items():
            per_kg = out.setdefault(name, {})
            if isinstance(table, ColumnStateTable):
                for namespace, bkeys, vals, boxed in table.column_blocks():
                    if vals is None:
                        for key, value in zip(bkeys, boxed):
                            kg = assign_to_key_group(key, mp)
                            e = entry(per_kg, kg)
                            e["rows"] += 1
                            e["bytes"] += pickled_len(value)
                            e["_ns"].add(namespace)
                        continue
                    itemsize = vals.dtype.itemsize
                    for kg, idx in split_column_by_key_group(bkeys, mp):
                        e = entry(per_kg, kg)
                        e["rows"] += len(idx)
                        e["bytes"] += len(idx) * itemsize
                        e["_ns"].add(namespace)
            else:
                for namespace, key, value in table.entries():
                    kg = assign_to_key_group(key, mp)
                    e = entry(per_kg, kg)
                    e["rows"] += 1
                    e["bytes"] += pickled_len(value)
                    e["_ns"].add(namespace)
        return {name: {kg: {"rows": e["rows"], "bytes": e["bytes"],
                            "namespaces": len(e["_ns"])}
                       for kg, e in per_kg.items()}
                for name, per_kg in out.items()}

    def _migrate_state_values(self, descriptor, serializer,
                              restored_cfg) -> None:
        """Rewrite restored table values through the serializer's
        migration hook (heap values are live objects, so migration is
        an in-place, state-TYPE-aware table pass)."""
        from flink_tpu.state.backend import migrate_table_values
        table = self._tables.get(descriptor.name)
        if table is None:
            return
        migrate_table_values(table, descriptor, serializer,
                             restored_cfg)

    # ---- snapshot / restore -----------------------------------------
    def snapshot(self) -> KeyedStateSnapshot:
        """Serialize state into per-key-group chunks (ref:
        HeapKeyedStateBackend snapshot :289-420, key-grouped
        writeStateTable loop) — v2 columnar chunk format: column tables
        serialize each namespace block as ONE key column (wire-codec
        encoded) + ONE numpy value buffer, the key-group split done in
        one vectorized hash pass; opaque values stay per-row."""
        from flink_tpu.state.backend import encode_obj_column
        from flink_tpu.state.stats import STATE_STATS
        per_kg_rows: Dict[int, List[Tuple[str, Any, Any, Any]]] = \
            defaultdict(list)
        per_kg_cols: Dict[int, Dict[str, list]] = defaultdict(dict)
        for name, table in self._tables.items():
            if isinstance(table, ColumnStateTable):
                for namespace, bkeys, vals, boxed in table.column_blocks():
                    if vals is None:
                        for key, value in zip(bkeys, boxed):
                            kg = assign_to_key_group(key,
                                                     self.max_parallelism)
                            per_kg_rows[kg].append(
                                (name, namespace, key, value))
                            STATE_STATS.snapshot_rows += 1
                        continue
                    for kg, idx in split_column_by_key_group(
                            bkeys, self.max_parallelism):
                        seg_keys = [bkeys[i] for i in idx]
                        per_kg_cols[kg].setdefault(name, []).append({
                            "keys": encode_obj_column(seg_keys),
                            "ns": ("const", namespace),
                            "comps": {"value": vals[idx]},
                            "kind": "scalar",
                        })
                        STATE_STATS.snapshot_columns += len(idx)
            else:
                for namespace, key, value in table.entries():
                    kg = assign_to_key_group(key, self.max_parallelism)
                    per_kg_rows[kg].append((name, namespace, key, value))
                    STATE_STATS.snapshot_rows += 1
        chunks = {}
        for kg in set(per_kg_rows) | set(per_kg_cols):
            chunks[kg] = pickle.dumps(
                {"v": 2, "rows": per_kg_rows.get(kg, []),
                 "cols": per_kg_cols.get(kg, {})},
                protocol=pickle.HIGHEST_PROTOCOL)
        return KeyedStateSnapshot(
            chunks,
            meta={"backend": self.name,
                  "max_parallelism": self.max_parallelism,
                  "serializers": self.serializer_config_snapshots()},
        )

    def _restore_rows(self, rows) -> None:
        for name, namespace, key, value in rows:
            self._table(name).put(key, namespace, value)

    def _restore_cols(self, cols: dict) -> None:
        from flink_tpu.state.backend import decode_obj_column
        for name, blocks in cols.items():
            for block in blocks:
                comps = block["comps"]
                n = len(next(iter(comps.values()))) if comps else 0
                keys = decode_obj_column(block["keys"], n)
                ns_field = block["ns"]
                if block["kind"] == "scalar":
                    vals = comps["value"]
                    table = self._table(name, columnar=True)
                    if (ns_field[0] == "const"
                            and isinstance(table, ColumnStateTable)):
                        table.bulk_load(ns_field[1], keys, vals)
                    else:
                        namespaces = ([ns_field[1]] * n
                                      if ns_field[0] == "const"
                                      else decode_obj_column(ns_field[1], n))
                        for k, ns, v in zip(keys, namespaces, vals):
                            table.put(k, ns, v.item())
                    continue
                # device accumulator block → per-row scalar-twin
                # accumulator dicts, the format HeapAggregatingState
                # operates on (same as the legacy tpu chunk path)
                namespaces = ([ns_field[1]] * n if ns_field[0] == "const"
                              else decode_obj_column(ns_field[1], n))
                table = self._table(name)
                for i in range(n):
                    row = {c: np.array(arr[i]) for c, arr in comps.items()}
                    table.put(keys[i], namespaces[i], row)

    def restore(self, snapshots) -> None:
        self.check_serializer_compatibility(snapshots)
        # clear in place: bound state objects hold table references
        for table in self._tables.values():
            table.clear_all()
        for snap in snapshots:
            for kg, blob in snap.blobs():
                if not self.key_group_range.contains(kg):
                    continue
                chunk = pickle.loads(blob)
                if isinstance(chunk, dict) and chunk.get("v") == 2:
                    self._restore_rows(chunk["rows"])
                    self._restore_cols(chunk["cols"])
                    continue
                if isinstance(chunk, dict):
                    # legacy chunk written by the tpu backend: host
                    # entries plus device rows, which ARE the
                    # scalar-twin accumulator format the heap
                    # aggregating state operates on
                    for name, namespace, key, value in chunk["host"]:
                        self._table(name).put(key, namespace, value)
                    for name, entries in chunk["device"].items():
                        table = self._table(name)
                        for key, namespace, row in entries:
                            table.put(key, namespace, row)
                    continue
                for name, namespace, key, value in chunk:
                    self._table(name).put(key, namespace, value)
        self._apply_restored_migrations()

    def dispose(self) -> None:
        super().dispose()
        self._tables.clear()
