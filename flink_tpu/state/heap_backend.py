"""Heap (host-dict) keyed-state backend — the reference semantics twin.

Re-designs flink-runtime/.../state/heap/HeapKeyedStateBackend.java:90
and the Heap*State family (HeapValueState, HeapListState,
HeapAggregatingState.java:80-89 …).  A `StateTable` here is
``{namespace: {key: value}}`` per registered state; the reference's
CopyOnWriteStateTable async-snapshot machinery is unnecessary because
snapshots serialize from a quiesced table (the streaming runtime
snapshots between micro-batches, under the task's single-owner loop —
see SURVEY.md §5 race-detection note).

This backend exists for (a) differential testing of the TPU backend,
(b) states whose values are arbitrary Python objects, and (c) the
`state.backend: heap` config (ref names `jobmanager`/`filesystem`,
StateBackendLoader.java:92-109).
"""

from __future__ import annotations

import pickle
from collections import defaultdict
from typing import Any, Dict, Iterable, List, Optional, Tuple

from flink_tpu.core.keygroups import KeyGroupRange, assign_to_key_group
from flink_tpu.core.state import (
    AggregatingState,
    AggregatingStateDescriptor,
    FoldingState,
    FoldingStateDescriptor,
    ListState,
    ListStateDescriptor,
    MapState,
    MapStateDescriptor,
    ReducingState,
    ReducingStateDescriptor,
    StateDescriptor,
    ValueState,
    ValueStateDescriptor,
)
from flink_tpu.state.backend import (
    VOID_NAMESPACE,
    KeyedStateBackend,
    KeyedStateSnapshot,
)


class StateTable:
    """{namespace: {key: value}} (ref: heap/StateTable.java)."""

    __slots__ = ("by_namespace",)

    def __init__(self):
        self.by_namespace: Dict[Any, Dict[Any, Any]] = {}

    def get(self, key, namespace, default=None):
        ns = self.by_namespace.get(namespace)
        if ns is None:
            return default
        return ns.get(key, default)

    def put(self, key, namespace, value) -> None:
        self.by_namespace.setdefault(namespace, {})[key] = value

    def remove(self, key, namespace) -> None:
        ns = self.by_namespace.get(namespace)
        if ns is not None:
            ns.pop(key, None)
            if not ns:
                del self.by_namespace[namespace]

    def contains(self, key, namespace) -> bool:
        ns = self.by_namespace.get(namespace)
        return ns is not None and key in ns

    def keys(self, namespace) -> Iterable[Any]:
        return self.by_namespace.get(namespace, {}).keys()

    def entries(self) -> Iterable[Tuple[Any, Any, Any]]:
        for namespace, by_key in self.by_namespace.items():
            for key, value in by_key.items():
                yield namespace, key, value

    def is_empty(self) -> bool:
        return not self.by_namespace


class _AbstractHeapState:
    def __init__(self, backend: "HeapKeyedStateBackend", descriptor: StateDescriptor,
                 table: StateTable):
        self._backend = backend
        self._descriptor = descriptor
        self._table = table
        self._namespace = VOID_NAMESPACE

    def set_current_namespace(self, namespace) -> None:
        self._namespace = namespace

    @property
    def _key(self):
        return self._backend.current_key

    def clear(self) -> None:
        self._table.remove(self._key, self._namespace)


class HeapValueState(_AbstractHeapState, ValueState):
    def value(self):
        v = self._table.get(self._key, self._namespace)
        if v is None:
            return self._descriptor.get_default_value()
        return v

    def update(self, value) -> None:
        if value is None:
            self.clear()
        else:
            self._table.put(self._key, self._namespace, value)


class HeapListState(_AbstractHeapState, ListState):
    def get(self):
        v = self._table.get(self._key, self._namespace)
        return list(v) if v else None

    def add(self, value) -> None:
        v = self._table.get(self._key, self._namespace)
        if v is None:
            self._table.put(self._key, self._namespace, [value])
        else:
            v.append(value)

    def add_all(self, values) -> None:
        values = list(values)
        if not values:
            return
        v = self._table.get(self._key, self._namespace)
        if v is None:
            self._table.put(self._key, self._namespace, values)
        else:
            v.extend(values)

    def update(self, values) -> None:
        values = list(values)
        if values:
            self._table.put(self._key, self._namespace, values)
        else:
            self.clear()

    def merge_namespaces(self, target, sources) -> None:
        """(ref: InternalMergingState#mergeNamespaces via
        HeapListState — concatenation)."""
        merged = self._table.get(self._key, target) or []
        for src in sources:
            v = self._table.get(self._key, src)
            if v:
                merged.extend(v)
            self._table.remove(self._key, src)
        if merged:
            self._table.put(self._key, target, merged)


class HeapReducingState(_AbstractHeapState, ReducingState):
    def __init__(self, backend, descriptor: ReducingStateDescriptor, table):
        super().__init__(backend, descriptor, table)
        self._reduce = descriptor.reduce_function.reduce

    def get(self):
        return self._table.get(self._key, self._namespace)

    def add(self, value) -> None:
        cur = self._table.get(self._key, self._namespace)
        self._table.put(self._key, self._namespace,
                        value if cur is None else self._reduce(cur, value))

    def merge_namespaces(self, target, sources) -> None:
        merged = self._table.get(self._key, target)
        for src in sources:
            v = self._table.get(self._key, src)
            self._table.remove(self._key, src)
            if v is not None:
                merged = v if merged is None else self._reduce(merged, v)
        if merged is not None:
            self._table.put(self._key, target, merged)


class HeapAggregatingState(_AbstractHeapState, AggregatingState):
    """add → agg.add(value, acc) (ref: HeapAggregatingState.java:80-89)."""

    def __init__(self, backend, descriptor: AggregatingStateDescriptor, table):
        super().__init__(backend, descriptor, table)
        self._agg = descriptor.aggregate_function

    def get(self):
        acc = self._table.get(self._key, self._namespace)
        if acc is None:
            return None
        return self._agg.get_result(acc)

    def get_accumulator(self):
        return self._table.get(self._key, self._namespace)

    def add(self, value) -> None:
        acc = self._table.get(self._key, self._namespace)
        if acc is None:
            acc = self._agg.create_accumulator()
        acc = self._agg.add(value, acc)
        self._table.put(self._key, self._namespace, acc)

    def merge_namespaces(self, target, sources) -> None:
        merged = self._table.get(self._key, target)
        for src in sources:
            v = self._table.get(self._key, src)
            self._table.remove(self._key, src)
            if v is not None:
                merged = v if merged is None else self._agg.merge(merged, v)
        if merged is not None:
            self._table.put(self._key, target, merged)


class HeapFoldingState(_AbstractHeapState, FoldingState):
    def __init__(self, backend, descriptor: FoldingStateDescriptor, table):
        super().__init__(backend, descriptor, table)
        self._fold = descriptor.fold_function

    def get(self):
        return self._table.get(self._key, self._namespace)

    def add(self, value) -> None:
        acc = self._table.get(self._key, self._namespace)
        if acc is None:
            acc = self._descriptor.get_default_value()
        self._table.put(self._key, self._namespace, self._fold(acc, value))


class HeapMapState(_AbstractHeapState, MapState):
    def _map(self, create=False) -> Optional[dict]:
        m = self._table.get(self._key, self._namespace)
        if m is None and create:
            m = {}
            self._table.put(self._key, self._namespace, m)
        return m

    def get(self, key):
        m = self._map()
        return None if m is None else m.get(key)

    def put(self, key, value) -> None:
        self._map(create=True)[key] = value

    def put_all(self, mapping: dict) -> None:
        if mapping:
            self._map(create=True).update(mapping)

    def remove(self, key) -> None:
        m = self._map()
        if m is not None:
            m.pop(key, None)
            if not m:
                self.clear()

    def contains(self, key) -> bool:
        m = self._map()
        return m is not None and key in m

    def entries(self):
        m = self._map()
        return list(m.items()) if m else []

    def keys(self):
        m = self._map()
        return list(m.keys()) if m else []

    def values(self):
        m = self._map()
        return list(m.values()) if m else []

    def is_empty(self) -> bool:
        m = self._map()
        return not m


class HeapKeyedStateBackend(KeyedStateBackend):
    """All registered states as host dict tables."""

    name = "heap"

    def __init__(self, key_group_range: KeyGroupRange, max_parallelism: int):
        super().__init__(key_group_range, max_parallelism)
        self._tables: Dict[str, StateTable] = {}

    def _table(self, name: str) -> StateTable:
        t = self._tables.get(name)
        if t is None:
            t = StateTable()
            self._tables[name] = t
        return t

    # ---- factories --------------------------------------------------
    def create_value_state(self, d: ValueStateDescriptor):
        return HeapValueState(self, d, self._table(d.name))

    def create_list_state(self, d: ListStateDescriptor):
        return HeapListState(self, d, self._table(d.name))

    def create_reducing_state(self, d: ReducingStateDescriptor):
        return HeapReducingState(self, d, self._table(d.name))

    def create_aggregating_state(self, d: AggregatingStateDescriptor):
        return HeapAggregatingState(self, d, self._table(d.name))

    def create_folding_state(self, d: FoldingStateDescriptor):
        return HeapFoldingState(self, d, self._table(d.name))

    def create_map_state(self, d: MapStateDescriptor):
        return HeapMapState(self, d, self._table(d.name))

    # ---- introspection ----------------------------------------------
    def get_keys(self, state_name: str, namespace) -> Iterable[Any]:
        t = self._tables.get(state_name)
        return list(t.keys(namespace)) if t else []

    def _migrate_state_values(self, descriptor, serializer,
                              restored_cfg) -> None:
        """Rewrite restored table values through the serializer's
        migration hook (heap values are live objects, so migration is
        an in-place, state-TYPE-aware table pass)."""
        from flink_tpu.state.backend import migrate_table_values
        table = self._tables.get(descriptor.name)
        if table is None:
            return
        migrate_table_values(table, descriptor, serializer,
                             restored_cfg)

    # ---- snapshot / restore -----------------------------------------
    def snapshot(self) -> KeyedStateSnapshot:
        """Serialize every (state, namespace, key, value) entry into
        its key group's chunk (ref: HeapKeyedStateBackend snapshot
        :289-420, key-grouped writeStateTable loop)."""
        per_kg: Dict[int, List[Tuple[str, Any, Any, Any]]] = defaultdict(list)
        for name, table in self._tables.items():
            for namespace, key, value in table.entries():
                kg = assign_to_key_group(key, self.max_parallelism)
                per_kg[kg].append((name, namespace, key, value))
        return KeyedStateSnapshot(
            {kg: pickle.dumps(entries, protocol=pickle.HIGHEST_PROTOCOL)
             for kg, entries in per_kg.items()},
            meta={"backend": self.name,
                  "serializers": self.serializer_config_snapshots()},
        )

    def restore(self, snapshots) -> None:
        self.check_serializer_compatibility(snapshots)
        # clear in place: bound state objects hold table references
        for table in self._tables.values():
            table.by_namespace.clear()
        for snap in snapshots:
            for kg, blob in snap.blobs():
                if not self.key_group_range.contains(kg):
                    continue
                chunk = pickle.loads(blob)
                if isinstance(chunk, dict):
                    # chunk written by the tpu backend: host entries plus
                    # device rows, which ARE the scalar-twin accumulator
                    # format the heap aggregating state operates on
                    for name, namespace, key, value in chunk["host"]:
                        self._table(name).put(key, namespace, value)
                    for name, entries in chunk["device"].items():
                        table = self._table(name)
                        for key, namespace, row in entries:
                            table.put(key, namespace, row)
                    continue
                for name, namespace, key, value in chunk:
                    self._table(name).put(key, namespace, value)
        self._apply_restored_migrations()

    def dispose(self) -> None:
        super().dispose()
        self._tables.clear()
