"""Incremental checkpoints: content-addressed shared state chunks.

The reference uploads only new RocksDB SST files per incremental
checkpoint and tracks cross-checkpoint sharing in a
SharedStateRegistry (ref: RocksDBKeyedStateBackend.java:342-381
snapshot strategy; SharedStateRegistry.java:42 refcounted handles).
Here the same seam is the :class:`SharedChunk`: any operator/backend
snapshot may wrap a stable unit of its state (a key group's bytes, a
window's compacted log) in a SharedChunk; the checkpoint storage
stores each distinct content hash ONCE, replaces repeats with light
references, refcounts chunks across retained checkpoints, and deletes
a chunk when its last referencing checkpoint is dropped.

Two chunk units ship wrapped:
- the keyed backends' per-key-group serialized chunks (heap + TPU
  backends, state/backend.py snapshot path) — an untouched key group
  contributes ~0 bytes to the next checkpoint;
- the log window engines' per-window compacted logs
  (streaming/log_windows.py) — a closed-but-unfired or simply
  untouched window re-uploads nothing (and skips re-hashing via a
  version cache).

Savepoints and cross-storage copies always materialize full payloads
(resolve_chunks) — a savepoint must be self-contained, exactly like
the reference's full-savepoint-from-incremental-checkpoint rule.
"""

from __future__ import annotations

import hashlib
from typing import Any, Callable, Dict, List, Set


class SharedChunk:
    """A content-addressed unit of snapshot state.  ``payload`` may be
    None when the producer knows the chunk is unchanged since a
    checkpoint this storage retains (hash-only reference); the storage
    falls back to requiring the payload for unknown hashes."""

    __slots__ = ("hash", "payload")

    def __init__(self, payload: Any, chunk_hash: str = None):
        self.payload = payload
        self.hash = chunk_hash if chunk_hash is not None \
            else content_hash(payload)

    def __repr__(self):
        return (f"SharedChunk({self.hash[:12]}, "
                f"{'ref' if self.payload is None else 'payload'})")


class ChunkRef:
    """Storage-internal replacement for a registered SharedChunk."""

    __slots__ = ("hash",)

    def __init__(self, chunk_hash: str):
        self.hash = chunk_hash

    def __repr__(self):
        return f"ChunkRef({self.hash[:12]})"


def content_hash(payload: Any) -> str:
    """Stable content hash of a chunk payload (bytes, numpy arrays,
    and nested list/tuple/dict compositions of them)."""
    h = hashlib.blake2b(digest_size=16)
    _feed(h, payload)
    return h.hexdigest()


def _feed(h, obj) -> None:
    # every field is length-prefixed: without delimiting, adjacent
    # fields can collide ([b"ab", b"c"] vs [b"a", b"bc"]) and a
    # collision in a content-addressed store is silent corruption
    import numpy as np

    def tagged(tag: bytes, payload: bytes) -> None:
        h.update(tag)
        h.update(len(payload).to_bytes(8, "little"))
        h.update(payload)

    if isinstance(obj, (bytes, bytearray, memoryview)):
        tagged(b"b", bytes(obj))
    elif isinstance(obj, np.ndarray):
        tagged(b"t", f"{obj.dtype}|{obj.shape}".encode())
        tagged(b"a", np.ascontiguousarray(obj).tobytes())
    elif isinstance(obj, dict):
        h.update(b"d")
        h.update(len(obj).to_bytes(8, "little"))
        for k in sorted(obj, key=repr):
            tagged(b"k", repr(k).encode())
            _feed(h, obj[k])
    elif isinstance(obj, (list, tuple)):
        h.update(b"l")
        h.update(len(obj).to_bytes(8, "little"))
        for x in obj:
            _feed(h, x)
    else:
        # deterministic scalar/struct fallback: pickle, never repr
        # (default reprs embed addresses — reuse would collide)
        import pickle
        tagged(b"o", pickle.dumps(obj, protocol=4))


def map_chunks(obj: Any, fn: Callable[[Any], Any],
               kinds=(SharedChunk, ChunkRef)) -> Any:
    """Rebuild a nested snapshot structure with every SharedChunk /
    ChunkRef node replaced by fn(node).  Containers are copied only
    along paths that contain chunks.  Objects exposing ``_map_chunks_``
    (e.g. KeyedStateSnapshot) map themselves."""
    if isinstance(obj, kinds):
        return fn(obj)
    mapper = getattr(obj, "_map_chunks_", None)
    if mapper is not None:
        return mapper(lambda c: fn(c) if isinstance(c, kinds) else c)
    if isinstance(obj, dict):
        out = None
        for k, v in obj.items():
            nv = map_chunks(v, fn, kinds)
            if nv is not v:
                if out is None:
                    out = dict(obj)
                out[k] = nv
        return out if out is not None else obj
    if isinstance(obj, (list, tuple)):
        mapped = [map_chunks(v, fn, kinds) for v in obj]
        if all(m is v for m, v in zip(mapped, obj)):
            return obj
        return type(obj)(mapped) if isinstance(obj, tuple) else mapped
    return obj


def find_chunks(obj: Any, out: List, kinds=(SharedChunk, ChunkRef)):
    if isinstance(obj, kinds):
        out.append(obj)
    elif hasattr(obj, "_map_chunks_"):
        obj._map_chunks_(lambda c: (out.append(c), c)[1]
                         if isinstance(c, kinds) else c)
    elif isinstance(obj, dict):
        for v in obj.values():
            find_chunks(v, out, kinds)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            find_chunks(v, out, kinds)
    return out


class SharedStateRegistry:
    """Refcounted chunk registry for one checkpoint storage (ref:
    SharedStateRegistry.java).  ``store``/``fetch``/``delete`` are
    provided by the storage (memory dict or files)."""

    def __init__(self, store: Callable[[str, Any], None],
                 delete: Callable[[str], None],
                 exists: Callable[[str], bool]):
        self._store = store
        self._delete = delete
        self._exists = exists
        self._refs: Dict[str, int] = {}
        self._by_checkpoint: Dict[int, Set[str]] = {}

    def register_checkpoint(self, checkpoint_id: int, snapshot: Any) -> Any:
        """Register every SharedChunk under this checkpoint; returns
        the snapshot with chunks replaced by ChunkRefs.  A payloadless
        chunk whose hash is unknown raises — the producer's unchanged
        claim was wrong for this storage.  ``last_new_hashes`` records
        the chunks actually stored by this call (the incremental
        bytes)."""
        hashes: Set[str] = set()
        self.last_new_hashes: List[str] = []

        def visit(chunk):
            if isinstance(chunk, ChunkRef):   # re-persist of loaded state
                hashes.add(chunk.hash)
                if chunk.hash not in self._refs \
                        and not self._exists(chunk.hash):
                    raise KeyError(
                        f"chunk {chunk.hash} referenced but not stored")
                return chunk
            if chunk.hash not in self._refs:
                if chunk.payload is None:
                    if not self._exists(chunk.hash):
                        raise KeyError(
                            f"chunk {chunk.hash} elided its payload but "
                            f"is unknown to this checkpoint storage")
                else:
                    self._store(chunk.hash, chunk.payload)
                    self.last_new_hashes.append(chunk.hash)
            hashes.add(chunk.hash)
            return ChunkRef(chunk.hash)

        out = map_chunks(snapshot, visit)
        for h in hashes:
            self._refs[h] = self._refs.get(h, 0) + 1
        self._by_checkpoint[checkpoint_id] = hashes
        return out

    def adopt_checkpoint(self, checkpoint_id: int, snapshot: Any) -> None:
        """Re-register refs of a checkpoint loaded from persistent
        storage (recovery in a fresh process)."""
        refs: List[ChunkRef] = []
        find_chunks(snapshot, refs, kinds=(ChunkRef,))
        hashes = {r.hash for r in refs}
        for h in hashes:
            self._refs[h] = self._refs.get(h, 0) + 1
        self._by_checkpoint[checkpoint_id] = hashes

    def release_checkpoint(self, checkpoint_id: int) -> None:
        for h in self._by_checkpoint.pop(checkpoint_id, ()):
            n = self._refs.get(h, 0) - 1
            if n <= 0:
                self._refs.pop(h, None)
                self._delete(h)
            else:
                self._refs[h] = n
