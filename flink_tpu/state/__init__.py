"""Keyed & operator state backends.

Re-designs the reference's state SPI (flink-runtime/.../state/
AbstractKeyedStateBackend.java:64-453) with two backends behind the
`state.backend` config switch (ref: StateBackendLoader.java:92-109):

  heap  — host dict tables, per-record semantics (ref:
          HeapKeyedStateBackend.java:90)
  tpu   — key-group-vectorized struct-of-arrays in TPU HBM with
          micro-batched scatter updates (replaces the RocksDB JNI
          backend, RocksDBKeyedStateBackend.java:134, whose per-record
          get/put round trips are the cost this design removes)
"""

from flink_tpu.state.backend import KeyedStateBackend
from flink_tpu.state.heap_backend import HeapKeyedStateBackend
from flink_tpu.state.tpu_backend import TpuKeyedStateBackend
from flink_tpu.state.operator_state import OperatorStateBackend
from flink_tpu.state.loader import load_state_backend

__all__ = [
    "KeyedStateBackend",
    "HeapKeyedStateBackend",
    "TpuKeyedStateBackend",
    "OperatorStateBackend",
    "load_state_backend",
]
