"""State-backend selection from configuration.

Mirrors flink-runtime/.../state/StateBackendLoader.java:92-109, where
the `state.backend` config key resolves shortcut names to factories —
the north-star requirement is that ONLY this switch changes between the
heap and TPU deployments.  Shortcuts accepted:

  heap | jobmanager | filesystem  → HeapKeyedStateBackend
  tpu  | rocksdb                  → TpuKeyedStateBackend
                                    (`rocksdb` maps to the TPU backend
                                    because it occupies the same role:
                                    the scalable keyed backend)
"""

from __future__ import annotations

from flink_tpu.core.config import Configuration
from flink_tpu.core.keygroups import KeyGroupRange
from flink_tpu.state.backend import KeyedStateBackend
from flink_tpu.state.heap_backend import HeapKeyedStateBackend
from flink_tpu.state.tpu_backend import TpuKeyedStateBackend

#: config key (ref: CheckpointingOptions.java:33 `state.backend`)
STATE_BACKEND_KEY = "state.backend"

_HEAP_NAMES = {"heap", "jobmanager", "filesystem", "memory", "hashmap"}
_TPU_NAMES = {"tpu", "rocksdb", "device", "hbm"}


def load_state_backend(
    config_or_name,
    key_group_range: KeyGroupRange,
    max_parallelism: int,
    **kwargs,
) -> KeyedStateBackend:
    if isinstance(config_or_name, Configuration):
        name = config_or_name.get_string(STATE_BACKEND_KEY, "heap")
        # HBM budget: beyond it, cold device slots spill to host RAM
        if config_or_name.contains("state.backend.tpu.max-device-slots"):
            cap = config_or_name.get_integer(
                "state.backend.tpu.max-device-slots")
            if cap is None or cap <= 0:
                raise ValueError(
                    "state.backend.tpu.max-device-slots must be > 0 "
                    f"(got {cap}); omit it for an uncapped device tier")
            kwargs.setdefault("max_device_slots", cap)
        # device scatter/gather micro-batch (pending-ring flush size)
        if config_or_name.contains("state.backend.tpu.microbatch-size"):
            mb = config_or_name.get_integer(
                "state.backend.tpu.microbatch-size")
            if mb is None or mb <= 0:
                raise ValueError(
                    "state.backend.tpu.microbatch-size must be > 0 "
                    f"(got {mb}); omit it for the built-in default")
            kwargs.setdefault("microbatch", mb)
    elif config_or_name is None:
        name = "heap"
    else:
        name = str(config_or_name)
    name = name.lower()
    if name in _HEAP_NAMES:
        return HeapKeyedStateBackend(key_group_range, max_parallelism)
    if name in _TPU_NAMES:
        return TpuKeyedStateBackend(key_group_range, max_parallelism, **kwargs)
    raise ValueError(
        f"unknown state backend {name!r}; expected one of "
        f"{sorted(_HEAP_NAMES | _TPU_NAMES)}")
