"""Process-wide state-pressure statistics.

One mutable singleton (`STATE_STATS`) counts batched-vs-row state
ingest and device flush traffic, plus a weak registry of the live
device-resident aggregation states so gauges can report slots in use,
spill-tier size, evictions and pending-ring depth without the backend
holding a reference to the metrics plane (mirrors NET_STATS in
runtime/netchannel.py).
"""

from __future__ import annotations

import threading
import weakref
from collections import deque


class StateStats:
    """Counters for the keyed-state ingest/flush hot path."""

    __slots__ = (
        "batch_rows", "row_fallback_rows", "batch_calls",
        "row_fallback_calls", "flush_batches", "flush_rows",
        "flush_sizes", "snapshot_columns", "snapshot_rows",
        "per_state_batch_rows", "per_state_batch_calls",
        "per_state_fallback_rows", "per_state_fallback_calls",
    )

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        #: rows ingested through a backend-native add_batch path
        self.batch_rows = 0
        #: rows that fell back to per-row state.add inside add_batch
        self.row_fallback_rows = 0
        self.batch_calls = 0
        self.row_fallback_calls = 0
        #: device micro-batch flushes and the rows they carried
        self.flush_batches = 0
        self.flush_rows = 0
        #: recent flush batch sizes (for mean/max gauges)
        self.flush_sizes = deque(maxlen=512)
        #: snapshot rows serialized as columns vs boxed per-row
        self.snapshot_columns = 0
        self.snapshot_rows = 0
        #: the same batch/fallback split ATTRIBUTED by state name, so a
        #: fallback is traceable to the state that caused it; the
        #: aggregate counters above stay authoritative for the
        #: established gauge names
        self.per_state_batch_rows = {}
        self.per_state_batch_calls = {}
        self.per_state_fallback_rows = {}
        self.per_state_fallback_calls = {}

    def note_batch(self, name: str, n: int) -> None:
        """One backend-native add_batch/get_batch call of `n` rows on
        state `name` (aggregates + the per-state split in one call)."""
        self.batch_calls += 1
        self.batch_rows += n
        self.per_state_batch_calls[name] = \
            self.per_state_batch_calls.get(name, 0) + 1
        self.per_state_batch_rows[name] = \
            self.per_state_batch_rows.get(name, 0) + n

    def note_fallback(self, name: str, n: int) -> None:
        """One per-row fallback pass of `n` rows on state `name`."""
        self.row_fallback_calls += 1
        self.row_fallback_rows += n
        self.per_state_fallback_calls[name] = \
            self.per_state_fallback_calls.get(name, 0) + 1
        self.per_state_fallback_rows[name] = \
            self.per_state_fallback_rows.get(name, 0) + n

    def note_flush(self, n: int) -> None:
        self.flush_batches += 1
        self.flush_rows += n
        self.flush_sizes.append(n)

    def flush_size_mean(self) -> float:
        sizes = self.flush_sizes
        return (sum(sizes) / len(sizes)) if sizes else 0.0

    def flush_size_max(self) -> int:
        sizes = self.flush_sizes
        return max(sizes) if sizes else 0


STATE_STATS = StateStats()

# Live device-resident states (DeviceAggregatingState instances).  A
# WeakSet so disposed backends drop out without an unregister call.
_LIVE_DEVICE_STATES: "weakref.WeakSet" = weakref.WeakSet()
_LIVE_LOCK = threading.Lock()


def register_device_state(state) -> None:
    with _LIVE_LOCK:
        _LIVE_DEVICE_STATES.add(state)


def device_state_summary() -> dict:
    """Aggregate live device-state pressure: slots in use, capacity,
    host-spill entries, evictions, host→device promotions, pending-ring
    depth.  Safe to call from a gauge thread."""
    slots = capacity = spilled = evictions = promotions = pending = 0
    states = 0
    with _LIVE_LOCK:
        live = list(_LIVE_DEVICE_STATES)
    for st in live:
        try:
            states += 1
            slots += len(st.slot_index)
            capacity += st.capacity
            spilled += len(st.host_tier)
            evictions += st.evictions
            promotions += st.promotions
            pending += len(st._pending_slots)
        except Exception:  # noqa: BLE001 — racing dispose
            continue
    return {
        "states": states,
        "slots_in_use": slots,
        "capacity": capacity,
        "spilled_entries": spilled,
        "evictions": evictions,
        "promotions": promotions,
        "pending_depth": pending,
    }
