"""Model evaluation + cross-validation.

The reference's flink-ml leaves evaluation to `evaluate()` on
Predictors and score functions; the fuller framework role (the
KFold / cross-validation / scoring surface of its roadmap and of
every practical pipeline) lives here, vectorized:

- scoring functions over numpy arrays (classification: accuracy /
  precision / recall / F1 / confusion matrix; regression: MSE / MAE /
  R²),
- deterministic shuffled splits (`train_test_split`, `KFold`),
- `cross_val_score` re-fitting a fresh clone of the estimator per
  fold, and `GridSearchCV`-style parameter search over it.

Estimators are the library's own Estimator/Predictor contract
(fit(X, y) / predict(X)); clones come from the estimator's class +
constructor params captured via `get_params` when present, else the
constructor's attribute convention used across flink_tpu.ml.
"""

from __future__ import annotations

import inspect
import itertools
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

__all__ = [
    "accuracy_score", "precision_score", "recall_score", "f1_score",
    "confusion_matrix", "mean_squared_error", "mean_absolute_error",
    "r2_score", "train_test_split", "KFold", "cross_val_score",
    "GridSearchCV",
]


# ---------------------------------------------------------------------
# scores
# ---------------------------------------------------------------------

def accuracy_score(y_true, y_pred) -> float:
    y_true = np.asarray(y_true)
    y_pred = np.asarray(y_pred)
    return float(np.mean(y_true == y_pred)) if len(y_true) else 0.0

def _binary_counts(y_true, y_pred, positive):
    y_true = np.asarray(y_true)
    y_pred = np.asarray(y_pred)
    tp = float(np.sum((y_pred == positive) & (y_true == positive)))
    fp = float(np.sum((y_pred == positive) & (y_true != positive)))
    fn = float(np.sum((y_pred != positive) & (y_true == positive)))
    return tp, fp, fn


def precision_score(y_true, y_pred, positive=1) -> float:
    tp, fp, _ = _binary_counts(y_true, y_pred, positive)
    return tp / (tp + fp) if tp + fp else 0.0


def recall_score(y_true, y_pred, positive=1) -> float:
    tp, _, fn = _binary_counts(y_true, y_pred, positive)
    return tp / (tp + fn) if tp + fn else 0.0


def f1_score(y_true, y_pred, positive=1) -> float:
    p = precision_score(y_true, y_pred, positive)
    r = recall_score(y_true, y_pred, positive)
    return 2 * p * r / (p + r) if p + r else 0.0


def confusion_matrix(y_true, y_pred
                     ) -> Tuple[np.ndarray, List[Any]]:
    """→ (matrix[label_i, label_j] = #(true i predicted j), labels)."""
    y_true = np.asarray(y_true)
    y_pred = np.asarray(y_pred)
    labels = sorted(set(y_true.tolist()) | set(y_pred.tolist()))
    index = {lab: i for i, lab in enumerate(labels)}
    m = np.zeros((len(labels), len(labels)), np.int64)
    for t, p in zip(y_true.tolist(), y_pred.tolist()):
        m[index[t], index[p]] += 1
    return m, labels


def mean_squared_error(y_true, y_pred) -> float:
    d = np.asarray(y_true, np.float64) - np.asarray(y_pred, np.float64)
    return float(np.mean(d * d))


def mean_absolute_error(y_true, y_pred) -> float:
    d = np.asarray(y_true, np.float64) - np.asarray(y_pred, np.float64)
    return float(np.mean(np.abs(d)))


def r2_score(y_true, y_pred) -> float:
    y = np.asarray(y_true, np.float64)
    p = np.asarray(y_pred, np.float64)
    ss_res = float(np.sum((y - p) ** 2))
    ss_tot = float(np.sum((y - y.mean()) ** 2))
    if ss_res == 0.0:
        return 1.0   # perfect fit, even on a constant target
    return 1.0 - ss_res / ss_tot if ss_tot else 0.0


_SCORERS: Dict[str, Callable] = {
    "accuracy": accuracy_score,
    "f1": f1_score,
    "neg_mean_squared_error":
        lambda yt, yp: -mean_squared_error(yt, yp),
    "neg_mean_absolute_error":
        lambda yt, yp: -mean_absolute_error(yt, yp),
    "r2": r2_score,
}


# ---------------------------------------------------------------------
# splits
# ---------------------------------------------------------------------

def train_test_split(X, y, test_size: float = 0.25, seed: int = 0):
    X = np.asarray(X)
    y = np.asarray(y)
    n = len(X)
    order = np.random.default_rng(seed).permutation(n)
    n_test = max(1, int(round(n * test_size)))
    test, train = order[:n_test], order[n_test:]
    return X[train], X[test], y[train], y[test]


class KFold:
    """Deterministic shuffled k-fold split."""

    def __init__(self, n_splits: int = 5, shuffle: bool = True,
                 seed: int = 0):
        if n_splits < 2:
            raise ValueError("need at least 2 folds")
        self.n_splits = n_splits
        self.shuffle = shuffle
        self.seed = seed

    def split(self, X) -> Iterable[Tuple[np.ndarray, np.ndarray]]:
        n = len(X)
        if n < self.n_splits:
            raise ValueError(
                f"cannot split {n} samples into {self.n_splits} "
                "folds (empty test folds would score 0)")
        idx = (np.random.default_rng(self.seed).permutation(n)
               if self.shuffle else np.arange(n))
        for fold in np.array_split(idx, self.n_splits):
            yield idx[~np.isin(idx, fold)], fold


# ---------------------------------------------------------------------
# estimator cloning + cross-validation
# ---------------------------------------------------------------------

def _clone(estimator, override: Optional[dict] = None):
    params = {}
    if hasattr(estimator, "get_params"):
        params = dict(estimator.get_params())
    else:
        sig = inspect.signature(type(estimator).__init__)
        for name in list(sig.parameters)[1:]:
            if hasattr(estimator, name):
                params[name] = getattr(estimator, name)
    if override:
        params.update(override)
    return type(estimator)(**params)


def cross_val_score(estimator, X, y, cv=5,
                    scoring: str = "accuracy") -> np.ndarray:
    """Fit a fresh clone per fold, score on the held-out fold."""
    X = np.asarray(X)
    y = np.asarray(y)
    folds = cv if isinstance(cv, KFold) else KFold(cv)
    scorer = _SCORERS[scoring] if isinstance(scoring, str) else scoring
    scores = []
    for train_idx, test_idx in folds.split(X):
        model = _clone(estimator)
        model.fit(X[train_idx], y[train_idx])
        scores.append(scorer(y[test_idx], model.predict(X[test_idx])))
    return np.asarray(scores, np.float64)


class GridSearchCV:
    """Exhaustive parameter search by mean cross-validation score;
    refits the winner on the full data (`best_estimator_`)."""

    def __init__(self, estimator, param_grid: Dict[str, list],
                 cv=3, scoring: str = "accuracy"):
        self.estimator = estimator
        self.param_grid = param_grid
        self.cv = cv
        self.scoring = scoring
        self.results_: List[Tuple[dict, float]] = []
        self.best_params_: Optional[dict] = None
        self.best_score_: Optional[float] = None
        self.best_estimator_ = None

    def fit(self, X, y) -> "GridSearchCV":
        keys = list(self.param_grid)
        for combo in itertools.product(
                *(self.param_grid[k] for k in keys)):
            params = dict(zip(keys, combo))
            model = _clone(self.estimator, params)
            score = float(np.mean(cross_val_score(
                model, X, y, cv=self.cv, scoring=self.scoring)))
            self.results_.append((params, score))
            if self.best_score_ is None or score > self.best_score_:
                self.best_score_ = score
                self.best_params_ = params
        self.best_estimator_ = _clone(self.estimator,
                                      self.best_params_)
        self.best_estimator_.fit(np.asarray(X), np.asarray(y))
        return self

    def predict(self, X):
        return self.best_estimator_.predict(np.asarray(X))
