"""Machine-learning library (the flink-ml analogue,
flink-libraries/flink-ml/src/main/scala/org/apache/flink/ml/:
pipeline/ Estimator-Transformer-Predictor, preprocessing/
StandardScaler MinMaxScaler PolynomialFeatures, regression/
MultipleLinearRegression, classification/ SVM + KNN, recommendation/
ALS, optimization/ GradientDescent, metrics/ distances) —
re-designed TPU-first: the reference trains with per-record DataSet
iterations; here every fit is a jitted full-batch device program
(gradient steps and normal-equation solves are MXU matmuls)."""

from flink_tpu.ml.pipeline import Estimator, Pipeline, Predictor, Transformer
from flink_tpu.ml.preprocessing import (
    MinMaxScaler,
    PolynomialFeatures,
    StandardScaler,
)
from flink_tpu.ml.regression import MultipleLinearRegression
from flink_tpu.ml.classification import KNN, SVM
from flink_tpu.ml.recommendation import ALS
from flink_tpu.ml.validation import (
    GridSearchCV,
    KFold,
    accuracy_score,
    confusion_matrix,
    cross_val_score,
    f1_score,
    mean_absolute_error,
    mean_squared_error,
    precision_score,
    r2_score,
    recall_score,
    train_test_split,
)
from flink_tpu.ml.metrics import (
    chebyshev_distance,
    cosine_distance,
    euclidean_distance,
    manhattan_distance,
    minkowski_distance,
    squared_euclidean_distance,
    tanimoto_distance,
)

__all__ = [
    "Estimator", "Transformer", "Predictor", "Pipeline",
    "StandardScaler", "MinMaxScaler", "PolynomialFeatures",
    "MultipleLinearRegression", "SVM", "KNN", "ALS",
    "euclidean_distance", "squared_euclidean_distance",
    "cosine_distance", "chebyshev_distance", "manhattan_distance",
    "minkowski_distance", "tanimoto_distance",
    "KFold", "GridSearchCV", "cross_val_score", "train_test_split",
    "accuracy_score", "precision_score", "recall_score", "f1_score",
    "confusion_matrix", "mean_squared_error", "mean_absolute_error",
    "r2_score",
]
