"""Machine-learning library (the flink-ml analogue,
flink-libraries/flink-ml/src/main/scala/org/apache/flink/ml/:
pipeline/ Estimator-Transformer-Predictor, preprocessing/
StandardScaler MinMaxScaler PolynomialFeatures, regression/
MultipleLinearRegression, classification/ SVM + KNN, recommendation/
ALS, optimization/ GradientDescent, metrics/ distances) —
re-designed TPU-first: the reference trains with per-record DataSet
iterations; here every fit is a jitted full-batch device program
(gradient steps and normal-equation solves are MXU matmuls)."""

from flink_tpu.ml.pipeline import Estimator, Pipeline, Predictor, Transformer
from flink_tpu.ml.preprocessing import (
    MinMaxScaler,
    PolynomialFeatures,
    StandardScaler,
)
from flink_tpu.ml.regression import MultipleLinearRegression
from flink_tpu.ml.classification import KNN, SVM
from flink_tpu.ml.recommendation import ALS
from flink_tpu.ml.metrics import (
    chebyshev_distance,
    cosine_distance,
    euclidean_distance,
    manhattan_distance,
    minkowski_distance,
    squared_euclidean_distance,
    tanimoto_distance,
)

__all__ = [
    "Estimator", "Transformer", "Predictor", "Pipeline",
    "StandardScaler", "MinMaxScaler", "PolynomialFeatures",
    "MultipleLinearRegression", "SVM", "KNN", "ALS",
    "euclidean_distance", "squared_euclidean_distance",
    "cosine_distance", "chebyshev_distance", "manhattan_distance",
    "minkowski_distance", "tanimoto_distance",
]
