"""Recommendation (ref: flink-ml recommendation/ALS.scala —
alternating least squares matrix factorization with implicit blocks).

TPU-first: the per-user / per-item normal-equation solves are BATCHED
into one `vmap(solve)` over dense per-entity Gram matrices built with
segment_sums — the reference's blocked message exchange becomes two
device programs per sweep (users then items)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.ml.pipeline import Estimator


class ALS(Estimator):
    def __init__(self, num_factors: int = 10, lambda_: float = 0.1,
                 iterations: int = 10, seed: int = 0):
        self.num_factors = num_factors
        self.lambda_ = lambda_
        self.iterations = iterations
        self.seed = seed
        self.user_factors = None
        self.item_factors = None
        self._users = None
        self._items = None

    def fit(self, ratings, y=None):
        """ratings: iterable of (user, item, rating)."""
        triples = [tuple(r) for r in ratings]
        users = sorted({u for u, _, _ in triples})
        items = sorted({i for _, i, _ in triples})
        uidx = {u: i for i, u in enumerate(users)}
        iidx = {i: j for j, i in enumerate(items)}
        n_u, n_i, f = len(users), len(items), self.num_factors
        u = np.fromiter((uidx[a] for a, _, _ in triples), np.int32,
                        count=len(triples))
        it = np.fromiter((iidx[b] for _, b, _ in triples), np.int32,
                         count=len(triples))
        r = np.fromiter((float(c) for _, _, c in triples), np.float32,
                        count=len(triples))
        rng = np.random.default_rng(self.seed)
        U = jnp.asarray(rng.normal(0, 0.1, (n_u, f)).astype(np.float32))
        V = jnp.asarray(rng.normal(0, 0.1, (n_i, f)).astype(np.float32))
        uj, ij, rj = jnp.asarray(u), jnp.asarray(it), jnp.asarray(r)
        lam = self.lambda_

        from functools import partial

        @partial(jax.jit, static_argnums=(4,))
        def solve_side(fixed, rows, cols, vals, n_rows):
            """For each row entity e: solve
            (sum_c v_c v_c^T + lam I) x = sum_c r_ec v_c
            with Gram matrices built by segment_sum over ratings."""
            vc = fixed[cols]                                # [nnz, f]
            outer = vc[:, :, None] * vc[:, None, :]         # [nnz, f, f]
            grams = jax.ops.segment_sum(outer, rows, num_segments=n_rows)
            rhs = jax.ops.segment_sum(vals[:, None] * vc, rows,
                                      num_segments=n_rows)
            grams = grams + lam * jnp.eye(fixed.shape[1])[None]
            return jax.vmap(jnp.linalg.solve)(grams, rhs)

        for _ in range(self.iterations):
            U = solve_side(V, uj, ij, rj, n_u)
            V = solve_side(U, ij, uj, rj, n_i)
        self.user_factors = np.asarray(U)
        self.item_factors = np.asarray(V)
        self._users = uidx
        self._items = iidx
        return self

    def predict(self, pairs) -> np.ndarray:
        out = []
        for user, item in pairs:
            if user in self._users and item in self._items:
                out.append(float(
                    self.user_factors[self._users[user]]
                    @ self.item_factors[self._items[item]]))
            else:
                out.append(0.0)
        return np.asarray(out, np.float32)

    def empirical_risk(self, ratings) -> float:
        preds = self.predict([(u, i) for u, i, _ in ratings])
        truth = np.asarray([r for _, _, r in ratings], np.float32)
        return float(((preds - truth) ** 2).mean())
