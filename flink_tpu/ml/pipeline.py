"""Pipeline abstractions (ref: flink-ml pipeline/Estimator.scala,
Transformer.scala, Predictor.scala, and the chainable pipeline built
by `transformer.chainTransformer(...)` / `chainPredictor(...)`).

Data is numpy arrays (features [n, d]; labels [n]) — the DataSet[
LabeledVector] of the reference collapsed to columns, ready for
device programs."""

from __future__ import annotations

import abc
from typing import Any, List, Optional, Tuple

import numpy as np


class Transformer(abc.ABC):
    """fit(X) learns transformation state; transform(X) applies it."""

    def fit(self, X, y=None) -> "Transformer":  # noqa: B027
        return self

    @abc.abstractmethod
    def transform(self, X) -> np.ndarray: ...

    def fit_transform(self, X, y=None) -> np.ndarray:
        return self.fit(X, y).transform(X)

    def chain_transformer(self, other: "Transformer") -> "Pipeline":
        return Pipeline([self, other])

    def chain_predictor(self, predictor: "Predictor") -> "Pipeline":
        return Pipeline([self, predictor])


class Estimator(abc.ABC):
    @abc.abstractmethod
    def fit(self, X, y=None) -> Any: ...


class Predictor(Estimator):
    """fit(X, y) trains; predict(X) scores."""

    @abc.abstractmethod
    def predict(self, X) -> np.ndarray: ...


class Pipeline(Predictor, Transformer):
    """Chained transformers with an optional terminal predictor
    (ref: the ChainedTransformer/ChainedPredictor pair)."""

    def __init__(self, stages: List[Any]):
        self.stages = list(stages)

    def fit(self, X, y=None) -> "Pipeline":
        data = np.asarray(X)
        for i, stage in enumerate(self.stages):
            last = i == len(self.stages) - 1
            if isinstance(stage, Transformer):
                data = stage.fit(data, y).transform(data)
            elif last:
                stage.fit(data, y)
            else:
                raise TypeError(
                    "non-terminal pipeline stages must be Transformers")
        return self

    def _apply_transformers(self, X) -> Tuple[np.ndarray, Optional[Any]]:
        data = np.asarray(X)
        terminal = None
        for i, stage in enumerate(self.stages):
            if isinstance(stage, Transformer) and not (
                    i == len(self.stages) - 1
                    and isinstance(stage, Predictor)):
                data = stage.transform(data)
            else:
                terminal = stage
        return data, terminal

    def transform(self, X) -> np.ndarray:
        data, _ = self._apply_transformers(X)
        return data

    def predict(self, X) -> np.ndarray:
        data, terminal = self._apply_transformers(X)
        if terminal is None or not isinstance(terminal, Predictor):
            raise TypeError("pipeline has no terminal predictor")
        return terminal.predict(data)
