"""Distance metrics (ref: flink-ml metrics/distances/:
EuclideanDistanceMetric.scala, SquaredEuclideanDistanceMetric,
CosineDistanceMetric, ChebyshevDistanceMetric,
ManhattanDistanceMetric, MinkowskiDistanceMetric,
TanimotoDistanceMetric).  Vectorized over trailing feature axes."""

from __future__ import annotations

import numpy as np


def _ab(a, b):
    return np.asarray(a, np.float64), np.asarray(b, np.float64)


def squared_euclidean_distance(a, b):
    a, b = _ab(a, b)
    return ((a - b) ** 2).sum(axis=-1)


def euclidean_distance(a, b):
    return np.sqrt(squared_euclidean_distance(a, b))


def manhattan_distance(a, b):
    a, b = _ab(a, b)
    return np.abs(a - b).sum(axis=-1)


def chebyshev_distance(a, b):
    a, b = _ab(a, b)
    return np.abs(a - b).max(axis=-1)


def minkowski_distance(a, b, p: float = 3.0):
    a, b = _ab(a, b)
    return (np.abs(a - b) ** p).sum(axis=-1) ** (1.0 / p)


def cosine_distance(a, b):
    a, b = _ab(a, b)
    denom = np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1)
    return 1.0 - (a * b).sum(axis=-1) / np.where(denom == 0, 1.0, denom)


def tanimoto_distance(a, b):
    a, b = _ab(a, b)
    dot = (a * b).sum(axis=-1)
    denom = (a * a).sum(axis=-1) + (b * b).sum(axis=-1) - dot
    return 1.0 - dot / np.where(denom == 0, 1.0, denom)
