"""Feature preprocessing (ref: flink-ml preprocessing/
StandardScaler.scala, MinMaxScaler.scala, PolynomialFeatures.scala)."""

from __future__ import annotations

from itertools import combinations_with_replacement

import numpy as np

from flink_tpu.ml.pipeline import Transformer


class StandardScaler(Transformer):
    """(ref: preprocessing/StandardScaler.scala — scale to the given
    mean/std)."""

    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.target_mean = mean
        self.target_std = std
        self.data_mean = None
        self.data_std = None

    def fit(self, X, y=None):
        X = np.asarray(X, np.float32)
        self.data_mean = X.mean(axis=0)
        self.data_std = X.std(axis=0)
        self.data_std = np.where(self.data_std == 0, 1.0, self.data_std)
        return self

    def transform(self, X):
        X = np.asarray(X, np.float32)
        return ((X - self.data_mean) / self.data_std * self.target_std
                + self.target_mean)


class MinMaxScaler(Transformer):
    """(ref: preprocessing/MinMaxScaler.scala)."""

    def __init__(self, min_value: float = 0.0, max_value: float = 1.0):
        self.lo = min_value
        self.hi = max_value
        self.data_min = None
        self.data_range = None

    def fit(self, X, y=None):
        X = np.asarray(X, np.float32)
        self.data_min = X.min(axis=0)
        rng = X.max(axis=0) - self.data_min
        self.data_range = np.where(rng == 0, 1.0, rng)
        return self

    def transform(self, X):
        X = np.asarray(X, np.float32)
        unit = (X - self.data_min) / self.data_range
        return unit * (self.hi - self.lo) + self.lo


class PolynomialFeatures(Transformer):
    """(ref: preprocessing/PolynomialFeatures.scala — maps a vector to
    the polynomial feature space up to the given degree: all monomials
    of the input features with total degree 1..degree)."""

    def __init__(self, degree: int = 2):
        self.degree = degree
        self._combos = None

    def fit(self, X, y=None):
        d = np.asarray(X).shape[1]
        self._combos = [c for deg in range(1, self.degree + 1)
                        for c in combinations_with_replacement(range(d), deg)]
        return self

    def transform(self, X):
        X = np.asarray(X, np.float32)
        if self._combos is None:
            self.fit(X)
        cols = [X[:, c].prod(axis=1) for c in self._combos]
        return np.stack(cols, axis=1)
