"""Linear regression (ref: flink-ml regression/
MultipleLinearRegression.scala — squared-loss linear model trained by
the optimization framework's gradient descent, optimization/
GradientDescent.scala).  TPU-first: full-batch gradient descent as
one jitted `lax.fori_loop` of MXU matmuls — the reference's per-
superstep DataSet reduce becomes X^T(Xw - y) on device."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.ml.pipeline import Predictor


class MultipleLinearRegression(Predictor):
    def __init__(self, iterations: int = 200, stepsize: float = 0.1,
                 l2: float = 0.0, convergence_threshold: float = 0.0):
        self.iterations = iterations
        self.stepsize = stepsize
        self.l2 = l2
        self.convergence_threshold = convergence_threshold
        self.weights = None
        self.intercept = None

    def fit(self, X, y=None):
        assert y is not None, "labels required"
        X = jnp.asarray(np.asarray(X, np.float32))
        y = jnp.asarray(np.asarray(y, np.float32))
        n, d = X.shape
        # standardize internally for conditioning; de-scale at the end
        mu, sigma = X.mean(0), jnp.maximum(X.std(0), 1e-8)
        Xs = (X - mu) / sigma
        ymu = y.mean()

        iterations = self.iterations
        step = self.stepsize
        l2 = self.l2
        thresh = self.convergence_threshold

        @jax.jit
        def train(Xs, yc):
            def cond(state):
                i, w, b, delta = state
                return (i < iterations) & (delta >= thresh)

            def body(state):
                i, w, b, _ = state
                pred = Xs @ w + b
                err = pred - yc
                # decayed effective step (the reference's
                # stepsize / sqrt(iteration) schedule)
                eta = step / jnp.sqrt(i + 1.0)
                grad_w = Xs.T @ err / n + l2 * w
                grad_b = err.mean()
                new_w = w - eta * grad_w
                new_b = b - eta * grad_b
                # convergence = max parameter movement this step (the
                # reference checks relative loss change; parameter
                # movement is the jit-friendly equivalent)
                delta = jnp.maximum(jnp.max(jnp.abs(new_w - w)),
                                    jnp.abs(new_b - b))
                return (i + 1, new_w, new_b, delta)

            w0 = jnp.zeros(Xs.shape[1], jnp.float32)
            state = (jnp.float32(0.0), w0, jnp.float32(0.0),
                     jnp.float32(jnp.inf))
            _, w, b, _ = jax.lax.while_loop(cond, body, state)
            return w, b

        w, b = train(Xs, y - ymu)
        # undo the internal standardization: y = (x - mu)/sigma . w + b + ymu
        w_orig = np.asarray(w) / np.asarray(sigma)
        self.weights = w_orig
        self.intercept = float(b + ymu - np.asarray(mu) @ w_orig)
        return self

    def predict(self, X):
        X = np.asarray(X, np.float32)
        return X @ self.weights + self.intercept

    def squared_residual_sum(self, X, y) -> float:
        pred = self.predict(X)
        return float(((pred - np.asarray(y)) ** 2).sum())
