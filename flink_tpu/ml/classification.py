"""Classification (ref: flink-ml classification/SVM.scala — CoCoA
distributed dual solver for the linear soft-margin SVM — and nn/
KNN.scala — exact k-nearest-neighbors with block joins).

TPU-first mechanisms:
- SVM: hinge-loss primal subgradient descent, one jitted fori_loop
  (full-batch matmul per step) — same model family and loss as CoCoA,
  device-batched instead of dual-coordinate;
- KNN: the all-pairs distance matrix is ONE MXU matmul
  (|a-b|^2 = |a|^2 + |b|^2 - 2ab), then top-k — the reference's
  blockwise cross-join collapsed to a device GEMM.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.ml.pipeline import Predictor


class SVM(Predictor):
    """Linear soft-margin SVM; labels in {-1, +1}
    (ref: classification/SVM.scala:73 — regularization constant,
    iterations, stepsize parameters)."""

    def __init__(self, iterations: int = 300, stepsize: float = 0.5,
                 regularization: float = 0.01):
        self.iterations = iterations
        self.stepsize = stepsize
        self.regularization = regularization
        self.weights = None
        self.intercept = 0.0
        self.threshold = 0.0  # decision threshold on the margin

    def fit(self, X, y=None):
        assert y is not None
        X = jnp.asarray(np.asarray(X, np.float32))
        y = jnp.asarray(np.asarray(y, np.float32))
        assert set(np.unique(np.asarray(y))) <= {-1.0, 1.0}, \
            "SVM labels must be -1/+1"
        n, d = X.shape
        lam = self.regularization
        step = self.stepsize
        iterations = self.iterations

        @jax.jit
        def train(X, y):
            def body(i, wb):
                w, b = wb
                margins = y * (X @ w + b)
                active = (margins < 1.0).astype(jnp.float32)
                eta = step / (lam * (i + 1.0))  # pegasos schedule
                grad_w = lam * w - (X.T @ (active * y)) / n
                grad_b = -(active * y).mean()
                return (w - eta * grad_w, b - eta * grad_b)

            w0 = jnp.zeros(d, jnp.float32)
            return jax.lax.fori_loop(0, iterations, body,
                                     (w0, jnp.float32(0.0)))

        w, b = train(X, y)
        self.weights = np.asarray(w)
        self.intercept = float(b)
        return self

    def decision_function(self, X) -> np.ndarray:
        return np.asarray(X, np.float32) @ self.weights + self.intercept

    def predict(self, X) -> np.ndarray:
        return np.where(self.decision_function(X) >= self.threshold,
                        1.0, -1.0)


class KNN(Predictor):
    """Exact k-NN (ref: nn/KNN.scala — exact blockwise solution with
    a quadtree option; here the full distance matrix is one GEMM)."""

    def __init__(self, k: int = 3):
        self.k = k
        self._X = None
        self._y = None

    def fit(self, X, y=None):
        self._X = np.asarray(X, np.float32)
        self._y = None if y is None else np.asarray(y)
        return self

    def kneighbors(self, Q) -> np.ndarray:
        """Indices [m, k] of the k nearest training points per query."""
        Q = jnp.asarray(np.asarray(Q, np.float32))
        X = jnp.asarray(self._X)
        k = self.k

        @jax.jit
        def nearest(Q, X):
            d2 = (jnp.sum(Q * Q, 1)[:, None]
                  + jnp.sum(X * X, 1)[None, :]
                  - 2.0 * Q @ X.T)
            _, idx = jax.lax.top_k(-d2, k)
            return idx

        return np.asarray(nearest(Q, X))

    def predict(self, Q) -> np.ndarray:
        assert self._y is not None, "fit with labels to predict"
        idx = self.kneighbors(Q)
        neighbor_labels = self._y[idx]  # [m, k]
        if neighbor_labels.dtype.kind in "fc":
            return neighbor_labels.mean(axis=1)  # regression: average
        # classification: majority vote
        out = []
        for row in neighbor_labels:
            vals, counts = np.unique(row, return_counts=True)
            out.append(vals[np.argmax(counts)])
        return np.asarray(out)
