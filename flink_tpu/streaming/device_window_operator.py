"""DeviceWindowOperator: the vectorized engines inside the framework.

Makes `keyBy().window(...).aggregate(device_agg)` run on the TPU hot
path (flink_tpu.streaming.vectorized / vectorized_sessions) while
living as a normal operator in the task layer: records buffer on the
host, every watermark (and every `flush_batch` records) flushes one
vectorized `process_batch` + `advance_watermark` into the engine, and
fires emit through the standard Output with the scalar operator's
timestamp contract (window.maxTimestamp — ref: WindowOperator.java:544
emitWindowContents).  Checkpoints snapshot the engine (device arrays
DMA'd to host + host indexes) so barrier checkpointing, recovery, and
restarts work identically to the scalar path.

Eligibility is decided by the graph builder (see
WindowedStream._build): DeviceAggregateFunction + event-time
tumbling/sliding/session assigner + default trigger, no evictor,
lateness 0.  Anything else stays on the scalar WindowOperator — same
split the reference drew between its (removed) aligned-window fast
operators and the general WindowOperator (WindowOperator.java:192-195).
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from flink_tpu.ops.device_agg import DeviceAggregateFunction
from flink_tpu.runtime.tracing import get_tracer
from flink_tpu.streaming.elements import (MAX_TIMESTAMP,
    StreamRecord, Watermark)
from flink_tpu.streaming.operators import StreamOperator, TimestampedCollector
from flink_tpu.streaming.vectorized import (
    VectorizedSlidingWindows,
    VectorizedTumblingWindows,
)
from flink_tpu.streaming.vectorized_sessions import VectorizedSessionWindows
from flink_tpu.streaming.windowing import (
    EventTimeSessionWindows,
    SlidingEventTimeWindows,
    TimeWindow,
    TumblingEventTimeWindows,
)


def assigner_supported(assigner) -> bool:
    """Shape check shared by the fail-fast open() and the planner: the
    assigners the single-device engines (either tier) cover."""
    if isinstance(assigner, TumblingEventTimeWindows):
        return assigner.offset == 0
    if isinstance(assigner, SlidingEventTimeWindows):
        return assigner.offset == 0 and assigner.size % assigner.slide == 0
    return isinstance(assigner, EventTimeSessionWindows)


def string_sum_engine_for_assigner(assigner, agg: DeviceAggregateFunction):
    """Fused intern+sum engine for STRING-keyed tumbling sums, or None
    when the shape doesn't fit.  Floating accumulation only: the C++
    kernel sums in double, so integer value dtypes (exact beyond 2^53)
    must stay on the exact tiers."""
    from flink_tpu.ops.device_agg import SumAggregate
    from flink_tpu.streaming.log_windows import StringSumTumblingWindows
    if (isinstance(agg, SumAggregate)
            and np.issubdtype(agg.value_dtype, np.floating)
            and isinstance(assigner, TumblingEventTimeWindows)
            and assigner.offset == 0):
        try:
            return StringSumTumblingWindows(agg, assigner.size)
        except RuntimeError:
            pass  # no native runtime
    return None


def log_engine_for_assigner(assigner, agg: DeviceAggregateFunction):
    """Log-structured combiner tier for this assigner+aggregate, or
    None when the cell decomposition / assigner shape doesn't fit
    (streaming/log_windows.py scope: integer keys, HLL/Sum/Quantile
    cells, Count-Min sessions)."""
    from flink_tpu.streaming import log_windows as lw
    try:
        if isinstance(assigner, TumblingEventTimeWindows) \
                and assigner.offset == 0:
            return lw.LogStructuredTumblingWindows(agg, assigner.size)
        if (isinstance(assigner, SlidingEventTimeWindows)
                and assigner.offset == 0
                and assigner.size % assigner.slide == 0):
            return lw.LogStructuredSlidingWindows(agg, assigner.size,
                                                  assigner.slide)
        if isinstance(assigner, EventTimeSessionWindows):
            return lw.LogStructuredSessionWindows(agg, assigner.gap)
    except (TypeError, ValueError, RuntimeError):
        pass  # unsupported cell decomposition / params / no native lib
    return None


def engine_for_assigner(assigner, agg: DeviceAggregateFunction,
                        initial_capacity: int = 1 << 14, mesh=None,
                        mesh_axis: str = "kg", max_parallelism: int = 128):
    """Assigner → engine, or None when no device engine applies.  With
    a mesh, tumbling windows run on the sharded multi-window engine
    (SPMD over the mesh axis, flink_tpu.parallel.mesh_windows); other
    assigners fall back to the single-device engines."""
    if isinstance(assigner, TumblingEventTimeWindows) and assigner.offset == 0:
        if mesh is not None:
            from flink_tpu.parallel.mesh_windows import MeshTumblingWindows
            return MeshTumblingWindows(
                agg, assigner.size, mesh, axis=mesh_axis,
                max_parallelism=max_parallelism,
                capacity_per_window_shard=max(
                    1 << 8, initial_capacity // mesh.shape[mesh_axis]))
        return VectorizedTumblingWindows(agg, assigner.size,
                                         initial_capacity=initial_capacity)
    if isinstance(assigner, SlidingEventTimeWindows):
        if assigner.size % assigner.slide == 0 and assigner.offset == 0:
            if mesh is not None:
                from flink_tpu.parallel.mesh_windows import (
                    MeshSlidingWindows,
                )
                return MeshSlidingWindows(
                    agg, assigner.size, assigner.slide, mesh,
                    axis=mesh_axis, max_parallelism=max_parallelism,
                    capacity_per_window_shard=max(
                        1 << 8, initial_capacity // mesh.shape[mesh_axis]))
            return VectorizedSlidingWindows(agg, assigner.size,
                                            assigner.slide,
                                            initial_capacity=initial_capacity)
        return None
    if isinstance(assigner, EventTimeSessionWindows):
        return VectorizedSessionWindows(agg, assigner.gap,
                                        initial_capacity=initial_capacity)
    return None


def is_mesh_factory(mesh) -> bool:
    """True for a callable that BUILDS a mesh (the pod-topology
    per-process factory) as opposed to a Mesh instance — jax's Mesh is
    itself callable (a context decorator), so `callable` alone cannot
    discriminate; factories have no device grid `.shape`."""
    return callable(mesh) and not hasattr(mesh, "shape")


def resolve_mesh(mesh):
    """Mesh | mesh-factory | None → Mesh | None (factories resolve in
    the CURRENT process; device handles cannot ride a pickled graph)."""
    return mesh() if is_mesh_factory(mesh) else mesh


def is_device_eligible(assigner, aggregate_function, trigger, evictor,
                       allowed_lateness, late_tag, window_function) -> bool:
    """The graph-builder gate for the device fast path."""
    if not isinstance(aggregate_function, DeviceAggregateFunction):
        return False
    if trigger is not None or evictor is not None:
        return False
    if allowed_lateness != 0 or late_tag is not None:
        return False
    if window_function is not None and not callable(window_function):
        return False
    if isinstance(assigner, SlidingEventTimeWindows):
        return assigner.size % assigner.slide == 0 and assigner.offset == 0
    if isinstance(assigner, TumblingEventTimeWindows):
        return assigner.offset == 0
    return isinstance(assigner, EventTimeSessionWindows)


class DeviceWindowOperator(StreamOperator):
    """Batched, device-backed twin of WindowOperator for the eligible
    aggregate path.  The key selector is applied per record at buffer
    time (the operator IS the keyed state; no keyed backend needed)."""

    def __init__(self, assigner, aggregate_function: DeviceAggregateFunction,
                 window_function=None, flush_batch: int = 8192,
                 initial_capacity: int = 1 << 14, mesh=None,
                 mesh_axis: str = "kg"):
        super().__init__()
        self.assigner = assigner
        self.agg = aggregate_function
        self.window_function = window_function
        self.flush_batch = flush_batch
        self.initial_capacity = initial_capacity
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        self.engine = None
        self._keys: List[Any] = []
        self._ts: List[int] = []
        self._values: List[Any] = []
        self._last_fireable = None
        self.num_late_records_dropped = 0  # metric parity
        # string keys dictionary-encode to dense uint64 ids in ONE C++
        # pass per batch (native.NativeStringInterner), so
        # keyBy("word") over strings rides the integer-keyed fast
        # tiers; emission maps ids back through _id_to_key (ref shape:
        # SocketWindowWordCount.java:70-84)
        self._interner = None
        self._id_to_key: List[Any] = []

    # ---- lifecycle --------------------------------------------------
    def open(self):
        if not assigner_supported(self.assigner):
            # fail fast at open, not at the first flush
            raise ValueError(
                f"no device engine for assigner {self.assigner!r}")
        self.collector = TimestampedCollector(self.output)
        # metric parity with the scalar WindowOperator (ref:
        # WindowOperator.java:138 numLateRecordsDropped); reset = this
        # execution attempt
        self._emit_batch_hist = None
        if self.metrics is not None:
            c = self.metrics.counter("numLateRecordsDropped")
            c.count = 0
            self._emit_batch_hist = self.metrics.histogram("emitBatchSize")

    # ---- input ------------------------------------------------------
    def set_key_context(self, record):
        pass  # no keyed backend; keys resolve vectorized at flush

    def process_element(self, record: StreamRecord):
        if record.timestamp is None:
            raise ValueError(
                "device window operator requires event-time records "
                "(assign timestamps upstream)")
        self._keys.append(self.key_selector.get_key(record.value)
                          if self.key_selector is not None else record.value)
        self._ts.append(record.timestamp)
        self._values.append(record.value)
        if len(self._keys) >= self.flush_batch:
            self._flush_buffer()

    def _wants_fused_string_sum(self) -> bool:
        from flink_tpu.ops.device_agg import SumAggregate
        from flink_tpu.streaming.log_windows import StringSumTumblingWindows
        if self.engine is not None:
            # locked at first flush; later batches must keep feeding
            # the fused engine raw strings
            return isinstance(self.engine, StringSumTumblingWindows)
        return (self.mesh is None
                and isinstance(self.agg, SumAggregate)
                and np.issubdtype(self.agg.value_dtype, np.floating)
                and isinstance(self.assigner, TumblingEventTimeWindows)
                and self.assigner.offset == 0)

    def _ensure_engine(self, keys_arr: np.ndarray):
        """Tier selection on the first flush: integer-keyed streams get
        the log-structured combiner tier when the aggregate has a cell
        decomposition (string keys reach it through the interner);
        string-keyed tumbling sums get the fused wordcount engine;
        everything else (and every aggregate the log tier doesn't
        cover) runs the device-resident scatter tier.  With a mesh,
        the sharded twins take over: the mesh log tier (all_to_all
        keyBy exchange + per-shard log fires, parallel/mesh_log.py)
        when eligible, else the sharded scatter engines."""
        if self.engine is not None:
            return
        self.mesh = resolve_mesh(self.mesh)
        if self.mesh is not None:
            if np.issubdtype(keys_arr.dtype, np.integer):
                from flink_tpu.parallel.mesh_log import (
                    mesh_log_engine_for_assigner,
                )
                self.engine = mesh_log_engine_for_assigner(
                    self.assigner, self.agg, self.mesh,
                    axis=self.mesh_axis,
                    max_parallelism=self.max_parallelism)
            if self.engine is None:
                self.engine = engine_for_assigner(
                    self.assigner, self.agg, self.initial_capacity,
                    mesh=self.mesh, mesh_axis=self.mesh_axis,
                    max_parallelism=self.max_parallelism)
            if self.engine is None:
                raise ValueError(
                    f"no device engine for assigner {self.assigner!r}")
        if self.engine is None \
                and keys_arr.dtype.kind in "US" and keys_arr.ndim == 1 \
                and self._wants_fused_string_sum():
            self.engine = string_sum_engine_for_assigner(self.assigner,
                                                         self.agg)
        if self.engine is None and np.issubdtype(keys_arr.dtype, np.integer):
            self.engine = log_engine_for_assigner(self.assigner, self.agg)
        if self.engine is None:
            self.engine = engine_for_assigner(self.assigner, self.agg,
                                              self.initial_capacity)
        if self.engine is None:
            raise ValueError(
                f"no device engine for assigner {self.assigner!r}")
        # fast-forward a lazily created engine to the operator's
        # watermark — records behind it must count as LATE, not be
        # aggregated into windows that already passed downstream
        wm = getattr(self, "current_watermark", None)
        if wm is not None and wm > -(2 ** 63):
            self.engine.advance_watermark(wm)

    def _flush_buffer(self):
        if not self._keys:
            return
        tracer = get_tracer()
        if tracer.enabled:
            with tracer.span("device_window.flush",
                             batch=len(self._keys)):
                self._flush_buffer_inner()
        else:
            self._flush_buffer_inner()

    def _flush_buffer_inner(self):
        agg = self.agg
        extract = agg.extract_value
        # overridden either on the class or per-instance (a plain
        # function set on the instance has no __func__)
        if getattr(extract, "__func__",
                   None) is not DeviceAggregateFunction.extract_value:
            values = [extract(v) for v in self._values]
        else:
            values = self._values
        if agg.needs_value or agg.needs_value_hash:
            vals = np.asarray(values)
        else:
            vals = None
        keys_arr = self._maybe_intern(np.asarray(self._keys))
        self._ensure_engine(keys_arr)
        self.engine.process_batch(
            keys_arr,
            np.asarray(self._ts, np.int64),
            vals)
        self._keys.clear()
        self._ts.clear()
        self._values.clear()

    def _maybe_intern(self, keys_arr: np.ndarray) -> np.ndarray:
        """Dictionary-encode fixed-width string keys to dense uint64
        ids (first batch decides; later batches coerce to the locked
        representation).  Without the native runtime the raw keys pass
        through to the object-key fallback path."""
        if self._interner is None:
            # 1-D only: composite keys coerce to 2-D string arrays
            # whose rows must stay tuples on emission
            if keys_arr.dtype.kind not in "US" or keys_arr.ndim != 1:
                return keys_arr
            import flink_tpu.native as nat
            if not nat.available():
                return keys_arr
            if self._wants_fused_string_sum():
                # the fused wordcount engine consumes raw strings
                # (intern + dense sum in one C++ pass) and emits the
                # original words itself
                return keys_arr
            self._interner = nat.NativeStringInterner()
        elif keys_arr.dtype.kind not in "US":
            keys_arr = keys_arr.astype(np.str_)
        ids, first_idx = self._interner.intern(keys_arr)
        if len(first_idx):
            self._id_to_key.extend(keys_arr[first_idx].tolist())
        return ids

    def process_watermark(self, watermark: Watermark):
        # Fires only happen when the watermark crosses a window-end
        # boundary (multiples of size/slide for the aligned engines).
        # Upstreams may emit a watermark per ELEMENT; paying a device
        # flush + advance for each would serialize the pipeline on
        # per-record device dispatches.  Between boundaries nothing can
        # fire, so the watermark forwards without touching the engine.
        wm = watermark.timestamp
        grid = self._fire_grid()
        if grid is not None and wm != MAX_TIMESTAMP:
            fireable = ((wm + 1) // grid) * grid if wm >= 0 else None
            if fireable is not None and fireable == self._last_fireable:
                self.current_watermark = wm
                self.output.emit_watermark(watermark)
                return
            self._last_fireable = fireable
        self._flush_buffer()
        if self.engine is not None:
            before = len(self.engine.emitted)
            tracer = get_tracer()
            if tracer.enabled:
                with tracer.span("device_window.fire", watermark=wm):
                    self.engine.advance_watermark(wm)
                    self._emit_from(before)
            else:
                self.engine.advance_watermark(wm)
                self._emit_from(before)
            self.num_late_records_dropped = self.engine.num_late_dropped
            if self.metrics is not None:
                self.metrics.counter(
                    "numLateRecordsDropped").count = \
                    self.engine.num_late_dropped
        self.current_watermark = wm
        self.output.emit_watermark(watermark)

    def _fire_grid(self):
        """Window-end alignment grid of the assigner, or None when
        fires can happen at arbitrary times (sessions)."""
        if isinstance(self.assigner, SlidingEventTimeWindows):
            return self.assigner.slide
        if isinstance(self.assigner, TumblingEventTimeWindows):
            return self.assigner.size
        return None

    def _emit_from(self, start_idx: int):
        emitted = self.engine.emitted
        if self._emit_batch_hist is not None and len(emitted) > start_idx:
            self._emit_batch_hist.update(len(emitted) - start_idx)
        fn = self.window_function
        id_to_key = self._id_to_key if self._interner is not None else None
        for key, result, w_start, w_end in emitted[start_idx:]:
            self.collector.set_absolute_timestamp(w_end - 1)
            if fn is None:
                self.collector.collect(result)
            else:
                if id_to_key is not None:
                    key = id_to_key[int(key)]
                out = fn(key, TimeWindow(w_start, w_end), [result])
                if out is not None:
                    for v in out:
                        self.collector.collect(v)
        # emitted results are delivered; drop them so buffers don't grow
        del emitted[start_idx:]

    # ---- checkpoint -------------------------------------------------
    def snapshot_state(self, checkpoint_id: Optional[int] = None) -> dict:
        self._flush_buffer()
        snap = super().snapshot_state(checkpoint_id)
        if self.engine is not None:
            from flink_tpu.parallel.mesh_log import _MeshShardedLogEngine
            from flink_tpu.streaming import log_windows as lw
            snap["device_engine"] = self.engine.snapshot()
            if isinstance(self.engine, lw.StringSumTumblingWindows):
                snap["device_tier"] = "string_sum"
            elif isinstance(self.engine, _MeshShardedLogEngine):
                snap["device_tier"] = "mesh_log"
            elif isinstance(self.engine, (lw.LogStructuredTumblingWindows,
                                          lw.LogStructuredSessionWindows)):
                snap["device_tier"] = "log"
            else:
                snap["device_tier"] = "vectorized"
        if self._interner is not None:
            # ids are dense first-seen: the directory alone rebuilds
            # the interner on restore (re-interning in order
            # reproduces every id)
            snap["string_key_directory"] = list(self._id_to_key)
        return snap

    def _kg_keep_fn(self):
        """Key-group filter for rescaled restores (the shared
        definition, so re-split engine state lands where the runtime's
        keyBy partitioner routes live records)."""
        from flink_tpu.core.keygroups import make_key_group_keep_fn
        return make_key_group_keep_fn(self.max_parallelism,
                                      self.num_subtasks,
                                      self.subtask_index)

    def restore_state(self, snapshots) -> None:
        super().restore_state(snapshots)
        engine_snaps = [s for s in snapshots if "device_engine" in s]
        rescaled = any(
            s.get("restore_old_parallelism", self.num_subtasks)
            != self.num_subtasks for s in snapshots)
        if rescaled or len(engine_snaps) > 1:
            if any(s.get("string_key_directory") is not None
                   for s in snapshots):
                raise ValueError(
                    "device window operator cannot re-split "
                    "dictionary-encoded string-keyed engine state "
                    "across a parallelism change; restore at the "
                    "checkpointed parallelism")
            tiers = {s.get("device_tier") for s in engine_snaps}
            if len(tiers) > 1:
                raise ValueError(
                    f"snapshots span engine tiers {sorted(tiers)}")
            if engine_snaps:
                tier = tiers.pop()
                if self.engine is None:
                    if tier == "log":
                        self.engine = log_engine_for_assigner(
                            self.assigner, self.agg)
                    elif tier == "string_sum":
                        self.engine = string_sum_engine_for_assigner(
                            self.assigner, self.agg)
                    if self.engine is None \
                            or not hasattr(self.engine, "restore_many"):
                        raise ValueError(
                            f"the {tier!r} engine tier cannot re-split "
                            "its state across a parallelism change; "
                            "restore at the checkpointed parallelism")
                self.engine.restore_many(
                    [s["device_engine"] for s in engine_snaps],
                    keep_fn=self._kg_keep_fn())
            return
        for s in snapshots:
            if s.get("string_key_directory") is not None:
                import flink_tpu.native as nat
                directory = s["string_key_directory"]
                self._interner = nat.NativeStringInterner(
                    max(16, 2 * len(directory)))
                self._id_to_key = list(directory)
                if directory:
                    ids, _ = self._interner.intern(np.asarray(directory))
                    assert int(ids[-1]) == len(directory) - 1
            if "device_engine" in s:
                if self.engine is None:
                    if s.get("device_tier") == "string_sum":
                        from flink_tpu.streaming.log_windows import (
                            StringSumTumblingWindows,
                        )
                        self.engine = StringSumTumblingWindows(
                            self.agg, self.assigner.size)
                    elif s.get("device_tier") == "log":
                        self.engine = log_engine_for_assigner(
                            self.assigner, self.agg)
                        if self.engine is None:
                            raise RuntimeError(
                                "checkpoint was taken on the log engine "
                                "tier, which is unavailable here (native "
                                "runtime required)")
                    elif s.get("device_tier") == "mesh_log":
                        from flink_tpu.parallel.mesh_log import (
                            mesh_log_engine_for_assigner,
                        )
                        self.mesh = resolve_mesh(self.mesh)
                        if self.mesh is None:
                            raise RuntimeError(
                                "checkpoint was taken on the mesh log "
                                "tier; restoring requires a mesh "
                                "(env.set_mesh)")
                        self.engine = mesh_log_engine_for_assigner(
                            self.assigner, self.agg, self.mesh,
                            axis=self.mesh_axis,
                            max_parallelism=self.max_parallelism)
                        if self.engine is None:
                            raise RuntimeError(
                                "checkpoint was taken on the mesh log "
                                "tier, which is unavailable here "
                                "(native runtime required)")
                    else:
                        self.engine = engine_for_assigner(
                            self.assigner, self.agg, self.initial_capacity,
                            mesh=self.mesh, mesh_axis=self.mesh_axis,
                            max_parallelism=self.max_parallelism)
                self.engine.restore(s["device_engine"])
