"""Stream partitioners: how records pick a downstream channel.

Re-designs flink-streaming-java/.../runtime/partitioner/ (10 files:
KeyGroupStreamPartitioner, ForwardPartitioner, RebalancePartitioner,
RescalePartitioner, BroadcastPartitioner, ShufflePartitioner,
GlobalPartitioner, CustomPartitionerWrapper).  select_channels returns
the list of target channel indices for one record;
select_channels_batch is the vectorized twin the batched router fan-out
uses — one numpy index per record, bit-identical to running
select_channels record by record.
"""

from __future__ import annotations

import abc
import random
from typing import Any, Callable, List, Optional

import numpy as np

from flink_tpu.core.functions import KeySelector
from flink_tpu.core.keygroups import (
    assign_operator_indexes_np,
    assign_to_key_group,
    compute_operator_index_for_key_group,
    splitmix64_np,
    stable_hash64,
    stable_hashes_np,
)


def _routing_hashes(keys: list) -> np.ndarray:
    """64-bit stable hash per key, EXACTLY matching `stable_hash64` —
    shared with key-group assignment in core.keygroups so routing and
    state bucketing can never disagree."""
    return stable_hashes_np(keys)


class StreamPartitioner(abc.ABC):
    is_broadcast = False
    is_pointwise = False
    #: True ⇒ unicast and safe to route a whole emit batch at once via
    #: select_channels_batch (multicast partitioners stay per-record)
    supports_batch = False

    @abc.abstractmethod
    def select_channels(self, value: Any, num_channels: int) -> List[int]:
        ...

    def select_channels_batch(self, values: list,
                              num_channels: int) -> np.ndarray:
        """One channel index per value.  Default: the scalar loop;
        the hot partitioners (Hash/Rebalance/Rescale/Forward/Global)
        override with vectorized math."""
        out = np.empty(len(values), np.int64)
        for i, v in enumerate(values):
            out[i] = self.select_channels(v, num_channels)[0]
        return out

    def split_batch(self, batch, num_channels: int):
        """Route a whole RecordBatch element: list of (channel_index,
        sub_batch) pairs, rows in original order within each channel.
        None ⇒ no batch split for this partitioner (the router boxes
        the batch and takes the per-record path)."""
        return None

    def setup(self, num_channels: int) -> None:  # noqa: B027
        pass


class ForwardPartitioner(StreamPartitioner):
    """Local forward, requires equal parallelism (ref: ForwardPartitioner)."""

    is_pointwise = True
    supports_batch = True

    def select_channels(self, value, num_channels):
        return [0]

    def select_channels_batch(self, values, num_channels):
        return np.zeros(len(values), np.int64)

    def split_batch(self, batch, num_channels):
        return [(0, batch)]

    def __repr__(self):
        return "FORWARD"


class RebalancePartitioner(StreamPartitioner):
    """Round-robin (ref: RebalancePartitioner)."""

    supports_batch = True

    def __init__(self):
        self._next = -1

    def setup(self, num_channels):
        self._next = random.randrange(num_channels) - 1 if num_channels else -1

    def select_channels(self, value, num_channels):
        self._next = (self._next + 1) % num_channels
        return [self._next]

    def select_channels_batch(self, values, num_channels):
        idx = ((self._next + 1 + np.arange(len(values), dtype=np.int64))
               % num_channels)
        if len(values):
            self._next = int(idx[-1])
        return idx

    def split_batch(self, batch, num_channels):
        # rebalance at BATCH granularity: one whole batch per channel,
        # round robin — load still spreads (batches are uniform-sized)
        # without paying a per-row scatter on a keyless exchange
        self._next = (self._next + 1) % num_channels
        return [(self._next, batch)]

    def __repr__(self):
        return "REBALANCE"


class RescalePartitioner(StreamPartitioner):
    """Round-robin within local groups (ref: RescalePartitioner) —
    pointwise wiring is decided by the scheduler; per-instance this is
    round-robin over its subset."""

    is_pointwise = True
    supports_batch = True

    def __init__(self):
        self._next = -1

    def select_channels(self, value, num_channels):
        self._next = (self._next + 1) % num_channels
        return [self._next]

    def select_channels_batch(self, values, num_channels):
        idx = ((self._next + 1 + np.arange(len(values), dtype=np.int64))
               % num_channels)
        if len(values):
            self._next = int(idx[-1])
        return idx

    def split_batch(self, batch, num_channels):
        self._next = (self._next + 1) % num_channels
        return [(self._next, batch)]

    def __repr__(self):
        return "RESCALE"


class ShufflePartitioner(StreamPartitioner):
    """Uniform random (ref: ShufflePartitioner)."""

    supports_batch = True  # unicast; the default scalar-loop batch path

    def select_channels(self, value, num_channels):
        return [random.randrange(num_channels)]

    def split_batch(self, batch, num_channels):
        # uniform-random at batch granularity (same spirit as the
        # per-record shuffle: no key affinity to preserve)
        return [(random.randrange(num_channels), batch)]

    def __repr__(self):
        return "SHUFFLE"


class BroadcastPartitioner(StreamPartitioner):
    """All channels (ref: BroadcastPartitioner)."""

    is_broadcast = True
    #: the batched router replicates a whole buffered batch to every
    #: channel instead of fanning per record
    broadcast_all = True

    def select_channels(self, value, num_channels):
        return list(range(num_channels))

    def __repr__(self):
        return "BROADCAST"


class GlobalPartitioner(StreamPartitioner):
    """Everything to subtask 0 (ref: GlobalPartitioner)."""

    supports_batch = True

    def select_channels(self, value, num_channels):
        return [0]

    def select_channels_batch(self, values, num_channels):
        return np.zeros(len(values), np.int64)

    def split_batch(self, batch, num_channels):
        return [(0, batch)]

    def __repr__(self):
        return "GLOBAL"


def _batch_row_value(batch, i):
    """Row i of a RecordBatch as the scalar path would see it."""
    arrays = tuple(batch.cols.values())
    if batch.is_scalar:
        x = arrays[0][i]
        return x.item() if isinstance(x, np.generic) else x
    return tuple(x.item() if isinstance(x, np.generic) else x
                 for x in (a[i] for a in arrays))


class KeyGroupStreamPartitioner(StreamPartitioner):
    """hash(key) → key group → operator index
    (ref: KeyGroupStreamPartitioner.java)."""

    supports_batch = True

    def __init__(self, key_selector: KeySelector, max_parallelism: int):
        self.key_selector = key_selector
        self.max_parallelism = max_parallelism
        #: vectorized key-selector state: None = undecided, True =
        #: selector rides columns (probe passed), False = per-row keys
        self._key_kernel = None

    def select_channels(self, value, num_channels):
        key = self.key_selector.get_key(value)
        kg = assign_to_key_group(key, self.max_parallelism)
        return [compute_operator_index_for_key_group(
            self.max_parallelism, num_channels, kg)]

    def select_channels_batch(self, values, num_channels):
        get_key = self.key_selector.get_key
        hashes = _routing_hashes([get_key(v) for v in values])
        return assign_operator_indexes_np(hashes, self.max_parallelism,
                                          num_channels)

    def split_batch(self, batch, num_channels):
        """The columnar keyBy exchange: ONE hash pass over the key
        column (vectorized selector when liftable, else per-row keys),
        one stable argsort, gathered sub-batches per channel.  Hash
        parity with the scalar path is exact: int64 key columns take
        the same splitmix64 arithmetic `_routing_hashes` applies to
        all-int key lists."""
        n = len(batch)
        if n == 0:
            return []
        pre = batch.routing
        if pre is not None and pre.shape == (n,):
            # a fused chain program already hashed the key column on
            # device (same splitmix64 arithmetic, verified by its
            # probe) — skip the host hash pass entirely
            hashes = pre
        else:
            keys = self._vector_keys(batch, n)
            if keys is not None:
                hashes = splitmix64_np(keys)
            else:
                get_key = self.key_selector.get_key
                hashes = _routing_hashes(
                    [get_key(v) for v in batch.row_values()])
        idx = assign_operator_indexes_np(hashes, self.max_parallelism,
                                         num_channels)
        order = np.argsort(idx, kind="stable")
        bounds = np.searchsorted(idx[order], np.arange(num_channels + 1))
        out = []
        for c in range(num_channels):
            lo, hi = int(bounds[c]), int(bounds[c + 1])
            if lo < hi:
                # stable sort ⇒ order[lo:hi] ascends ⇒ original row
                # order per channel is preserved
                out.append((c, batch.take(order[lo:hi])))
        return out

    def _vector_keys(self, batch, n):
        """int64 ndarray from the vectorized selector, or None (per-
        row path).  Only int64 columns qualify — any other key type
        must hash through scalar stable_hash64 for routing parity."""
        kk = self._key_kernel
        if kk is False:
            return None
        if kk is None and not self._decide_key_kernel():
            return None
        try:
            out = self.key_selector.get_key(batch.value_arrays())
        except Exception:  # noqa: BLE001
            self._key_kernel = False
            return None
        if not (isinstance(out, np.ndarray) and out.shape == (n,)
                and out.dtype == np.int64):
            self._key_kernel = False
            return None
        if kk is None:
            # first batch: probe the edge rows against the scalar
            # selector before trusting the vectorized keys
            get_key = self.key_selector.get_key
            for i in (0, n - 1):
                if get_key(_batch_row_value(batch, i)) != int(out[i]):
                    self._key_kernel = False
                    return None
            self._key_kernel = True
        return out

    def _decide_key_kernel(self) -> bool:
        from flink_tpu.core.functions import _FieldKeySelector
        sel = self.key_selector
        if isinstance(sel, _FieldKeySelector) \
                and isinstance(sel._field, int):
            return True  # positional field access: column indexing
        try:
            from flink_tpu.analysis.liftability import (
                LIFTABLE,
                analyze_udf,
            )
            fn = getattr(sel, "_fn", None)
            if not callable(fn):
                fn = getattr(sel, "get_key", sel)
            if analyze_udf(fn).verdict == LIFTABLE:
                return True
        except Exception:  # noqa: BLE001
            pass
        self._key_kernel = False
        return False

    def __repr__(self):
        return "HASH"


class TaggedBroadcastPartitioner(StreamPartitioner):
    """Per-record multicast for tagged (input_index, value) carriers:
    inputs in `broadcast_tags` replicate to EVERY channel (a join's
    broadcast build side), the rest spread round-robin (the probe
    side) — the batch optimizer's BROADCAST ship strategy riding one
    union edge (ref: ShipStrategyType.BROADCAST)."""

    is_broadcast = True  # channel capacity accounting: may multicast

    def __init__(self, broadcast_tags):
        self.broadcast_tags = frozenset(broadcast_tags)
        self._rr = 0

    def select_channels(self, value, num_channels):
        if value[0] in self.broadcast_tags:
            return list(range(num_channels))
        self._rr = (self._rr + 1) % num_channels
        return [self._rr]

    def __repr__(self):
        return f"TAGGED_BROADCAST{sorted(self.broadcast_tags)}"


class CustomPartitionerWrapper(StreamPartitioner):
    """(ref: CustomPartitionerWrapper.java) — partitioner(key,
    num_channels) -> channel."""

    def __init__(self, partitioner: Callable[[Any, int], int],
                 key_selector: Optional[KeySelector] = None):
        self.partitioner = partitioner
        self.key_selector = key_selector

    def select_channels(self, value, num_channels):
        key = self.key_selector.get_key(value) if self.key_selector else value
        return [self.partitioner(key, num_channels) % num_channels]

    def __repr__(self):
        return "CUSTOM"
