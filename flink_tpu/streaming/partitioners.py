"""Stream partitioners: how records pick a downstream channel.

Re-designs flink-streaming-java/.../runtime/partitioner/ (10 files:
KeyGroupStreamPartitioner, ForwardPartitioner, RebalancePartitioner,
RescalePartitioner, BroadcastPartitioner, ShufflePartitioner,
GlobalPartitioner, CustomPartitionerWrapper).  select_channels returns
the list of target channel indices for one record.
"""

from __future__ import annotations

import abc
import random
from typing import Any, Callable, List, Optional

from flink_tpu.core.functions import KeySelector
from flink_tpu.core.keygroups import (
    assign_to_key_group,
    compute_operator_index_for_key_group,
)


class StreamPartitioner(abc.ABC):
    is_broadcast = False
    is_pointwise = False

    @abc.abstractmethod
    def select_channels(self, value: Any, num_channels: int) -> List[int]:
        ...

    def setup(self, num_channels: int) -> None:  # noqa: B027
        pass


class ForwardPartitioner(StreamPartitioner):
    """Local forward, requires equal parallelism (ref: ForwardPartitioner)."""

    is_pointwise = True

    def select_channels(self, value, num_channels):
        return [0]

    def __repr__(self):
        return "FORWARD"


class RebalancePartitioner(StreamPartitioner):
    """Round-robin (ref: RebalancePartitioner)."""

    def __init__(self):
        self._next = -1

    def setup(self, num_channels):
        self._next = random.randrange(num_channels) - 1 if num_channels else -1

    def select_channels(self, value, num_channels):
        self._next = (self._next + 1) % num_channels
        return [self._next]

    def __repr__(self):
        return "REBALANCE"


class RescalePartitioner(StreamPartitioner):
    """Round-robin within local groups (ref: RescalePartitioner) —
    pointwise wiring is decided by the scheduler; per-instance this is
    round-robin over its subset."""

    is_pointwise = True

    def __init__(self):
        self._next = -1

    def select_channels(self, value, num_channels):
        self._next = (self._next + 1) % num_channels
        return [self._next]

    def __repr__(self):
        return "RESCALE"


class ShufflePartitioner(StreamPartitioner):
    """Uniform random (ref: ShufflePartitioner)."""

    def select_channels(self, value, num_channels):
        return [random.randrange(num_channels)]

    def __repr__(self):
        return "SHUFFLE"


class BroadcastPartitioner(StreamPartitioner):
    """All channels (ref: BroadcastPartitioner)."""

    is_broadcast = True

    def select_channels(self, value, num_channels):
        return list(range(num_channels))

    def __repr__(self):
        return "BROADCAST"


class GlobalPartitioner(StreamPartitioner):
    """Everything to subtask 0 (ref: GlobalPartitioner)."""

    def select_channels(self, value, num_channels):
        return [0]

    def __repr__(self):
        return "GLOBAL"


class KeyGroupStreamPartitioner(StreamPartitioner):
    """hash(key) → key group → operator index
    (ref: KeyGroupStreamPartitioner.java)."""

    def __init__(self, key_selector: KeySelector, max_parallelism: int):
        self.key_selector = key_selector
        self.max_parallelism = max_parallelism

    def select_channels(self, value, num_channels):
        key = self.key_selector.get_key(value)
        kg = assign_to_key_group(key, self.max_parallelism)
        return [compute_operator_index_for_key_group(
            self.max_parallelism, num_channels, kg)]

    def __repr__(self):
        return "HASH"


class TaggedBroadcastPartitioner(StreamPartitioner):
    """Per-record multicast for tagged (input_index, value) carriers:
    inputs in `broadcast_tags` replicate to EVERY channel (a join's
    broadcast build side), the rest spread round-robin (the probe
    side) — the batch optimizer's BROADCAST ship strategy riding one
    union edge (ref: ShipStrategyType.BROADCAST)."""

    is_broadcast = True  # channel capacity accounting: may multicast

    def __init__(self, broadcast_tags):
        self.broadcast_tags = frozenset(broadcast_tags)
        self._rr = 0

    def select_channels(self, value, num_channels):
        if value[0] in self.broadcast_tags:
            return list(range(num_channels))
        self._rr = (self._rr + 1) % num_channels
        return [self._rr]

    def __repr__(self):
        return f"TAGGED_BROADCAST{sorted(self.broadcast_tags)}"


class CustomPartitionerWrapper(StreamPartitioner):
    """(ref: CustomPartitionerWrapper.java) — partitioner(key,
    num_channels) -> channel."""

    def __init__(self, partitioner: Callable[[Any, int], int],
                 key_selector: Optional[KeySelector] = None):
        self.partitioner = partitioner
        self.key_selector = key_selector

    def select_channels(self, value, num_channels):
        key = self.key_selector.get_key(value) if self.key_selector else value
        return [self.partitioner(key, num_channels) % num_channels]

    def __repr__(self):
        return "CUSTOM"
