"""Timer services.

Re-designs flink-streaming-java/.../api/operators/
HeapInternalTimerService.java:43 (two priority queues of
InternalTimer(timestamp, key, namespace), advanceWatermark :276-288
draining event-time timers) and runtime/tasks/
SystemProcessingTimeService.java / TestProcessingTimeService.java.

Timers are exactly-once: registering the same (key, namespace,
timestamp) twice is a no-op; they are part of operator snapshots, keyed
per key group (ref: InternalTimerServiceSerializationProxy.java).
"""

from __future__ import annotations

import abc
import heapq
import threading
import time as _time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from flink_tpu.core.keygroups import assign_to_key_group
from flink_tpu.streaming.elements import MIN_TIMESTAMP


class ProcessingTimeService(abc.ABC):
    """(ref: ProcessingTimeService.java)"""

    @abc.abstractmethod
    def get_current_processing_time(self) -> int:
        ...

    @abc.abstractmethod
    def register_timer(self, timestamp: int, callback: Callable[[int], None]):
        ...

    def shutdown(self) -> None:  # noqa: B027
        pass


class SystemProcessingTimeService(ProcessingTimeService):
    """Wall-clock timers on a scheduler thread; callbacks run under the
    owner's callback lock, mirroring how the reference fires timers
    under the checkpoint lock (SystemProcessingTimeService.java)."""

    def __init__(self, lock: Optional[threading.Lock] = None):
        self._lock = lock or threading.Lock()
        self._timers: Set[threading.Timer] = set()
        self._shutdown = False

    def get_current_processing_time(self) -> int:
        return int(_time.time() * 1000)

    def register_timer(self, timestamp: int, callback):
        delay = max(0.0, (timestamp - self.get_current_processing_time()) / 1000.0)
        t_box = []

        def fire():
            with self._lock:
                self._timers.discard(t_box[0])  # fired → drop the ref
                if not self._shutdown:
                    callback(timestamp)

        t = threading.Timer(delay, fire)
        t_box.append(t)
        t.daemon = True
        self._timers.add(t)
        t.start()
        return t

    def shutdown(self):
        with self._lock:
            self._shutdown = True
            timers = list(self._timers)
            self._timers.clear()
        for t in timers:
            t.cancel()


class PolledProcessingTimeService(ProcessingTimeService):
    """Wall-clock timers fired on the CALLER's thread via fire_due() —
    the executor loop polls it each iteration, keeping timer callbacks
    on the single-owner loop (the reference instead fires on a
    scheduler thread under the checkpoint lock,
    SystemProcessingTimeService.java)."""

    def __init__(self):
        self._queue: List[Tuple[int, int, Callable]] = []
        self._seq = 0
        # register_timer may be called from a source thread (ingestion-
        # time contexts register inside collect) while fire_due pops on
        # the executor loop — guard the heap
        self._lock = threading.Lock()

    def get_current_processing_time(self) -> int:
        return int(_time.time() * 1000)

    def register_timer(self, timestamp: int, callback):
        with self._lock:
            heapq.heappush(self._queue, (timestamp, self._seq, callback))
            self._seq += 1

    def fire_due(self) -> int:
        """Fire every timer due at the current wall clock; returns the
        number fired (loop-progress signal).  Callbacks run OUTSIDE the
        heap lock, on the caller's (executor-loop) thread."""
        now = self.get_current_processing_time()
        fired = 0
        while True:
            with self._lock:
                if not self._queue or self._queue[0][0] > now:
                    break
                ts, _, cb = heapq.heappop(self._queue)
            cb(ts)
            fired += 1
        return fired

    def fire_all_pending(self) -> None:
        """End-of-input drain for finite jobs: fire every timer
        registered at entry regardless of wall clock, bounded by the
        entry horizon so self-re-arming timers (continuous triggers)
        terminate — same contract as TestProcessingTimeService."""
        with self._lock:
            if not self._queue:
                return
            horizon = max(ts for ts, _, _ in self._queue)
        while True:
            with self._lock:
                if not self._queue or self._queue[0][0] > horizon:
                    return
                ts, _, cb = heapq.heappop(self._queue)
            cb(ts)

    def has_pending(self) -> bool:
        with self._lock:
            return bool(self._queue)


class TestProcessingTimeService(ProcessingTimeService):
    """Manually advanced clock for harness tests
    (ref: TestProcessingTimeService.java)."""

    def __init__(self):
        self._now = 0
        #: (timestamp, seq, callback) min-heap
        self._queue: List[Tuple[int, int, Callable]] = []
        self._seq = 0

    def get_current_processing_time(self) -> int:
        return self._now

    def register_timer(self, timestamp: int, callback):
        heapq.heappush(self._queue, (timestamp, self._seq, callback))
        self._seq += 1

    def set_current_time(self, now: int) -> None:
        """Advance the clock, firing due timers in order."""
        self._now = now
        while self._queue and self._queue[0][0] <= now:
            ts, _, cb = heapq.heappop(self._queue)
            cb(ts)

    def advance(self, delta: int) -> None:
        self.set_current_time(self._now + delta)

    def fire_all_pending(self) -> None:
        """Advance the clock to the latest currently-registered timer,
        firing everything due.  Timers that re-arm themselves past that
        horizon (continuous triggers) stop firing — this bounds the
        end-of-input drain of a finite job."""
        if not self._queue:
            return
        horizon = max(ts for ts, _, _ in self._queue)
        self.set_current_time(max(horizon, self._now))

    def has_pending(self) -> bool:
        return bool(self._queue)


class InternalTimer:
    __slots__ = ("timestamp", "key", "namespace")

    def __init__(self, timestamp: int, key, namespace):
        self.timestamp = timestamp
        self.key = key
        self.namespace = namespace

    def __repr__(self):
        return f"Timer({self.timestamp}, {self.key!r}, {self.namespace!r})"


class InternalTimerService:
    """Keyed event-time + processing-time timers for one operator
    (ref: HeapInternalTimerService.java)."""

    def __init__(self, name: str, keyed_backend, processing_time_service: ProcessingTimeService,
                 triggerable):
        self.name = name
        self._backend = keyed_backend
        self._pts = processing_time_service
        #: the operator: has on_event_time(timer) / on_processing_time(timer)
        self._triggerable = triggerable
        self.current_watermark = MIN_TIMESTAMP
        # heaps of (timestamp, seq, key, namespace); set for dedup
        self._event_heap: List[Tuple[int, int, Any, Any]] = []
        self._event_set: Set[Tuple[int, Any, Any]] = set()
        self._proc_heap: List[Tuple[int, int, Any, Any]] = []
        self._proc_set: Set[Tuple[int, Any, Any]] = set()
        self._seq = 0
        self._next_proc_registered: Optional[int] = None

    # ---- registration (key = backend's current key) -----------------
    def register_event_time_timer(self, namespace, timestamp: int) -> None:
        key = self._backend.current_key
        entry = (timestamp, key, namespace)
        if entry in self._event_set:
            return
        self._event_set.add(entry)
        heapq.heappush(self._event_heap, (timestamp, self._seq, key, namespace))
        self._seq += 1

    def register_event_time_timers_bulk(self, namespace, timestamp: int,
                                        keys) -> None:
        """Register the same (namespace, timestamp) timer for MANY keys
        without touching the backend's current-key context — the
        batched window path registers one trigger/cleanup timer per
        distinct key in a sub-batch.  Semantics per key are identical
        to register_event_time_timer."""
        push = heapq.heappush
        heap = self._event_heap
        seen = self._event_set
        for key in keys:
            entry = (timestamp, key, namespace)
            if entry in seen:
                continue
            seen.add(entry)
            push(heap, (timestamp, self._seq, key, namespace))
            self._seq += 1

    def delete_event_time_timer(self, namespace, timestamp: int) -> None:
        # lazy deletion: remove from the set; heap entries are skipped
        self._event_set.discard((timestamp, self._backend.current_key, namespace))

    def register_processing_time_timer(self, namespace, timestamp: int) -> None:
        key = self._backend.current_key
        entry = (timestamp, key, namespace)
        if entry in self._proc_set:
            return
        self._proc_set.add(entry)
        heapq.heappush(self._proc_heap, (timestamp, self._seq, key, namespace))
        self._seq += 1
        if self._next_proc_registered is None or timestamp < self._next_proc_registered:
            self._next_proc_registered = timestamp
            self._pts.register_timer(timestamp, self._on_processing_time)

    def delete_processing_time_timer(self, namespace, timestamp: int) -> None:
        self._proc_set.discard((timestamp, self._backend.current_key, namespace))

    def num_event_time_timers(self) -> int:
        return len(self._event_set)

    def num_processing_time_timers(self) -> int:
        return len(self._proc_set)

    # ---- firing -----------------------------------------------------
    def advance_watermark(self, watermark: int) -> None:
        """Fire all event-time timers <= watermark
        (ref: HeapInternalTimerService.advanceWatermark :276-288)."""
        self.current_watermark = watermark
        while self._event_heap and self._event_heap[0][0] <= watermark:
            ts, _, key, namespace = heapq.heappop(self._event_heap)
            entry = (ts, key, namespace)
            if entry not in self._event_set:
                continue  # deleted
            self._event_set.remove(entry)
            self._backend.set_current_key(key)
            self._triggerable.on_event_time(InternalTimer(ts, key, namespace))

    def pop_due_event_time_timers(
            self, watermark: int) -> Tuple[List[int], List[Any], List[Any]]:
        """Bulk sweep: pop EVERY due event-time timer <= watermark and
        return (timestamps, keys, namespaces) as parallel columns in
        the exact per-row order advance_watermark would have fired
        them (heap (timestamp, seq) order; lazily-deleted entries
        skipped).  The watermark advances exactly as advance_watermark
        does; FIRING is the caller's job.

        Contract: only valid when the caller's timer callbacks would
        not have registered NEW timers <= watermark mid-drain (the
        batched window fire path qualifies: the default
        EventTimeTrigger registers nothing from on_event_time) — a
        timer registered during the sweep's processing fires on the
        NEXT watermark instead of the current one."""
        self.current_watermark = watermark
        heap = self._event_heap
        live = self._event_set
        timestamps: List[int] = []
        keys: List[Any] = []
        namespaces: List[Any] = []
        pop = heapq.heappop
        while heap and heap[0][0] <= watermark:
            ts, _, key, namespace = pop(heap)
            entry = (ts, key, namespace)
            if entry not in live:
                continue  # deleted
            live.remove(entry)
            timestamps.append(ts)
            keys.append(key)
            namespaces.append(namespace)
        return timestamps, keys, namespaces

    def delete_event_time_timers_bulk(self, entries) -> None:
        """Bulk lazy delete: `entries` yields (timestamp, key,
        namespace) triples.  Semantics per entry are identical to
        delete_event_time_timer (set removal; stale heap nodes are
        skipped on pop) without touching the backend's current-key
        context — the batched fire path drops every cleaned window's
        trigger timer in one call."""
        self._event_set.difference_update(entries)

    def _on_processing_time(self, fired_at: int) -> None:
        self._next_proc_registered = None
        now = self._pts.get_current_processing_time()
        while self._proc_heap and self._proc_heap[0][0] <= now:
            ts, _, key, namespace = heapq.heappop(self._proc_heap)
            entry = (ts, key, namespace)
            if entry not in self._proc_set:
                continue
            self._proc_set.remove(entry)
            self._backend.set_current_key(key)
            self._triggerable.on_processing_time(InternalTimer(ts, key, namespace))
        if self._proc_heap:
            nxt = self._proc_heap[0][0]
            self._next_proc_registered = nxt
            self._pts.register_timer(nxt, self._on_processing_time)

    # ---- snapshot (timers are state, keyed per key group) -----------
    def snapshot(self) -> dict:
        per_kg_event: Dict[int, list] = {}
        per_kg_proc: Dict[int, list] = {}
        mp = self._backend.max_parallelism
        for ts, key, namespace in self._event_set:
            per_kg_event.setdefault(assign_to_key_group(key, mp), []).append(
                (ts, key, namespace))
        for ts, key, namespace in self._proc_set:
            per_kg_proc.setdefault(assign_to_key_group(key, mp), []).append(
                (ts, key, namespace))
        return {"watermark": self.current_watermark,
                "event": per_kg_event, "proc": per_kg_proc}

    def restore(self, snapshots: List[dict]) -> None:
        self._event_heap.clear()
        self._event_set.clear()
        self._proc_heap.clear()
        self._proc_set.clear()
        rng = self._backend.key_group_range
        saved_key = self._backend.current_key
        for snap in snapshots:
            for kg, timers in snap.get("event", {}).items():
                if not rng.contains(kg):
                    continue
                for ts, key, namespace in timers:
                    self._backend.set_current_key(key)
                    self.register_event_time_timer(namespace, ts)
            for kg, timers in snap.get("proc", {}).items():
                if not rng.contains(kg):
                    continue
                for ts, key, namespace in timers:
                    self._backend.set_current_key(key)
                    self.register_processing_time_timer(namespace, ts)
        if saved_key is not None:
            self._backend.set_current_key(saved_key)
