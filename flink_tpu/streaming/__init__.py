"""Streaming runtime + DataStream API (ref: flink-streaming-java).

  elements    StreamRecord / Watermark / StreamStatus / LatencyMarker
  windowing   windows, assigners, triggers, evictors, time
  timers      InternalTimerService (event + processing time)
  operators   operator lifecycle + stateless/keyed operators
  window_operator  WindowOperator + MergingWindowSet (session merging)
  functions   ProcessFunction, window functions, source/sink contracts
  datastream  fluent API (in flink_tpu/streaming/datastream.py)
  graph       StreamGraph -> JobGraph translation with chaining
  task        single-process StreamTask execution
  vectorized  device-resident scatter window engines (TPU HBM state)
  log_windows log-structured combiner window engines (sort + reduce)
  columnar    RecordBatch vectorized-execution tier (sources, window
              operator, explode bridge)
"""
