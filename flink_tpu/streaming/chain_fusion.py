"""Chain fusion: one jitted columnar program per typeflow-proven run.

A conclusively-proven operator chain still pays one Python dispatch —
and one host-materialized intermediate column set — per operator per
batch.  This module lowers the maximal fusable RUN of a chain (map
arithmetic, filter mask + compaction, the splitmix64 keyBy hash, and
tumbling/sliding first-pane assignment) into ONE ``traced_jit``
program: columns cross the host↔device boundary exactly twice per
batch (in once, out once) and every intermediate lives on device.

Pipeline position
-----------------
``try_fuse_subtask`` runs at the END of ``SubtaskInstance.open()`` —
after executor wiring, so the router's routes (and therefore the
downstream channel count) are compile-time constants.  It anchors a
:class:`FusedChainProgram` on the first operator of the run; the task
layer's batch dispatch (``process_batch_element`` for chain heads,
``_ChainedOutput.collect_batch`` mid-chain) checks that anchor and
hands the whole batch to the program instead of the per-operator
kernels.

What fuses
----------
* ``StreamMap`` / ``StreamFilter`` whose UDF the AOT liftability
  analyzer proved LIFTABLE (or the type-flow prover stamped
  ``_static_kernel``) and whose per-operator state machine hasn't
  locked boxed.
* When the run reaches the chain tail and the only out-route is a
  ``KeyGroupStreamPartitioner`` over a positional int key field, the
  keyBy exchange itself: splitmix64 + the 32-bit key-group avalanche
  run on device, and compaction + channel routing fold into a single
  stable sort.  The host then emits zero-copy per-channel slices.
* A tumbling/sliding ``WindowOperator`` directly after the kernel run
  in the same chain: the first-pane-start column is computed on
  device and injected via ``process_batch_fused``.

Safety contract
---------------
The per-operator ``_ColumnKernelMixin`` boxed fallback stays fully
intact.  The first batch of every new dtype signature is verified against
a full numpy twin (values, timestamps, validity masks, routing hashes,
channel bounds, pane starts — exact equality, NaN-aware) BEFORE
anything is emitted; any mismatch, trace failure, or runtime error
demotes the WHOLE chain back to per-operator dispatch with a recorded
reason.  Demotion can never produce wrong output because the failing
batch is replayed through the untouched per-operator path.

Mesh sharding
-------------
With >1 device and a large enough bucket the same program runs under
``shard_map`` on a named mesh (batch axis): each shard compacts its
row block locally and the host reassembles shard-order prefixes —
bit-identical to the single-device program, and loop-free (this env
has no ``shard_map`` replication rule for ``lax.while_loop``, so no
collective may sit behind one).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)

#: master switch (env FLINK_TPU_CHAIN_FUSION=0 disables)
FUSION_ENABLED = os.environ.get(
    "FLINK_TPU_CHAIN_FUSION", "1").lower() not in ("0", "false", "off")

#: batches below this row count take the per-operator path — a jit
#: dispatch costs more than a few small numpy passes (tests patch this)
MIN_FUSED_ROWS = 512

#: per-shard row floor before the mesh variant beats one device
MESH_MIN_ROWS_PER_SHARD = 2048


class _FusionStats:
    """Process-wide counters for the fused-chain plane."""

    def __init__(self) -> None:
        self.programs = 0        # compiled FusedChainPrograms
        self.fused_batches = 0
        self.fused_rows = 0
        self.probes = 0          # numpy-twin verifications run
        self.demotions = 0
        self.small_batches = 0   # wanted but under MIN_FUSED_ROWS
        self.last_demotion: Optional[Tuple[str, str]] = None

    def reset(self) -> None:
        self.__init__()


FUSION_STATS = _FusionStats()


class _Demoted(Exception):
    """Internal: raised inside _execute after demote() already ran."""


# ---------------------------------------------------------------------
# AOT eligibility (no jax import — safe for linters and reports)
# ---------------------------------------------------------------------

def _kernel_stage(op) -> Optional[Tuple[str, Callable, str]]:
    """(kind, fn, "") when ``op`` is a fusable map/filter stage, else
    (None, None, reason)."""
    from flink_tpu.streaming.operators import (
        StreamFilter,
        StreamMap,
        _kernel_fn,
        _udf_liftable,
    )
    if isinstance(op, StreamMap):
        kind = "map"
    elif isinstance(op, StreamFilter):
        kind = "filter"
    else:
        return None
    if op._batch_kernel is False:
        return None
    if not op._static_kernel:
        ok, _reason = _udf_liftable(op.user_function, op._KERNEL_ATTR)
        if not ok:
            return None
    return kind, _kernel_fn(op.user_function, op._KERNEL_ATTR), ""


def _window_stage_reason(op) -> Optional[str]:
    """None when ``op`` can take a fused pane column, else the reason
    it can't."""
    from flink_tpu.streaming.window_operator import (
        EvictingWindowOperator,
        WindowOperator,
    )
    if not isinstance(op, WindowOperator):
        return "not a window operator"
    if isinstance(op, EvictingWindowOperator):
        return "evicting window operator is per-row"
    reason = op._batch_eligibility()
    if reason is not None:
        return reason
    return None


def _blocker_reason(op) -> str:
    """Why ``op`` blocks fusion (for reports)."""
    from flink_tpu.streaming.operators import (
        StreamFilter,
        StreamMap,
        _udf_liftable,
    )
    if isinstance(op, (StreamMap, StreamFilter)):
        if op._batch_kernel is False:
            return (op.columnar_fallback_reason
                    or "operator locked onto the boxed path")
        if not op._static_kernel:
            ok, reason = _udf_liftable(op.user_function, op._KERNEL_ATTR)
            if not ok:
                return reason
        return "fusable"  # shouldn't be reported as a blocker
    wreason = _window_stage_reason(op)
    if wreason != "not a window operator":
        return wreason or "fusable"
    return f"{type(op).__name__} has no columnar kernel"


def select_run(operators) -> Tuple[int, int, Optional[int]]:
    """The maximal fusable run of an operator chain.

    Returns ``(start, n_kernel, window_index)``: the run covers
    ``operators[start : start + n_kernel]`` kernel stages plus, when
    ``window_index`` is not None, the window operator directly after.
    ``n_kernel == 0`` means no fusable run exists.
    """
    n = len(operators)
    start = 0
    while start < n and _kernel_stage(operators[start]) is None:
        start += 1
    k = 0
    while start + k < n and _kernel_stage(operators[start + k]) is not None:
        k += 1
    if k == 0:
        return 0, 0, None
    widx = None
    nxt = start + k
    if nxt < n and _window_stage_reason(operators[nxt]) is None:
        widx = nxt
    return start, k, widx


def fusion_report(operators) -> dict:
    """AOT fusion summary for one chain — feeds ``chain_report``,
    FT184 and ``flink_tpu lint --types``.  Never imports jax."""
    start, k, widx = select_run(operators)
    names = [getattr(op, "operator_id", "") or type(op).__name__
             for op in operators]
    if k == 0:
        blocker = None
        reason = None
        for i, op in enumerate(operators):
            stage = _kernel_stage(op)
            if stage is None and _window_stage_reason(op) is not None:
                blocker = names[i]
                reason = _blocker_reason(op)
                break
        return {"fusable": False, "fused_ops": [],
                "first_blocker": blocker, "blocker_reason": reason}
    end = (widx + 1) if widx is not None else (start + k)
    fused = names[start:end]
    blocker = None
    reason = None
    if end < len(operators):
        blocker = names[end]
        reason = _blocker_reason(operators[end])
    elif start > 0:
        # the run exists but a non-fusable prefix (usually the source)
        # keeps it from covering the whole chain — name the LAST
        # prefix op so the report explains the gap
        blocker = names[start - 1]
        reason = _blocker_reason(operators[start - 1])
    return {"fusable": True, "fused_ops": fused,
            "first_blocker": blocker, "blocker_reason": reason}


# ---------------------------------------------------------------------
# compile
# ---------------------------------------------------------------------

def try_fuse_subtask(subtask) -> None:
    """Compile and anchor a fused program for one SubtaskInstance —
    called at the end of ``SubtaskInstance.open()`` (routes wired,
    operators opened).  Never raises: any failure leaves the ordinary
    per-operator path untouched."""
    if not FUSION_ENABLED:
        return
    try:
        from flink_tpu.streaming import columnar
        if not columnar.PIPELINE_ENABLED:
            return
        ops = getattr(subtask, "operators", None)
        if not ops:
            return
        # idempotent: open() can run again after a restore
        for op in ops:
            if "_fused_chain" in op.__dict__ and op._fused_chain is not None:
                return
        program = compile_chain(ops, router=getattr(subtask, "router", None))
        if program is not None:
            program.anchor._fused_chain = program
            FUSION_STATS.programs += 1
    except Exception as e:  # noqa: BLE001
        log.warning("chain fusion disabled for subtask: %r", e)


def compile_chain(operators, router=None) -> Optional["FusedChainProgram"]:
    """Lower the maximal fusable run of ``operators`` into a
    :class:`FusedChainProgram`, or None when nothing fuses (no jax,
    no proven run, run of a single stage with no routing/window leg
    to amortize it)."""
    try:
        import jax  # noqa: F401
    except Exception:  # noqa: BLE001
        return None
    start, k, widx = select_run(operators)
    if k == 0:
        return None
    stages = []
    for op in operators[start:start + k]:
        kind, fn, _ = _kernel_stage(op)
        stages.append((kind, fn))
    window_op = operators[widx] if widx is not None else None
    kernel_ops = list(operators[start:start + k])
    tail_op = operators[widx] if widx is not None else operators[start + k - 1]

    # routing leg: only when the run ends at the chain tail and the
    # single non-side route is a key-group exchange over a positional
    # int field of the POST-map row tuple
    route_field = None
    route_channels = None
    route_part = None
    if window_op is None and start + k == len(operators) and router is not None:
        from flink_tpu.core.functions import _FieldKeySelector
        from flink_tpu.streaming.partitioners import KeyGroupStreamPartitioner
        data_routes = [r for r in getattr(router, "routes", [])
                       if r[2] is None]
        if len(data_routes) == 1:
            part, channels, _tag = data_routes[0]
            sel = getattr(part, "key_selector", None)
            if (isinstance(part, KeyGroupStreamPartitioner)
                    and not getattr(part, "broadcast_all", False)
                    and len(channels) > 1
                    and isinstance(sel, _FieldKeySelector)
                    and type(sel._field) is int):
                route_field = sel._field
                route_channels = channels
                route_part = part
    if k == 1 and window_op is None and route_field is None:
        # one kernel stage and nothing else fused: the per-operator
        # kernel is already a single vectorized pass — no win
        return None
    return FusedChainProgram(
        operators=operators, start=start, kernel_ops=kernel_ops,
        stages=stages, window_op=window_op, router=router,
        route_field=route_field, route_channels=route_channels,
        route_part=route_part, tail_op=tail_op)


# ---------------------------------------------------------------------
# the program
# ---------------------------------------------------------------------

class FusedChainProgram:
    """One compiled chain run: dtype-signature-probed jitted columnar
    program + host emission glue.  Anchored on the run's first
    operator; the task layer calls :meth:`wants` / :meth:`run`."""

    def __init__(self, operators, start, kernel_ops, stages, window_op,
                 router, route_field, route_channels, route_part, tail_op):
        self.operators = operators
        self.start = start
        self.anchor = operators[start]
        self.kernel_ops = kernel_ops
        self.stages = stages
        self.window_op = window_op
        self.router = router
        self.route_field = route_field
        self.route_channels = route_channels
        self.route_part = route_part
        self.renames = any(kind == "map" for kind, _ in stages)
        self.members = list(kernel_ops) + ([window_op] if window_op else [])
        head_id = getattr(self.anchor, "operator_id", "") \
            or type(self.anchor).__name__
        tail_id = getattr(tail_op, "operator_id", "") \
            or type(tail_op).__name__
        self.label = f"chain.{head_id}→{tail_id}"
        self.active = True
        self.demoted_reason: Optional[str] = None
        self._verified_sigs: set = set()
        self._fns: dict = {}
        #: (mode, scalar, use_mesh) → did the traced program produce
        #: tuple rows?  Written at trace time (the python body only
        #: runs then), read by the emission glue for output naming.
        self._tuple_out: dict = {}
        # mesh: largest power-of-two device prefix, batch ("rows") axis
        self.mesh = None
        self.mesh_shards = 1
        try:
            import jax
            devs = jax.devices()
            if len(devs) >= 2:
                s = 1 << (len(devs).bit_length() - 1)
                from jax.sharding import Mesh
                self.mesh = Mesh(np.array(devs[:s]), ("rows",))
                self.mesh_shards = s
        except Exception:  # noqa: BLE001
            self.mesh = None
            self.mesh_shards = 1
        for op in self.members:
            op._fused_member = self
        if self.window_op is not None:
            wassigner = self.window_op.assigner
            self._w_size = int(wassigner.size)
            self._w_slide = int(getattr(wassigner, "slide", wassigner.size))
            self._w_offset = int(wassigner.offset)
        if self.route_part is not None:
            self._r_maxpar = int(self.route_part.max_parallelism)
            self._r_nch = len(self.route_channels)

    # ---- dispatch predicate -----------------------------------------
    def wants(self, batch) -> bool:
        if not self.active:
            return False
        n = len(batch)
        if n < MIN_FUSED_ROWS:
            FUSION_STATS.small_batches += 1
            return False
        if batch.routing is not None:
            return False  # upstream already routed: shape unknown here
        if self.window_op is not None:
            # the fused pane column needs every row timestamped; the
            # per-op path handles the (rare) partially-stamped batch
            if batch.ts is None:
                return False
            m = batch.ts_mask
            if m is not None and not m.all():
                return False
        return True

    # ---- demotion ----------------------------------------------------
    def demote(self, reason: str) -> None:
        if not self.active:
            return
        self.active = False
        self.demoted_reason = reason
        FUSION_STATS.demotions += 1
        FUSION_STATS.last_demotion = (self.label, reason)
        for op in self.members:
            if op.columnar_decided_by == "fused":
                op.columnar_decided_by = None
            op._fused_member = None
        log.warning("fused chain %s demoted to per-operator dispatch: %s",
                    self.label, reason)

    # ---- run ---------------------------------------------------------
    def run(self, batch) -> None:
        """Execute the fused program on ``batch``; on ANY failure the
        chain demotes and the batch replays through the untouched
        per-operator path (nothing was emitted yet — compute-all-
        then-emit)."""
        try:
            emit = self._execute(batch)
        except _Demoted:
            self.anchor.process_batch(batch)
            return
        except Exception as e:  # noqa: BLE001
            self.demote(f"fused program raised {e!r}")
            self.anchor.process_batch(batch)
            return
        emit()

    # ---- internals ---------------------------------------------------
    def _execute(self, batch):
        import jax
        from jax.experimental import enable_x64

        from flink_tpu.runtime.device_stats import TELEMETRY, tree_nbytes

        n = len(batch)
        col_arrays = tuple(batch.cols.values())
        for name, a in batch.cols.items():
            if a.dtype.kind not in "biuf":
                self.demote(f"column {name!r} dtype {a.dtype} is not "
                            f"device-representable")
                raise _Demoted
        scalar = batch.is_scalar
        ts, tsm = batch.ts, batch.ts_mask
        use_window = self.window_op is not None and ts is not None
        use_route = self.route_field is not None

        bucket = max(MIN_FUSED_ROWS, 1 << (n - 1).bit_length())
        use_mesh = (self.mesh is not None
                    and bucket >= self.mesh_shards * MESH_MIN_ROWS_PER_SHARD)
        # routing folds into the program's sort on one device AND on
        # the mesh: per-shard partitions merge channel-major on the
        # host, which IS the global stable order (shards are position
        # ranges)
        mode = ("window" if use_window
                else ("route" if use_route else "plain"))

        valid = np.zeros(bucket, bool)
        valid[:n] = True

        def pad(a, fill=0):
            if a is None or bucket == n:
                return a
            out = np.empty(bucket, a.dtype)
            out[:n] = a
            out[n:] = fill
            return out

        p_cols = tuple(pad(a) for a in col_arrays)
        p_ts = pad(ts)
        p_tsm = pad(tsm, fill=False)

        fn = self._device_fn(mode, scalar, use_mesh)
        tel = TELEMETRY
        with enable_x64():
            args = (p_cols, p_ts, p_tsm, valid)
            if tel.enabled:
                # explicit boundary copies so the ledger shows the fused
                # region's ONLY host↔device traffic: one h2d, one d2h
                sharding = None
                if use_mesh:
                    from jax.sharding import NamedSharding
                    from jax.sharding import PartitionSpec as P
                    sharding = NamedSharding(self.mesh, P("rows"))
                t0 = time.perf_counter_ns()
                args = jax.device_put(args, sharding)
                jax.block_until_ready(args)
                tel.record_transfer("h2d", tree_nbytes(args), t0,
                                    time.perf_counter_ns(),
                                    "chain.boundary")
            try:
                outs = fn(*args)
            except _Demoted:
                raise
            except Exception as e:  # noqa: BLE001
                self.demote(f"device trace/dispatch failed: {e!r}")
                raise _Demoted from e
            if tel.enabled:
                jax.block_until_ready(outs)
                t2 = time.perf_counter_ns()
                host = jax.tree_util.tree_map(np.asarray, outs)
                tel.record_transfer("d2h", tree_nbytes(outs), t2,
                                    time.perf_counter_ns(),
                                    "chain.boundary")
            else:
                host = jax.tree_util.tree_map(np.asarray, outs)
        out_cols, out_ts, out_tsm, stage_rows, count_out, bounds, hashes, \
            pane = host
        if use_mesh:
            # per-shard kept prefixes → global arrays, shard order
            counts = np.asarray(count_out).ravel()
            count = int(counts.sum())
            m = bucket // self.mesh_shards
            if bounds is not None:
                # route: per-shard partitions [S, nch+1] gathered
                # channel-major, shard-minor — shards are position
                # ranges, so this IS the global stable route order
                b = np.asarray(bounds, np.int64)
                sel = np.concatenate(
                    [np.arange(i * m + b[i, c], i * m + b[i, c + 1])
                     for c in range(self._r_nch)
                     for i in range(self.mesh_shards)]) if count else \
                    np.zeros(0, np.int64)
                per_ch = (b[:, 1:] - b[:, :-1]).sum(axis=0)
                bounds = np.concatenate(([0], np.cumsum(per_ch)))
            else:
                sel = np.concatenate(
                    [np.arange(i * m, i * m + int(c)) for i, c
                     in enumerate(counts.tolist())]) if count else \
                    np.zeros(0, np.int64)
            gather = lambda a: a[sel] if a is not None else None  # noqa: E731
            out_cols = tuple(gather(a) for a in out_cols)
            out_ts, out_tsm = gather(out_ts), gather(out_tsm)
            hashes, pane = gather(hashes), gather(pane)
            stage_rows = np.asarray(stage_rows).reshape(
                self.mesh_shards, -1).sum(axis=0)
        else:
            count = int(count_out)
            sl = lambda a: a[:count] if a is not None else None  # noqa: E731
            out_cols = tuple(sl(a) for a in out_cols)
            out_ts, out_tsm = sl(out_ts), sl(out_tsm)
            hashes, pane = sl(hashes), sl(pane)
            stage_rows = np.asarray(stage_rows)
        if bounds is not None:
            bounds = np.asarray(bounds, np.int64)
        tuple_out = self._tuple_out[(mode, scalar, use_mesh)]

        sig = (mode, scalar, use_mesh,
               tuple(a.dtype.str for a in col_arrays),
               ts is None, tsm is None)
        if sig not in self._verified_sigs:
            self._verify(batch, n, mode, out_cols, out_ts, out_tsm,
                         count, bounds, hashes, pane)
            self._verified_sigs.add(sig)

        return self._make_emit(batch, n, mode, tuple_out, out_cols, out_ts,
                               out_tsm, stage_rows, count, bounds, hashes,
                               pane)

    # .................................................................
    def _numpy_twin(self, batch, n, mode):
        """The per-operator reference: every fused stage replayed in
        numpy on the UNPADDED batch.  Returns (cols, ts, tsm, count,
        bounds, hashes, pane) in emission order."""
        from flink_tpu.core.keygroups import (
            assign_operator_indexes_np,
            splitmix64_np,
        )
        from flink_tpu.streaming.operators import _normalize_kernel_output
        vals = batch.value_arrays()
        keep = np.ones(n, bool)
        for kind, fn in self.stages:
            out = fn(vals)
            if kind == "map":
                arrays = _normalize_kernel_output(out, n)
                if arrays is None:
                    return None
                vals = arrays
            else:
                if not (isinstance(out, np.ndarray) and out.shape == (n,)
                        and out.dtype == np.bool_):
                    return None
                keep = keep & out
        cols = vals if type(vals) is tuple else (vals,)
        eff = None
        hashes = bounds = None
        if mode in ("route", "attach"):
            if type(vals) is not tuple or self.route_field >= len(cols):
                return None  # routing leg needs tuple rows
            key = cols[self.route_field]
            if key.dtype != np.int64:
                return None
            hashes = splitmix64_np(key)
            if mode == "route":
                idx = assign_operator_indexes_np(
                    hashes, self._r_maxpar, self._r_nch)
                eff = np.where(keep, idx, self._r_nch)
        if eff is None:
            eff = np.where(keep, 0, 1)
        order = np.argsort(eff, kind="stable")
        cnt = int(keep.sum())
        kord = order[:cnt]
        if mode == "route":
            bounds = np.searchsorted(eff[order],
                                     np.arange(self._r_nch + 1))
        ref_cols = tuple(a[kord] for a in cols)
        ref_ts = batch.ts[kord] if batch.ts is not None else None
        ref_tsm = batch.ts_mask[kord] if batch.ts_mask is not None else None
        # route mode drops the hash column on device (consumed by the
        # partition) — mirror that, the bounds carry the verification
        ref_h = (hashes[kord] if hashes is not None and mode != "route"
                 else None)
        ref_pane = None
        if mode == "window" and ref_ts is not None:
            t = ref_ts.astype(np.int64)
            ref_pane = t - ((t - self._w_offset) % self._w_slide)
        return ref_cols, ref_ts, ref_tsm, cnt, bounds, ref_h, ref_pane

    @staticmethod
    def _arr_eq(a, b) -> bool:
        if a is None or b is None:
            return a is None and b is None
        a, b = np.asarray(a), np.asarray(b)
        if a.dtype != b.dtype or a.shape != b.shape:
            return False
        if a.dtype.kind == "f":
            return bool(np.array_equal(a, b, equal_nan=True))
        return bool(np.array_equal(a, b))

    def _verify(self, batch, n, mode, out_cols, out_ts, out_tsm, count,
                bounds, hashes, pane) -> None:
        """First batch per dtype signature: exact comparison against
        the numpy twin BEFORE anything is emitted.  Mismatch demotes
        the whole chain."""
        FUSION_STATS.probes += 1
        ref = self._numpy_twin(batch, n, mode)
        if ref is None:
            self.demote("probe: numpy reference not columnar "
                        "(kernel output shape or key dtype)")
            raise _Demoted
        ref_cols, ref_ts, ref_tsm, cnt, ref_bounds, ref_h, ref_pane = ref
        ok = (cnt == count
              and len(ref_cols) == len(out_cols)
              and all(self._arr_eq(a, b)
                      for a, b in zip(out_cols, ref_cols))
              and self._arr_eq(out_ts, ref_ts)
              and self._arr_eq(out_tsm, ref_tsm)
              and self._arr_eq(bounds, ref_bounds)
              and self._arr_eq(hashes, ref_h)
              and self._arr_eq(pane, ref_pane))
        if not ok:
            self.demote("probe mismatch (fused != per-operator result)")
            raise _Demoted

    # .................................................................
    def _make_emit(self, batch, n, mode, tuple_out, out_cols, out_ts,
                   out_tsm, stage_rows, count, bounds, hashes, pane):
        """Emission closure — runs OUTSIDE the demotion try/except:
        from here on the fused result is committed (it is verified or
        its signature was)."""
        from flink_tpu.streaming.elements import RecordBatch
        if self.renames:
            # map stages rename machine-style, exactly like the
            # per-operator _kernel_output_batch
            if tuple_out:
                cols = {f"f{i}": a for i, a in enumerate(out_cols)}
            else:
                cols = {"v": out_cols[0]}
        else:
            cols = dict(zip(batch.cols.keys(), out_cols))

        def emit():
            rows = stage_rows.tolist()
            for op, r in zip(self.kernel_ops, rows):
                op._note_fused(int(r))
            FUSION_STATS.fused_batches += 1
            FUSION_STATS.fused_rows += n
            if count == 0:
                return
            out = RecordBatch(cols, out_ts, out_tsm)
            if mode == "window":
                self.window_op.process_batch_fused(out, pane)
                return
            if mode == "route":
                router = self.router
                if router.records_out_counter is not None:
                    router.records_out_counter.count += count
                router.flush_records()
                channels = self.route_channels
                bl = bounds.tolist()
                for c in range(self._r_nch):
                    lo, hi = int(bl[c]), int(bl[c + 1])
                    if lo < hi:
                        channels[c].push(RecordBatch(
                            {k: a[lo:hi] for k, a in cols.items()},
                            out_ts[lo:hi] if out_ts is not None else None,
                            out_tsm[lo:hi] if out_tsm is not None else None))
                return
            if mode == "attach" and hashes is not None:
                out.routing = hashes
            self._after_output().collect_batch(out)

        return emit

    def _after_output(self):
        """Where the fused run's output goes when it doesn't terminate
        in a window/routing leg: the last fused op's own output (the
        next _ChainedOutput, or the router at chain tail)."""
        return self.kernel_ops[-1].output

    # .................................................................
    def _device_fn(self, mode, scalar, use_mesh):
        key = (mode, scalar, use_mesh)
        fn = self._fns.get(key)
        if fn is None:
            fn = self._build_fn(mode, scalar, use_mesh)
            self._fns[key] = fn
        return fn

    def _build_fn(self, mode, scalar, use_mesh):
        import jax.numpy as jnp

        from flink_tpu.runtime.tracing import traced_jit

        stages = self.stages
        route_field = self.route_field
        maxpar = getattr(self, "_r_maxpar", 0)
        nch = getattr(self, "_r_nch", 0)
        w_offset = getattr(self, "_w_offset", 0)
        w_slide = getattr(self, "_w_slide", 1)
        program = self

        def norm_map(out, nrows):
            if type(out) is tuple:
                if not out:
                    raise _trace_err("map kernel returned an empty tuple")
                cols = []
                for item in out:
                    if hasattr(item, "dtype") and hasattr(item, "shape"):
                        if tuple(item.shape) != (nrows,):
                            raise _trace_err(
                                "kernel output is not a column shape")
                        cols.append(item)
                    elif isinstance(item, (bool, int, float, np.generic)):
                        cols.append(jnp.full(nrows, item))
                    else:
                        raise _trace_err(
                            f"map output field of type "
                            f"{type(item).__name__} is not "
                            f"device-representable")
                return tuple(cols)
            if hasattr(out, "dtype") and hasattr(out, "shape"):
                if tuple(out.shape) != (nrows,):
                    raise _trace_err("kernel output is not a column shape")
                return out
            raise _trace_err("kernel output is not a column shape")

        def stable_order(eff, nrows, nclass):
            # Stable partition permutation WITHOUT argsort: sort the
            # combined key ``class * n + position`` (all values unique,
            # ties impossible) and decode with divmod.  A value sort is
            # ~5x cheaper than argsort on the XLA CPU backend and the
            # result is bit-identical to np.argsort(eff, kind="stable").
            if nclass * nrows < 2 ** 31:
                comb = eff.astype(jnp.int32) * jnp.int32(nrows) \
                    + jnp.arange(nrows, dtype=jnp.int32)
            else:
                comb = eff.astype(jnp.int64) * jnp.int64(nrows) \
                    + jnp.arange(nrows, dtype=jnp.int64)
            s = jnp.sort(comb)
            return s % nrows, s // nrows

        def body(cols, ts, tsm, valid):
            nrows = valid.shape[0]
            vals = cols[0] if scalar else cols
            keep = valid
            stage_rows = []
            for kind, fn in stages:
                stage_rows.append(keep.sum())
                out = fn(vals)
                if kind == "map":
                    vals = norm_map(out, nrows)
                else:
                    if not (hasattr(out, "dtype")
                            and out.dtype == jnp.bool_
                            and tuple(out.shape) == (nrows,)):
                        raise _trace_err(
                            "filter kernel did not produce a bool mask")
                    keep = keep & out
            out_cols = vals if type(vals) is tuple else (vals,)
            program._tuple_out[(mode, scalar, use_mesh)] = \
                type(vals) is tuple
            hashes = bounds = pane = None
            if mode in ("route", "attach"):
                if type(vals) is not tuple or route_field >= len(out_cols):
                    raise _trace_err(
                        "routing leg needs tuple rows with the key field")
                key_col = out_cols[route_field]
                if key_col.dtype != jnp.int64:
                    raise _trace_err(
                        f"key column dtype {key_col.dtype} is not int64 "
                        f"(routing parity needs the int fast path)")
                hashes = _jnp_splitmix64(key_col)
            if mode == "route":
                idx = _jnp_operator_indexes(hashes, maxpar, nch)
                # the partition consumes the hashes; rows leave already
                # grouped per channel, so nothing downstream reads them
                # — dropping the column saves a gather and a d2h copy
                hashes = None
                eff = jnp.where(keep, idx, jnp.int32(nch))
                order, cls = stable_order(eff, nrows, nch + 1)
                bounds = jnp.searchsorted(
                    cls, jnp.arange(nch + 1, dtype=cls.dtype))
            else:
                order, _ = stable_order(
                    (~keep).astype(jnp.int32), nrows, 2)
            count = keep.sum()
            g = lambda a: None if a is None else a[order]  # noqa: E731
            out_cols = tuple(g(a) for a in out_cols)
            out_ts, out_tsm = g(ts), g(tsm)
            hashes = g(hashes)
            if mode == "window" and out_ts is not None:
                t = out_ts.astype(jnp.int64)
                pane = t - ((t - w_offset) % w_slide)
            srows = (jnp.stack(stage_rows) if stage_rows
                     else jnp.zeros(0, jnp.int64))
            return out_cols, out_ts, out_tsm, srows, count, bounds, \
                hashes, pane

        if not use_mesh:
            return traced_jit(body, self.label)

        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def shard_body(cols, ts, tsm, valid):
            out_cols, out_ts, out_tsm, srows, count, b, hashes, pane = \
                body(cols, ts, tsm, valid)
            # leading shard axis for the scalars so out_specs P("rows")
            # concatenates them into [n_shards] / [n_shards, n_stages]
            # (and [n_shards, nch+1] for the per-shard route bounds)
            return (out_cols, out_ts, out_tsm, srows[None, :],
                    count[None], None if b is None else b[None, :],
                    hashes, pane)

        spec = P("rows")
        bspec = spec if mode == "route" else None
        sharded = shard_map(
            shard_body, mesh=self.mesh,
            in_specs=(spec, spec, spec, spec),
            out_specs=(spec, spec, spec, spec, spec, bspec, spec, spec),
            check_rep=False)
        return traced_jit(sharded, self.label)


def _trace_err(msg: str) -> Exception:
    return TypeError(f"chain fusion: {msg}")


# ---------------------------------------------------------------------
# jnp twins of the routing arithmetic (keygroups.py)
# ---------------------------------------------------------------------

def _jnp_splitmix64(x):
    """splitmix64 on an int64 column — bit-identical to
    ``keygroups.splitmix64_np`` / ``_routing_hashes`` int keys."""
    import jax.numpy as jnp
    z = x.astype(jnp.uint64) + jnp.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return z ^ (z >> jnp.uint64(31))


def _jnp_operator_indexes(hashes, max_parallelism, num_channels):
    """hash → key group (32-bit murmur avalanche) → operator index —
    bit-identical to ``keygroups.assign_operator_indexes_np``."""
    import jax.numpy as jnp
    m32 = jnp.uint64(0xFFFFFFFF)
    h = hashes & m32
    h = h ^ (h >> jnp.uint64(16))
    h = (h * jnp.uint64(0x85EBCA6B)) & m32
    h = h ^ (h >> jnp.uint64(13))
    h = (h * jnp.uint64(0xC2B2AE35)) & m32
    h = h ^ (h >> jnp.uint64(16))
    kg = h % jnp.uint64(max_parallelism)
    idx = (kg * jnp.uint64(num_channels)) // jnp.uint64(max_parallelism)
    return idx.astype(jnp.int32)
