"""Stream operators: lifecycle + the stateless/keyed operator family.

Re-designs flink-streaming-java/.../api/operators/:
AbstractStreamOperator (state/timer plumbing), AbstractUdfStreamOperator,
StreamMap/StreamFlatMap/StreamFilter, ProcessOperator,
KeyedProcessOperator, StreamGroupedReduce, StreamSink, and the co-
(two-input) operators.  An operator receives StreamElements from its
input(s) and emits to an `Output`; chains of operators are built by the
task layer (ref: OperatorChain.java).
"""

from __future__ import annotations

import abc
import logging
import threading
import time as _time_mod
from typing import List, Optional, TypeVar

from flink_tpu.core.functions import (
    KeySelector,
    ReduceFunction,
    RichFunction,
)
from flink_tpu.core.state import ReducingStateDescriptor, StateDescriptor
from flink_tpu.state.backend import VOID_NAMESPACE, KeyedStateBackend
from flink_tpu.state.operator_state import OperatorStateBackend
from flink_tpu.streaming.elements import (
    MAX_TIMESTAMP,
    MIN_TIMESTAMP,
    LatencyMarker,
    StreamRecord,
    Watermark,
)
from flink_tpu.streaming.timers import (
    InternalTimerService,
    ProcessingTimeService,
)

IN = TypeVar("IN")
OUT = TypeVar("OUT")

log = logging.getLogger("flink_tpu.operators")


class _KernelStats:
    """Process-wide first-batch probe accounting for the map/filter
    column kernels.  The differential typeflow suite asserts
    ``probes == 0`` for statically proven chains."""

    __slots__ = ("probes", "static_skips")

    def __init__(self):
        self.reset()

    def reset(self):
        self.probes = 0
        self.static_skips = 0


KERNEL_STATS = _KernelStats()

#: (operator class name, reason prefix) pairs already warned about —
#: the boxed fallback is once-per-class noise, not per-instance spam
_FALLBACK_WARNED = set()


class OutputTag:
    """Side-output tag (ref: org.apache.flink.util.OutputTag)."""

    __slots__ = ("tag_id",)

    def __init__(self, tag_id: str):
        self.tag_id = tag_id

    def __eq__(self, other):
        return isinstance(other, OutputTag) and self.tag_id == other.tag_id

    def __hash__(self):
        return hash(self.tag_id)

    def __repr__(self):
        return f"OutputTag({self.tag_id!r})"


class Output(abc.ABC):
    """Where an operator emits (ref: Output.java extends Collector)."""

    @abc.abstractmethod
    def collect(self, record: StreamRecord) -> None: ...

    @abc.abstractmethod
    def emit_watermark(self, watermark: Watermark) -> None: ...

    def collect_batch(self, batch) -> None:
        """Emit a whole RecordBatch element.  Default: box into
        per-row records — outputs that can carry batches natively
        (chained operators, the router) override this, so once a
        batch survives an operator nothing downstream reboxes it."""
        for record in batch.to_records():
            self.collect(record)

    def collect_side(self, tag: OutputTag, record: StreamRecord) -> None:
        pass  # dropped unless a side output is wired

    def emit_latency_marker(self, marker: LatencyMarker) -> None:  # noqa: B027
        pass

    def close(self) -> None:  # noqa: B027
        pass


class CollectorOutput(Output):
    """Buffers emissions in lists — test harness + chain tails."""

    def __init__(self):
        self.records: List[StreamRecord] = []
        self.watermarks: List[Watermark] = []
        self.side: dict = {}
        self.latency_markers: List[LatencyMarker] = []

    def collect(self, record):
        self.records.append(record)

    def emit_watermark(self, watermark):
        self.watermarks.append(watermark)

    def collect_side(self, tag, record):
        self.side.setdefault(tag.tag_id, []).append(record)

    def emit_latency_marker(self, marker):
        self.latency_markers.append(marker)

    def extract_values(self):
        return [r.value for r in self.records]


class TimestampedCollector:
    """Collector bound to one timestamp (ref:
    api/operators/TimestampedCollector.java)."""

    __slots__ = ("_output", "timestamp")

    def __init__(self, output: Output, timestamp: Optional[int] = None):
        self._output = output
        self.timestamp = timestamp

    def collect(self, value) -> None:
        self._output.collect(StreamRecord(value, self.timestamp))

    def set_absolute_timestamp(self, ts: Optional[int]) -> None:
        self.timestamp = ts


class StreamOperator(abc.ABC):
    """Operator lifecycle (ref: StreamOperator.java + lifecycle docs
    docs/internals/task_lifecycle.md): setup → open → process* →
    snapshot* → close → dispose."""

    #: chain_fusion.FusedChainProgram anchored at this operator, or
    #: None — a class attribute so the hot-path check in the task
    #: layer is one attribute load with no per-instance cost
    _fused_chain = None
    #: the FusedChainProgram this operator is a MEMBER of (any
    #: position in the chain, not just the anchor); cleared on demote
    _fused_member = None

    def __init__(self):
        self.output: Optional[Output] = None
        self.keyed_backend: Optional[KeyedStateBackend] = None
        self.operator_state_backend: Optional[OperatorStateBackend] = None
        self.processing_time_service: Optional[ProcessingTimeService] = None
        self.timer_service: Optional[InternalTimerService] = None
        self.current_watermark: int = MIN_TIMESTAMP
        self.key_selector: Optional[KeySelector] = None
        self.operator_id: str = ""
        self.metrics = None  # OperatorMetricGroup, set by task layer
        self.subtask_index: int = 0
        self.num_subtasks: int = 1
        self.max_parallelism: int = 128
        # columnar-pipeline accounting (over rows DELIVERED AS
        # BATCHES; pure row streams leave the ratio undefined)
        self.columnar_rows: int = 0
        self.boxed_rows: int = 0
        self.boxed_fallbacks: int = 0
        self.columnar_fallback_reason: Optional[str] = None
        self._boxed_fallbacks_counter = None
        # who decided the column-kernel path: "static" (typeflow
        # verdict, probe skipped), "probe" (first-batch probe) or
        # "fused" (member of a chain_fusion program)
        self.columnar_decided_by: Optional[str] = None
        self.kernel_probes: int = 0
        # rows this operator processed INSIDE a fused chain program
        # (counted into columnar_rows too: fused is a strict subset
        # of the columnar path)
        self.fused_rows: int = 0

    # ---- wiring -----------------------------------------------------
    def setup(self, output: Output,
              keyed_backend: Optional[KeyedStateBackend] = None,
              operator_state_backend: Optional[OperatorStateBackend] = None,
              processing_time_service: Optional[ProcessingTimeService] = None,
              key_selector: Optional[KeySelector] = None,
              operator_id: str = "",
              subtask_index: int = 0,
              num_subtasks: int = 1,
              max_parallelism: int = 128) -> None:
        self.output = output
        self.keyed_backend = keyed_backend
        self.operator_state_backend = operator_state_backend or OperatorStateBackend()
        self.processing_time_service = processing_time_service
        self.key_selector = key_selector
        self.operator_id = operator_id or type(self).__name__
        self.subtask_index = subtask_index
        self.num_subtasks = num_subtasks
        self.max_parallelism = max_parallelism
        if keyed_backend is not None and processing_time_service is not None:
            self.timer_service = InternalTimerService(
                f"{self.operator_id}-timers", keyed_backend,
                processing_time_service, self)

    def register_standard_metrics(self, group) -> None:
        """Attach the operator's MetricGroup and publish the standard
        pipeline-health gauges every operator gets for free:
        ``currentWatermark`` and ``watermarkLag`` (event-time vs wall
        clock, ms) — the per-operator lag the web monitor and
        Prometheus endpoint surface (ref: the reference's
        currentInputWatermark / task metric group)."""
        self.metrics = group
        group.gauge("currentWatermark", lambda: self.current_watermark)
        group.gauge("watermarkLag", self._watermark_lag_ms)
        col = group.add_group("columnar")
        col.gauge("ratio", self._columnar_ratio)
        col.gauge("fused_ratio", self._fused_ratio)
        col.gauge("fallback_reason",
                  lambda: self.columnar_fallback_reason or "")
        col.gauge("decided_by",
                  lambda: self.columnar_decided_by or "")
        col.gauge("probes", lambda: self.kernel_probes)
        self._boxed_fallbacks_counter = col.counter("boxed_fallbacks")
        self._boxed_fallbacks_counter.count = self.boxed_fallbacks

    def _columnar_ratio(self):
        total = self.columnar_rows + self.boxed_rows
        if total == 0:
            return None  # never saw a batch: ratio undefined
        return self.columnar_rows / total

    def _fused_ratio(self):
        total = self.columnar_rows + self.boxed_rows
        if total == 0:
            return None  # never saw a batch: ratio undefined
        return self.fused_rows / total

    def _note_columnar(self, n: int) -> None:
        self.columnar_rows += n

    def _note_fused(self, n: int) -> None:
        """Rows handled inside a fused chain program on this
        operator's behalf — its own kernel never dispatched."""
        self.fused_rows += n
        self.columnar_rows += n
        self.columnar_decided_by = "fused"

    def _note_boxed(self, n: int, reason: str) -> None:
        self.boxed_rows += n
        self.boxed_fallbacks += 1
        if self.columnar_fallback_reason is None:
            self.columnar_fallback_reason = reason
        if self._boxed_fallbacks_counter is not None:
            self._boxed_fallbacks_counter.inc()

    def _watermark_lag_ms(self):
        wm = self.current_watermark
        if wm <= MIN_TIMESTAMP:
            return None  # no watermark seen yet: lag undefined
        if wm >= MAX_TIMESTAMP:
            return 0.0  # final watermark: stream drained, no lag
        return max(0.0, _time_mod.time() * 1000.0 - wm)

    def open(self) -> None:  # noqa: B027
        pass

    def finish(self) -> None:  # noqa: B027
        """End of input reached (after the final watermark, before
        close): flush buffered output.  The drain-then-flush step of
        stop-with-savepoint, applied at natural end of input so finite
        jobs don't strand a 2PC sink's tail transaction."""
        pass

    def close(self) -> None:  # noqa: B027
        pass

    def dispose(self) -> None:  # noqa: B027
        pass

    # ---- elements ---------------------------------------------------
    @abc.abstractmethod
    def process_element(self, record: StreamRecord) -> None: ...

    def process_batch(self, batch) -> None:
        """Consume a whole RecordBatch.  The universal fallback boxes
        the batch into per-row records ONCE at this operator (counted
        in `columnar.boxed_fallbacks`) and runs the scalar path —
        operators with a column kernel override this.  Downstream of
        a boxing operator the stream is rows; downstream of a
        surviving operator it stays a batch."""
        self._note_boxed(
            len(batch),
            f"no batch kernel on {type(self).__name__}")
        for record in batch.to_records():
            self.set_key_context(record)
            self.process_element(record)

    def process_watermark(self, watermark: Watermark) -> None:
        """(ref: AbstractStreamOperator.processWatermark :737)"""
        self.current_watermark = watermark.timestamp
        if self.timer_service is not None:
            self.timer_service.advance_watermark(watermark.timestamp)
        self.output.emit_watermark(watermark)

    def process_latency_marker(self, marker: LatencyMarker) -> None:
        self.output.emit_latency_marker(marker)

    # ---- keyed context ----------------------------------------------
    def set_key_context(self, record: StreamRecord) -> None:
        """(ref: setKeyContextElement1 — key extraction + backend key)"""
        if self.key_selector is not None and self.keyed_backend is not None:
            self.keyed_backend.set_current_key(
                self.key_selector.get_key(record.value))

    # ---- timers (Triggerable contract) ------------------------------
    def on_event_time(self, timer) -> None:  # noqa: B027
        pass

    def on_processing_time(self, timer) -> None:  # noqa: B027
        pass

    # ---- snapshot ---------------------------------------------------
    def snapshot_state(self, checkpoint_id: Optional[int] = None) -> dict:
        snap = {}
        if self.keyed_backend is not None:
            if hasattr(self.keyed_backend, "flush_all"):
                self.keyed_backend.flush_all()
            snap["keyed"] = self.keyed_backend.snapshot()
        if self.operator_state_backend is not None:
            snap["operator"] = self.operator_state_backend.snapshot()
        if self.timer_service is not None:
            snap["timers"] = self.timer_service.snapshot()
        return snap

    def restore_state(self, snapshots: List[dict]) -> None:
        keyed = [s["keyed"] for s in snapshots if "keyed" in s]
        if keyed and self.keyed_backend is not None:
            self.keyed_backend.restore(keyed)
        ops = [s["operator"] for s in snapshots if "operator" in s]
        if ops and self.operator_state_backend is not None:
            from flink_tpu.state.operator_state import OperatorStateSnapshot
            if len(ops) == 1:
                self.operator_state_backend.restore(ops[0])
            else:
                self.operator_state_backend.restore(
                    OperatorStateSnapshot.redistribute(ops, 1)[0])
        timers = [s["timers"] for s in snapshots if "timers" in s]
        if timers and self.timer_service is not None:
            self.timer_service.restore(timers)

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:  # noqa: B027
        pass


class KeyedStateStore:
    """Adapter giving user functions keyed-state access in the VOID
    namespace (ref: DefaultKeyedStateStore.java)."""

    def __init__(self, backend: KeyedStateBackend):
        self._backend = backend

    def _bind(self, descriptor):
        return self._backend.get_partitioned_state(VOID_NAMESPACE, descriptor)

    get_value_state = _bind
    get_list_state = _bind
    get_reducing_state = _bind
    get_aggregating_state = _bind
    get_map_state = _bind


class AbstractUdfStreamOperator(StreamOperator):
    """Hosts a user function, forwarding open/close
    (ref: AbstractUdfStreamOperator.java)."""

    #: at parallelism > 1, rich functions are copied per subtask so each
    #: gets its own RuntimeContext and state (the reference serializes
    #: the function into every subtask).  At parallelism 1 the instance
    #: is shared — tests rely on reading e.g. a CollectSink's buffer.
    #: Sources opt out: their factory already deep-copies.
    COPY_UDF_PER_SUBTASK = True

    def __init__(self, user_function):
        super().__init__()
        self.user_function = user_function

    def setup(self, *args, **kwargs):
        super().setup(*args, **kwargs)
        # EVERY function is per-subtask at parallelism > 1, not just
        # RichFunctions — the reference deserializes a fresh instance
        # per task, and any stateful function (e.g. a periodic
        # watermark assigner's running max) silently corrupts its
        # siblings when shared across worker threads.  Sinks opt out
        # (COPY_UDF_PER_SUBTASK=False): tests/drivers read a shared
        # CollectSink buffer, and accumulator gathering dedupes by
        # instance.
        if self.COPY_UDF_PER_SUBTASK and self.num_subtasks > 1:
            import copy
            self.user_function = copy.deepcopy(self.user_function)

    def open(self):
        if isinstance(self.user_function, RichFunction):
            from flink_tpu.core.functions import RuntimeContext
            store = (KeyedStateStore(self.keyed_backend)
                     if self.keyed_backend is not None else None)
            ctx = RuntimeContext(
                task_name=self.operator_id,
                index_of_subtask=self.subtask_index,
                parallelism=self.num_subtasks,
                keyed_state_store=store,
                operator_state_store=self.operator_state_backend,
            )
            self.user_function.set_runtime_context(ctx)
            self.user_function.open(None)
        # CheckpointedFunction-style operator-state access for plain
        # functions (ref: FunctionInitializationContext — the seam the
        # Kafka/Kinesis consumers use for UNION offset state).  Called
        # AFTER restore_state has repopulated the backend when the
        # runtime opens operators post-restore.
        fn = self.user_function
        if hasattr(fn, "initialize_state"):
            fn.initialize_state(self)

    def finish(self):
        fn = self.user_function
        if hasattr(fn, "finish"):
            fn.finish()

    def close(self):
        if isinstance(self.user_function, RichFunction):
            self.user_function.close()

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        fn = self.user_function
        if hasattr(fn, "notify_checkpoint_complete"):
            fn.notify_checkpoint_complete(checkpoint_id)

    def snapshot_state(self, checkpoint_id: Optional[int] = None) -> dict:
        """Functions with checkpoint hooks (2PC sinks, replayable
        sources) ride in the operator snapshot (ref: the
        CheckpointedFunction path in AbstractUdfStreamOperator
        .snapshotState)."""
        snap = super().snapshot_state(checkpoint_id)
        fn = self.user_function
        if hasattr(fn, "snapshot_function_state"):
            snap["function"] = fn.snapshot_function_state(checkpoint_id)
        return snap

    def restore_state(self, snapshots) -> None:
        super().restore_state(snapshots)
        fn = self.user_function
        if hasattr(fn, "restore_function_state"):
            for s in snapshots:
                if "function" in s:
                    fn.restore_function_state(s["function"])


# ---------------------------------------------------------------------
# Columnar kernels for the stateless UDF operators: a proven-LIFTABLE
# UDF (PR 4's AOT bytecode analysis) applies directly to the batch's
# numpy columns — arithmetic bytecode vectorizes through ndarray
# operator overloading.  The first surviving batch is probe-validated
# (vectorized row vs the scalar UDF on the same row); any exception,
# shape mismatch, or probe divergence locks the operator onto the
# boxed path permanently.  Verdicts and probes are per-operator, so an
# opaque UDF boxes only its own hop.
# ---------------------------------------------------------------------

def _np_scalar(x):
    import numpy as np
    return x.item() if isinstance(x, np.generic) else x


def _batch_row_value(batch, i):
    arrays = tuple(batch.cols.values())
    if batch.is_scalar:
        return _np_scalar(arrays[0][i])
    return tuple(_np_scalar(a[i]) for a in arrays)


def _kernel_row_value(out, i):
    """Row i of a kernel result (ndarray or tuple of ndarrays)."""
    if type(out) is tuple:
        return tuple(_np_scalar(a[i]) for a in out)
    return _np_scalar(out[i])


def _same_scalar(a, b) -> bool:
    if type(a) is tuple or type(b) is tuple:
        return (type(a) is tuple and type(b) is tuple
                and len(a) == len(b)
                and all(_same_scalar(x, y) for x, y in zip(a, b)))
    if type(a) is not type(b):
        return False
    try:
        if a == b:
            return True
        return a != a and b != b  # NaN == NaN for probe purposes
    except Exception:  # noqa: BLE001
        return False


def _normalize_kernel_output(out, n):
    """Kernel result → ndarray (scalar rows) or tuple of ndarrays
    (tuple rows), broadcasting constant fields; None = not columnar."""
    import numpy as np
    if isinstance(out, np.ndarray):
        return out if out.shape == (n,) else None
    if type(out) is tuple and out:
        cols = []
        for item in out:
            if isinstance(item, np.ndarray):
                if item.shape != (n,):
                    return None
                cols.append(item)
            elif isinstance(item, (int, float, str, np.generic)):
                cols.append(np.full(n, item))
            else:
                return None
        return tuple(cols)
    return None


def _kernel_output_batch(batch, arrays):
    """Wrap normalized kernel output as a batch keeping timestamps."""
    from flink_tpu.streaming.elements import RecordBatch
    if type(arrays) is tuple:
        cols = {f"f{i}": a for i, a in enumerate(arrays)}
    else:
        cols = {"v": arrays}
    return RecordBatch(cols, batch.ts, batch.ts_mask)


def _kernel_fn(user_function, attr: str):
    """The callable the kernel path applies to column arrays: the raw
    wrapped lambda when present (lambda adapters like _LambdaFilter
    coerce their method's return — bool() chokes on a mask array), so
    the kernel runs exactly the function the analyzer proved liftable."""
    fn = getattr(user_function, "_fn", None)
    if callable(fn):
        return fn
    return getattr(user_function, attr, user_function)


def _udf_liftable(user_function, attr: str):
    """(liftable, reason) for the wrapped UDF — conclusive LIFTABLE
    from the AOT analyzer rides columns; everything else boxes."""
    fn = _kernel_fn(user_function, attr)
    try:
        from flink_tpu.analysis.liftability import LIFTABLE, analyze_udf
        rep = analyze_udf(fn)
        if rep.verdict == LIFTABLE:
            return True, ""
        return False, f"{attr} UDF not liftable ({rep.verdict}: " \
                      + "; ".join(rep.reasons[:2]) + ")"
    except Exception as e:  # noqa: BLE001
        return False, f"liftability analysis failed: {e!r}"


class _ColumnKernelMixin:
    """Shared decide/probe/fallback state machine for StreamMap and
    StreamFilter.  `_batch_kernel` is None (undecided), True (riding
    columns, probe passed or statically proven), or False (locked onto
    the boxed path).

    ``_static_kernel`` is stamped by the type-flow prover
    (:func:`flink_tpu.analysis.typeflow.apply_static`) when the whole
    dtype flow of the kernel was proven AOT — the first-batch probe is
    skipped and ``decided_by`` records "static".  The output-shape
    validation in ``_emit_kernel_result`` stays armed either way, so a
    runtime mismatch still demotes boxed with a recorded reason."""

    _batch_kernel = None
    _KERNEL_ATTR = ""
    _static_kernel = False
    _typeflow_verdict = None

    def _decide_kernel(self) -> bool:
        if self._static_kernel:
            return True
        ok, reason = _udf_liftable(self.user_function, self._KERNEL_ATTR)
        if not ok:
            self._batch_kernel = False
            self.columnar_fallback_reason = reason
        return ok

    def _kernel_fallback(self, batch, reason: str):
        self._batch_kernel = False
        self.columnar_fallback_reason = reason
        self.columnar_decided_by = None
        key = (type(self).__name__, reason.split(":")[0])
        if key not in _FALLBACK_WARNED:
            _FALLBACK_WARNED.add(key)
            verdict = self._typeflow_verdict
            log.warning(
                "%s '%s' falls back to the boxed path: %s%s",
                type(self).__name__, self.operator_id, reason,
                f" (typeflow verdict was: {verdict})" if verdict
                else "")
        StreamOperator.process_batch(self, batch)

    def process_batch(self, batch):
        n = len(batch)
        if n == 0:
            return
        decided = self._batch_kernel
        if decided is False or (decided is None
                                and not self._decide_kernel()):
            StreamOperator.process_batch(self, batch)
            return
        fn = _kernel_fn(self.user_function, self._KERNEL_ATTR)
        try:
            out = fn(batch.value_arrays())
        except Exception as e:  # noqa: BLE001
            self._kernel_fallback(batch, f"kernel raised {e!r}")
            return
        if decided is None:
            if self._static_kernel:
                # the type-flow prover certified the dtype flow AOT:
                # no probe (the emit-side shape validation still
                # demotes on any runtime divergence)
                self._batch_kernel = True
                self.columnar_decided_by = "static"
                KERNEL_STATS.static_skips += 1
            else:
                # first surviving batch: validate the vectorized
                # result against the scalar UDF on the edge rows
                # (LIFTABLE UDFs are proven pure, so replaying rows
                # is safe)
                self.kernel_probes += 1
                KERNEL_STATS.probes += 1
                err = self._probe(batch, fn, out, n)
                if err is not None:
                    self._kernel_fallback(batch, err)
                    return
                self._batch_kernel = True
                self.columnar_decided_by = "probe"
        self._emit_kernel_result(batch, out, n)


class StreamMap(_ColumnKernelMixin, AbstractUdfStreamOperator):
    """(ref: StreamMap.java)"""

    _KERNEL_ATTR = "map"

    def process_element(self, record):
        self.output.collect(record.replace(self.user_function.map(record.value)))

    def _probe(self, batch, fn, out, n):
        arrays = _normalize_kernel_output(out, n)
        if arrays is None:
            return "kernel output is not a column shape"
        for i in (0, n - 1):
            if not _same_scalar(fn(_batch_row_value(batch, i)),
                                _kernel_row_value(arrays, i)):
                return "probe mismatch (vectorized != scalar result)"
        return None

    def _emit_kernel_result(self, batch, out, n):
        arrays = _normalize_kernel_output(out, n)
        if arrays is None:
            self._kernel_fallback(batch,
                                  "kernel output is not a column shape")
            return
        self._note_columnar(n)
        self.output.collect_batch(_kernel_output_batch(batch, arrays))


class StreamFlatMap(AbstractUdfStreamOperator):
    """(ref: StreamFlatMap.java)"""

    def process_element(self, record):
        out = self.user_function.flat_map(record.value)
        if out is not None:
            for value in out:
                self.output.collect(record.replace(value))


class StreamFilter(_ColumnKernelMixin, AbstractUdfStreamOperator):
    """(ref: StreamFilter.java)"""

    _KERNEL_ATTR = "filter"

    def process_element(self, record):
        if self.user_function.filter(record.value):
            self.output.collect(record)

    def _probe(self, batch, fn, out, n):
        import numpy as np
        if not (isinstance(out, np.ndarray) and out.shape == (n,)
                and out.dtype == np.bool_):
            return "filter kernel did not produce a bool mask"
        for i in (0, n - 1):
            if bool(fn(_batch_row_value(batch, i))) != bool(out[i]):
                return "probe mismatch (vectorized != scalar result)"
        return None

    def _emit_kernel_result(self, batch, out, n):
        import numpy as np
        if not (isinstance(out, np.ndarray) and out.shape == (n,)
                and out.dtype == np.bool_):
            self._kernel_fallback(
                batch, "filter kernel did not produce a bool mask")
            return
        self._note_columnar(n)
        if out.all():
            self.output.collect_batch(batch)
        elif out.any():
            self.output.collect_batch(batch.take(out))


class StreamSink(AbstractUdfStreamOperator):
    """(ref: StreamSink.java) — user_function is a SinkFunction."""

    #: parallel sink subtasks in one process share the instance:
    #: tests/drivers read a CollectSink's buffer directly, and
    #: accumulator gathering dedupes by instance identity
    COPY_UDF_PER_SUBTASK = False

    def process_element(self, record):
        self.user_function.invoke(record.value,
                                  SinkContext(record.timestamp, self))

    def process_batch(self, batch):
        """Vectorized collect: a sink function exposing invoke_batch
        takes the whole batch in one call (a batch dies columnar);
        plain sinks box per row."""
        fn = self.user_function
        if hasattr(fn, "invoke_batch"):
            self._note_columnar(len(batch))
            fn.invoke_batch(batch)
        else:
            StreamOperator.process_batch(self, batch)


class SinkContext:
    """(ref: SinkFunction.Context)"""

    __slots__ = ("timestamp", "_op")

    def __init__(self, timestamp, op):
        self.timestamp = timestamp
        self._op = op

    def current_processing_time(self):
        pts = self._op.processing_time_service
        return pts.get_current_processing_time() if pts else 0

    def current_watermark(self):
        return self._op.current_watermark


class StreamGroupedReduce(AbstractUdfStreamOperator):
    """Rolling keyed reduce: emits the running reduction per element
    (ref: StreamGroupedReduce.java)."""

    STATE_NAME = "_reduce_state"

    def __init__(self, reduce_function: ReduceFunction):
        super().__init__(reduce_function)

    def open(self):
        super().open()
        self._state = self.keyed_backend.get_or_create_keyed_state(
            ReducingStateDescriptor(self.STATE_NAME, self.user_function))

    def process_element(self, record):
        self._state.set_current_namespace(VOID_NAMESPACE)
        self._state.add(record.value)
        self.output.collect(record.replace(self._state.get()))


class ProcessOperator(AbstractUdfStreamOperator):
    """Non-keyed ProcessFunction host (ref: ProcessOperator.java)."""

    def open(self):
        super().open()
        self._collector = TimestampedCollector(self.output)

    def process_element(self, record):
        self._collector.set_absolute_timestamp(record.timestamp)
        ctx = ProcessFunctionContext(record, self)
        self.user_function.process_element(record.value, ctx, self._collector)


class KeyedProcessOperator(AbstractUdfStreamOperator):
    """Keyed ProcessFunction with timer access
    (ref: KeyedProcessOperator.java)."""

    def open(self):
        super().open()
        self._collector = TimestampedCollector(self.output)

    def process_element(self, record):
        self._collector.set_absolute_timestamp(record.timestamp)
        ctx = KeyedProcessFunctionContext(record, self)
        self.user_function.process_element(record.value, ctx, self._collector)

    def on_event_time(self, timer):
        self._collector.set_absolute_timestamp(timer.timestamp)
        ctx = OnTimerContext(timer, self, "event")
        self.user_function.on_timer(timer.timestamp, ctx, self._collector)

    def on_processing_time(self, timer):
        self._collector.set_absolute_timestamp(None)
        ctx = OnTimerContext(timer, self, "processing")
        self.user_function.on_timer(timer.timestamp, ctx, self._collector)


class ProcessFunctionContext:
    """(ref: ProcessFunction.Context)"""

    def __init__(self, record: StreamRecord, op: StreamOperator):
        self._record = record
        self._op = op

    def timestamp(self) -> Optional[int]:
        return self._record.timestamp

    def current_processing_time(self) -> int:
        pts = self._op.processing_time_service
        return pts.get_current_processing_time() if pts else 0

    def current_watermark(self) -> int:
        return self._op.current_watermark

    def output(self, tag: OutputTag, value) -> None:
        self._op.output.collect_side(tag, StreamRecord(value, self._record.timestamp))


class KeyedProcessFunctionContext(ProcessFunctionContext):
    """Adds timers + current key (ref: KeyedProcessFunction.Context)."""

    def get_current_key(self):
        return self._op.keyed_backend.current_key

    def register_event_time_timer(self, timestamp: int) -> None:
        self._op.timer_service.register_event_time_timer(VOID_NAMESPACE, timestamp)

    def register_processing_time_timer(self, timestamp: int) -> None:
        self._op.timer_service.register_processing_time_timer(VOID_NAMESPACE, timestamp)

    def delete_event_time_timer(self, timestamp: int) -> None:
        self._op.timer_service.delete_event_time_timer(VOID_NAMESPACE, timestamp)

    def delete_processing_time_timer(self, timestamp: int) -> None:
        self._op.timer_service.delete_processing_time_timer(VOID_NAMESPACE, timestamp)

    # state access for ProcessFunctions
    def get_state(self, descriptor: StateDescriptor):
        return self._op.keyed_backend.get_partitioned_state(VOID_NAMESPACE, descriptor)


class OnTimerContext(KeyedProcessFunctionContext):
    """(ref: ProcessFunction.OnTimerContext)"""

    def __init__(self, timer, op, time_domain: str):
        self._timer = timer
        self._op = op
        self._record = StreamRecord(None, timer.timestamp)
        self.time_domain = time_domain

    def timestamp(self):
        return self._timer.timestamp

    def get_current_key(self):
        return self._timer.key


class ProcessFunction(abc.ABC):
    """(ref: api/functions/ProcessFunction.java)"""

    @abc.abstractmethod
    def process_element(self, value, ctx, out) -> None: ...

    def on_timer(self, timestamp: int, ctx, out) -> None:  # noqa: B027
        pass


KeyedProcessFunction = ProcessFunction  # same shape; keyed ctx at runtime


# ---------------------------------------------------------------------
# Two-input (co-) operators (ref: api/operators/co/)
# ---------------------------------------------------------------------

class TwoInputStreamOperator(StreamOperator):
    @abc.abstractmethod
    def process_element1(self, record: StreamRecord) -> None: ...

    @abc.abstractmethod
    def process_element2(self, record: StreamRecord) -> None: ...

    def process_element(self, record):
        raise RuntimeError("two-input operator: use process_element1/2")

    def process_watermark1(self, watermark: Watermark) -> None:
        self._wm1 = watermark.timestamp
        self._combine_watermarks()

    def process_watermark2(self, watermark: Watermark) -> None:
        self._wm2 = watermark.timestamp
        self._combine_watermarks()

    def _combine_watermarks(self):
        """min-combine the two input watermarks
        (ref: AbstractStreamOperator.processWatermark1/2)."""
        wm1 = getattr(self, "_wm1", MIN_TIMESTAMP)
        wm2 = getattr(self, "_wm2", MIN_TIMESTAMP)
        combined = min(wm1, wm2)
        if combined > self.current_watermark:
            self.process_watermark(Watermark(combined))


class CoStreamMap(TwoInputStreamOperator, AbstractUdfStreamOperator):
    """(ref: CoStreamMap.java) — user_function is a CoMapFunction."""

    def __init__(self, fn):
        AbstractUdfStreamOperator.__init__(self, fn)

    def process_element1(self, record):
        self.output.collect(record.replace(self.user_function.map1(record.value)))

    def process_element2(self, record):
        self.output.collect(record.replace(self.user_function.map2(record.value)))


class CoStreamFlatMap(TwoInputStreamOperator, AbstractUdfStreamOperator):
    """(ref: CoStreamFlatMap.java)"""

    def __init__(self, fn):
        AbstractUdfStreamOperator.__init__(self, fn)

    def process_element1(self, record):
        out = self.user_function.flat_map1(record.value)
        if out is not None:
            for v in out:
                self.output.collect(record.replace(v))

    def process_element2(self, record):
        out = self.user_function.flat_map2(record.value)
        if out is not None:
            for v in out:
                self.output.collect(record.replace(v))


class CoProcessOperator(TwoInputStreamOperator, AbstractUdfStreamOperator):
    """(ref: CoProcessOperator.java / KeyedCoProcessOperator.java)"""

    def __init__(self, fn):
        AbstractUdfStreamOperator.__init__(self, fn)
        self.key_selector2: Optional[KeySelector] = None

    def open(self):
        AbstractUdfStreamOperator.open(self)
        self._collector = TimestampedCollector(self.output)

    def set_key_context2(self, record: StreamRecord) -> None:
        if self.key_selector2 is not None and self.keyed_backend is not None:
            self.keyed_backend.set_current_key(
                self.key_selector2.get_key(record.value))

    def process_element1(self, record):
        self._collector.set_absolute_timestamp(record.timestamp)
        ctx = KeyedProcessFunctionContext(record, self)
        self.user_function.process_element1(record.value, ctx, self._collector)

    def process_element2(self, record):
        self._collector.set_absolute_timestamp(record.timestamp)
        ctx = KeyedProcessFunctionContext(record, self)
        self.user_function.process_element2(record.value, ctx, self._collector)

    def on_event_time(self, timer):
        self._collector.set_absolute_timestamp(timer.timestamp)
        ctx = OnTimerContext(timer, self, "event")
        if hasattr(self.user_function, "on_timer"):
            self.user_function.on_timer(timer.timestamp, ctx, self._collector)

    def on_processing_time(self, timer):
        self._collector.set_absolute_timestamp(None)
        ctx = OnTimerContext(timer, self, "processing")
        if hasattr(self.user_function, "on_timer"):
            self.user_function.on_timer(timer.timestamp, ctx, self._collector)


# ---------------------------------------------------------------------
# Broadcast-connected operators (ref: api/operators/co/
# CoBroadcastWithKeyedOperator.java / CoBroadcastWithNonKeyedOperator.java
# + the broadcast state pattern)
# ---------------------------------------------------------------------

class BroadcastProcessFunction(abc.ABC):
    """(ref: api/functions/co/BroadcastProcessFunction.java;
    the keyed variant adds timers — KeyedBroadcastProcessFunction)."""

    @abc.abstractmethod
    def process_element(self, value, ctx, out) -> None: ...

    @abc.abstractmethod
    def process_broadcast_element(self, value, ctx, out) -> None: ...

    def on_timer(self, timestamp: int, ctx, out) -> None:  # noqa: B027
        pass


KeyedBroadcastProcessFunction = BroadcastProcessFunction


class _ReadOnlyBroadcastState:
    """Read view of a BroadcastState (the non-broadcast side must not
    write — ref: ReadOnlyBroadcastState.java)."""

    def __init__(self, state):
        self._s = state

    def get(self, key):
        return self._s.get(key)

    def contains(self, key):
        return self._s.contains(key)

    def immutable_entries(self):
        return self._s.immutable_entries()

    def keys(self):
        return self._s.keys()


class _BroadcastBaseContext(ProcessFunctionContext):
    def __init__(self, record, op, writable: bool):
        super().__init__(record, op)
        self._writable = writable

    def get_broadcast_state(self, descriptor_or_name):
        name = getattr(descriptor_or_name, "name", descriptor_or_name)
        state = self._op.operator_state_backend.get_broadcast_state(name)
        return state if self._writable else _ReadOnlyBroadcastState(state)


class _BroadcastReadOnlyContext(_BroadcastBaseContext):
    """Keyed-side context: read-only broadcast state + keyed state +
    timers (when the data side is keyed)."""

    def __init__(self, record, op):
        super().__init__(record, op, writable=False)

    def get_current_key(self):
        return self._op.keyed_backend.current_key

    def get_state(self, descriptor):
        return self._op.keyed_backend.get_partitioned_state(
            VOID_NAMESPACE, descriptor)

    def register_event_time_timer(self, timestamp):
        self._op.timer_service.register_event_time_timer(
            VOID_NAMESPACE, timestamp)

    def register_processing_time_timer(self, timestamp):
        self._op.timer_service.register_processing_time_timer(
            VOID_NAMESPACE, timestamp)


class CoBroadcastOperator(TwoInputStreamOperator, AbstractUdfStreamOperator):
    """Input 1 = the (possibly keyed) data stream; input 2 = the
    broadcast stream whose elements update broadcast state on EVERY
    parallel instance (the broadcast partitioner delivers to all)."""

    def __init__(self, fn: BroadcastProcessFunction):
        AbstractUdfStreamOperator.__init__(self, fn)

    def open(self):
        super().open()
        self._collector = TimestampedCollector(self.output)

    def process_element1(self, record):
        self._collector.set_absolute_timestamp(record.timestamp)
        ctx = _BroadcastReadOnlyContext(record, self)
        self.user_function.process_element(record.value, ctx,
                                           self._collector)

    def process_element2(self, record):
        self._collector.set_absolute_timestamp(record.timestamp)
        ctx = _BroadcastBaseContext(record, self, writable=True)
        self.user_function.process_broadcast_element(record.value, ctx,
                                                     self._collector)

    def on_event_time(self, timer):
        self._collector.set_absolute_timestamp(timer.timestamp)
        ctx = OnTimerContext(timer, self, "event")
        self.user_function.on_timer(timer.timestamp, ctx, self._collector)

    def on_processing_time(self, timer):
        self._collector.set_absolute_timestamp(None)
        ctx = OnTimerContext(timer, self, "processing")
        self.user_function.on_timer(timer.timestamp, ctx, self._collector)


# ---------------------------------------------------------------------
# Async I/O (ref: api/operators/async/AsyncWaitOperator.java + the
# ordered/unordered stream element queues under queue/)
# ---------------------------------------------------------------------

class AsyncFunction(abc.ABC):
    """(ref: api/functions/async/AsyncFunction.java).  async_invoke
    runs ON A POOL THREAD here (Python has no JVM-style callback
    futures baked in), so a blocking client call inside it overlaps
    with other records' calls — the same throughput effect the
    reference gets from callback-style clients."""

    @abc.abstractmethod
    def async_invoke(self, value, result_future: "ResultFuture") -> None:
        ...

    def timeout(self, value, result_future: "ResultFuture") -> None:
        result_future.complete_exceptionally(
            TimeoutError(f"async I/O timed out for {value!r}"))


class ResultFuture:
    """(ref: api/functions/async/ResultFuture.java)"""

    __slots__ = ("_results", "_error", "_done", "_notify")

    def __init__(self, notify=None):
        self._results = None
        self._error = None
        self._done = threading.Event()
        #: operator-level "any completion" event (wait-any support)
        self._notify = notify

    def complete(self, results) -> None:
        self._results = list(results)
        self._done.set()
        if self._notify is not None:
            self._notify.set()

    def complete_exceptionally(self, error: BaseException) -> None:
        self._error = error
        self._done.set()
        if self._notify is not None:
            self._notify.set()

    @property
    def done(self) -> bool:
        return self._done.is_set()


class AsyncWaitOperator(AbstractUdfStreamOperator):
    """Bounded in-flight async requests with ordered or unordered
    result emission.  Watermarks act as order barriers: every pending
    request drains before the watermark forwards, in BOTH modes (the
    reference's unordered queue also never reorders across
    watermarks)."""

    def __init__(self, fn: AsyncFunction, capacity: int = 100,
                 timeout_ms: Optional[int] = None, ordered: bool = True):
        super().__init__(fn)
        self.capacity = capacity
        self.timeout_ms = timeout_ms
        self.ordered = ordered
        self._pending = None  # deque of (record, ResultFuture, deadline)

    def open(self):
        super().open()
        from collections import deque as _deque
        from concurrent.futures import ThreadPoolExecutor
        self._pending = _deque()
        self._any_done = threading.Event()
        self._pool = ThreadPoolExecutor(
            max_workers=min(self.capacity, 64),
            thread_name_prefix="async-io")

    def process_element(self, record):
        while len(self._pending) >= self.capacity:
            self._drain(block_one=True)
        rf = ResultFuture(notify=self._any_done)
        value = record.value
        deadline = (None if self.timeout_ms is None
                    else _time_mod.monotonic() + self.timeout_ms / 1000.0)
        self._pool.submit(self._invoke, value, rf)
        self._pending.append((record, rf, deadline, value))
        self._drain()

    def _invoke(self, value, rf):
        try:
            self.user_function.async_invoke(value, rf)
        except BaseException as e:  # noqa: BLE001
            rf.complete_exceptionally(e)

    def _drain(self, block_one: bool = False, block_all: bool = False):
        """Emit completed results; ordered mode emits only from the
        head, unordered emits any completed entry."""
        while self._pending:
            if self.ordered:
                entry = self._pending[0]
                if not self._entry_ready(entry, block_one or block_all):
                    if not (block_one or block_all):
                        return
                self._pending.popleft()
                self._emit(entry)
            else:
                # wait-any: a blocked unordered drain must wake on ANY
                # completion, not poll the head (head-of-line blocking
                # is exactly what unordered mode exists to avoid)
                while True:
                    ready = [e for e in self._pending if e[1].done
                             or self._expired(e)]
                    if ready or not (block_one or block_all):
                        break
                    self._any_done.clear()
                    self._any_done.wait(0.005)
                if not ready:
                    return
                for entry in ready:
                    self._pending.remove(entry)
                    self._emit(entry)
            if block_one and not block_all:
                return

    def _entry_ready(self, entry, block: bool) -> bool:
        record, rf, deadline, value = entry
        if rf.done:
            return True
        if self._expired(entry):
            return True
        if not block:
            return False
        while not rf.done and not self._expired(entry):
            rf._done.wait(0.005)
        return True

    def _expired(self, entry) -> bool:
        _, rf, deadline, _ = entry
        return (deadline is not None and not rf.done
                and _time_mod.monotonic() > deadline)

    def _emit(self, entry):
        record, rf, deadline, value = entry
        if not rf.done and self._expired(entry):
            self.user_function.timeout(value, rf)
            rf._done.wait(1.0)
        if rf._error is not None:
            raise rf._error
        for v in rf._results or []:
            self.output.collect(record.replace(v))

    def process_watermark(self, watermark):
        self._drain(block_all=True)
        super().process_watermark(watermark)

    def snapshot_state(self, checkpoint_id=None):
        # a barrier must not leave records in flight: upstream will not
        # replay records consumed before it, so drain-and-emit before
        # the snapshot (the reference instead persists its queue; a
        # full drain gives the same exactly-once guarantee at some
        # checkpoint-latency cost)
        self._drain(block_all=True)
        return super().snapshot_state(checkpoint_id)

    def finish(self):
        self._drain(block_all=True)
        super().finish()

    def close(self):
        if getattr(self, "_pool", None) is not None:
            self._pool.shutdown(wait=False)
        super().close()
