"""Stream operators: lifecycle + the stateless/keyed operator family.

Re-designs flink-streaming-java/.../api/operators/:
AbstractStreamOperator (state/timer plumbing), AbstractUdfStreamOperator,
StreamMap/StreamFlatMap/StreamFilter, ProcessOperator,
KeyedProcessOperator, StreamGroupedReduce, StreamSink, and the co-
(two-input) operators.  An operator receives StreamElements from its
input(s) and emits to an `Output`; chains of operators are built by the
task layer (ref: OperatorChain.java).
"""

from __future__ import annotations

import abc
from typing import Any, Callable, Generic, Iterable, List, Optional, TypeVar

from flink_tpu.core.functions import (
    FilterFunction,
    FlatMapFunction,
    KeySelector,
    MapFunction,
    ReduceFunction,
    RichFunction,
)
from flink_tpu.core.state import ReducingStateDescriptor, StateDescriptor
from flink_tpu.state.backend import VOID_NAMESPACE, KeyedStateBackend
from flink_tpu.state.operator_state import OperatorStateBackend
from flink_tpu.streaming.elements import (
    MIN_TIMESTAMP,
    LatencyMarker,
    StreamRecord,
    Watermark,
)
from flink_tpu.streaming.timers import (
    InternalTimerService,
    ProcessingTimeService,
)

IN = TypeVar("IN")
OUT = TypeVar("OUT")


class OutputTag:
    """Side-output tag (ref: org.apache.flink.util.OutputTag)."""

    __slots__ = ("tag_id",)

    def __init__(self, tag_id: str):
        self.tag_id = tag_id

    def __eq__(self, other):
        return isinstance(other, OutputTag) and self.tag_id == other.tag_id

    def __hash__(self):
        return hash(self.tag_id)

    def __repr__(self):
        return f"OutputTag({self.tag_id!r})"


class Output(abc.ABC):
    """Where an operator emits (ref: Output.java extends Collector)."""

    @abc.abstractmethod
    def collect(self, record: StreamRecord) -> None: ...

    @abc.abstractmethod
    def emit_watermark(self, watermark: Watermark) -> None: ...

    def collect_side(self, tag: OutputTag, record: StreamRecord) -> None:
        pass  # dropped unless a side output is wired

    def emit_latency_marker(self, marker: LatencyMarker) -> None:  # noqa: B027
        pass

    def close(self) -> None:  # noqa: B027
        pass


class CollectorOutput(Output):
    """Buffers emissions in lists — test harness + chain tails."""

    def __init__(self):
        self.records: List[StreamRecord] = []
        self.watermarks: List[Watermark] = []
        self.side: dict = {}
        self.latency_markers: List[LatencyMarker] = []

    def collect(self, record):
        self.records.append(record)

    def emit_watermark(self, watermark):
        self.watermarks.append(watermark)

    def collect_side(self, tag, record):
        self.side.setdefault(tag.tag_id, []).append(record)

    def emit_latency_marker(self, marker):
        self.latency_markers.append(marker)

    def extract_values(self):
        return [r.value for r in self.records]


class TimestampedCollector:
    """Collector bound to one timestamp (ref:
    api/operators/TimestampedCollector.java)."""

    __slots__ = ("_output", "timestamp")

    def __init__(self, output: Output, timestamp: Optional[int] = None):
        self._output = output
        self.timestamp = timestamp

    def collect(self, value) -> None:
        self._output.collect(StreamRecord(value, self.timestamp))

    def set_absolute_timestamp(self, ts: Optional[int]) -> None:
        self.timestamp = ts


class StreamOperator(abc.ABC):
    """Operator lifecycle (ref: StreamOperator.java + lifecycle docs
    docs/internals/task_lifecycle.md): setup → open → process* →
    snapshot* → close → dispose."""

    def __init__(self):
        self.output: Optional[Output] = None
        self.keyed_backend: Optional[KeyedStateBackend] = None
        self.operator_state_backend: Optional[OperatorStateBackend] = None
        self.processing_time_service: Optional[ProcessingTimeService] = None
        self.timer_service: Optional[InternalTimerService] = None
        self.current_watermark: int = MIN_TIMESTAMP
        self.key_selector: Optional[KeySelector] = None
        self.operator_id: str = ""
        self.metrics = None  # OperatorMetricGroup, set by task layer

    # ---- wiring -----------------------------------------------------
    def setup(self, output: Output,
              keyed_backend: Optional[KeyedStateBackend] = None,
              operator_state_backend: Optional[OperatorStateBackend] = None,
              processing_time_service: Optional[ProcessingTimeService] = None,
              key_selector: Optional[KeySelector] = None,
              operator_id: str = "",
              subtask_index: int = 0,
              num_subtasks: int = 1) -> None:
        self.output = output
        self.keyed_backend = keyed_backend
        self.operator_state_backend = operator_state_backend or OperatorStateBackend()
        self.processing_time_service = processing_time_service
        self.key_selector = key_selector
        self.operator_id = operator_id or type(self).__name__
        self.subtask_index = subtask_index
        self.num_subtasks = num_subtasks
        if keyed_backend is not None and processing_time_service is not None:
            self.timer_service = InternalTimerService(
                f"{self.operator_id}-timers", keyed_backend,
                processing_time_service, self)

    def open(self) -> None:  # noqa: B027
        pass

    def finish(self) -> None:  # noqa: B027
        """End of input reached (after the final watermark, before
        close): flush buffered output.  The drain-then-flush step of
        stop-with-savepoint, applied at natural end of input so finite
        jobs don't strand a 2PC sink's tail transaction."""
        pass

    def close(self) -> None:  # noqa: B027
        pass

    def dispose(self) -> None:  # noqa: B027
        pass

    # ---- elements ---------------------------------------------------
    @abc.abstractmethod
    def process_element(self, record: StreamRecord) -> None: ...

    def process_watermark(self, watermark: Watermark) -> None:
        """(ref: AbstractStreamOperator.processWatermark :737)"""
        self.current_watermark = watermark.timestamp
        if self.timer_service is not None:
            self.timer_service.advance_watermark(watermark.timestamp)
        self.output.emit_watermark(watermark)

    def process_latency_marker(self, marker: LatencyMarker) -> None:
        self.output.emit_latency_marker(marker)

    # ---- keyed context ----------------------------------------------
    def set_key_context(self, record: StreamRecord) -> None:
        """(ref: setKeyContextElement1 — key extraction + backend key)"""
        if self.key_selector is not None and self.keyed_backend is not None:
            self.keyed_backend.set_current_key(
                self.key_selector.get_key(record.value))

    # ---- timers (Triggerable contract) ------------------------------
    def on_event_time(self, timer) -> None:  # noqa: B027
        pass

    def on_processing_time(self, timer) -> None:  # noqa: B027
        pass

    # ---- snapshot ---------------------------------------------------
    def snapshot_state(self, checkpoint_id: Optional[int] = None) -> dict:
        snap = {}
        if self.keyed_backend is not None:
            if hasattr(self.keyed_backend, "flush_all"):
                self.keyed_backend.flush_all()
            snap["keyed"] = self.keyed_backend.snapshot()
        if self.operator_state_backend is not None:
            snap["operator"] = self.operator_state_backend.snapshot()
        if self.timer_service is not None:
            snap["timers"] = self.timer_service.snapshot()
        return snap

    def restore_state(self, snapshots: List[dict]) -> None:
        keyed = [s["keyed"] for s in snapshots if "keyed" in s]
        if keyed and self.keyed_backend is not None:
            self.keyed_backend.restore(keyed)
        ops = [s["operator"] for s in snapshots if "operator" in s]
        if ops and self.operator_state_backend is not None:
            from flink_tpu.state.operator_state import OperatorStateSnapshot
            if len(ops) == 1:
                self.operator_state_backend.restore(ops[0])
            else:
                self.operator_state_backend.restore(
                    OperatorStateSnapshot.redistribute(ops, 1)[0])
        timers = [s["timers"] for s in snapshots if "timers" in s]
        if timers and self.timer_service is not None:
            self.timer_service.restore(timers)

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:  # noqa: B027
        pass


class KeyedStateStore:
    """Adapter giving user functions keyed-state access in the VOID
    namespace (ref: DefaultKeyedStateStore.java)."""

    def __init__(self, backend: KeyedStateBackend):
        self._backend = backend

    def _bind(self, descriptor):
        return self._backend.get_partitioned_state(VOID_NAMESPACE, descriptor)

    get_value_state = _bind
    get_list_state = _bind
    get_reducing_state = _bind
    get_aggregating_state = _bind
    get_map_state = _bind


class AbstractUdfStreamOperator(StreamOperator):
    """Hosts a user function, forwarding open/close
    (ref: AbstractUdfStreamOperator.java)."""

    #: at parallelism > 1, rich functions are copied per subtask so each
    #: gets its own RuntimeContext and state (the reference serializes
    #: the function into every subtask).  At parallelism 1 the instance
    #: is shared — tests rely on reading e.g. a CollectSink's buffer.
    #: Sources opt out: their factory already deep-copies.
    COPY_UDF_PER_SUBTASK = True

    def __init__(self, user_function):
        super().__init__()
        self.user_function = user_function

    def setup(self, *args, **kwargs):
        super().setup(*args, **kwargs)
        if (self.COPY_UDF_PER_SUBTASK and self.num_subtasks > 1
                and isinstance(self.user_function, RichFunction)):
            import copy
            self.user_function = copy.deepcopy(self.user_function)

    def open(self):
        if isinstance(self.user_function, RichFunction):
            from flink_tpu.core.functions import RuntimeContext
            store = (KeyedStateStore(self.keyed_backend)
                     if self.keyed_backend is not None else None)
            ctx = RuntimeContext(
                task_name=self.operator_id,
                index_of_subtask=self.subtask_index,
                parallelism=self.num_subtasks,
                keyed_state_store=store,
                operator_state_store=self.operator_state_backend,
            )
            self.user_function.set_runtime_context(ctx)
            self.user_function.open(None)

    def finish(self):
        fn = self.user_function
        if hasattr(fn, "finish"):
            fn.finish()

    def close(self):
        if isinstance(self.user_function, RichFunction):
            self.user_function.close()

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        fn = self.user_function
        if hasattr(fn, "notify_checkpoint_complete"):
            fn.notify_checkpoint_complete(checkpoint_id)

    def snapshot_state(self, checkpoint_id: Optional[int] = None) -> dict:
        """Functions with checkpoint hooks (2PC sinks, replayable
        sources) ride in the operator snapshot (ref: the
        CheckpointedFunction path in AbstractUdfStreamOperator
        .snapshotState)."""
        snap = super().snapshot_state(checkpoint_id)
        fn = self.user_function
        if hasattr(fn, "snapshot_function_state"):
            snap["function"] = fn.snapshot_function_state(checkpoint_id)
        return snap

    def restore_state(self, snapshots) -> None:
        super().restore_state(snapshots)
        fn = self.user_function
        if hasattr(fn, "restore_function_state"):
            for s in snapshots:
                if "function" in s:
                    fn.restore_function_state(s["function"])


class StreamMap(AbstractUdfStreamOperator):
    """(ref: StreamMap.java)"""

    def process_element(self, record):
        self.output.collect(record.replace(self.user_function.map(record.value)))


class StreamFlatMap(AbstractUdfStreamOperator):
    """(ref: StreamFlatMap.java)"""

    def process_element(self, record):
        out = self.user_function.flat_map(record.value)
        if out is not None:
            for value in out:
                self.output.collect(record.replace(value))


class StreamFilter(AbstractUdfStreamOperator):
    """(ref: StreamFilter.java)"""

    def process_element(self, record):
        if self.user_function.filter(record.value):
            self.output.collect(record)


class StreamSink(AbstractUdfStreamOperator):
    """(ref: StreamSink.java) — user_function is a SinkFunction."""

    def process_element(self, record):
        self.user_function.invoke(record.value,
                                  SinkContext(record.timestamp, self))


class SinkContext:
    """(ref: SinkFunction.Context)"""

    __slots__ = ("timestamp", "_op")

    def __init__(self, timestamp, op):
        self.timestamp = timestamp
        self._op = op

    def current_processing_time(self):
        pts = self._op.processing_time_service
        return pts.get_current_processing_time() if pts else 0

    def current_watermark(self):
        return self._op.current_watermark


class StreamGroupedReduce(AbstractUdfStreamOperator):
    """Rolling keyed reduce: emits the running reduction per element
    (ref: StreamGroupedReduce.java)."""

    STATE_NAME = "_reduce_state"

    def __init__(self, reduce_function: ReduceFunction):
        super().__init__(reduce_function)

    def open(self):
        super().open()
        self._state = self.keyed_backend.get_or_create_keyed_state(
            ReducingStateDescriptor(self.STATE_NAME, self.user_function))

    def process_element(self, record):
        self._state.set_current_namespace(VOID_NAMESPACE)
        self._state.add(record.value)
        self.output.collect(record.replace(self._state.get()))


class ProcessOperator(AbstractUdfStreamOperator):
    """Non-keyed ProcessFunction host (ref: ProcessOperator.java)."""

    def open(self):
        super().open()
        self._collector = TimestampedCollector(self.output)

    def process_element(self, record):
        self._collector.set_absolute_timestamp(record.timestamp)
        ctx = ProcessFunctionContext(record, self)
        self.user_function.process_element(record.value, ctx, self._collector)


class KeyedProcessOperator(AbstractUdfStreamOperator):
    """Keyed ProcessFunction with timer access
    (ref: KeyedProcessOperator.java)."""

    def open(self):
        super().open()
        self._collector = TimestampedCollector(self.output)

    def process_element(self, record):
        self._collector.set_absolute_timestamp(record.timestamp)
        ctx = KeyedProcessFunctionContext(record, self)
        self.user_function.process_element(record.value, ctx, self._collector)

    def on_event_time(self, timer):
        self._collector.set_absolute_timestamp(timer.timestamp)
        ctx = OnTimerContext(timer, self, "event")
        self.user_function.on_timer(timer.timestamp, ctx, self._collector)

    def on_processing_time(self, timer):
        self._collector.set_absolute_timestamp(None)
        ctx = OnTimerContext(timer, self, "processing")
        self.user_function.on_timer(timer.timestamp, ctx, self._collector)


class ProcessFunctionContext:
    """(ref: ProcessFunction.Context)"""

    def __init__(self, record: StreamRecord, op: StreamOperator):
        self._record = record
        self._op = op

    def timestamp(self) -> Optional[int]:
        return self._record.timestamp

    def current_processing_time(self) -> int:
        pts = self._op.processing_time_service
        return pts.get_current_processing_time() if pts else 0

    def current_watermark(self) -> int:
        return self._op.current_watermark

    def output(self, tag: OutputTag, value) -> None:
        self._op.output.collect_side(tag, StreamRecord(value, self._record.timestamp))


class KeyedProcessFunctionContext(ProcessFunctionContext):
    """Adds timers + current key (ref: KeyedProcessFunction.Context)."""

    def get_current_key(self):
        return self._op.keyed_backend.current_key

    def register_event_time_timer(self, timestamp: int) -> None:
        self._op.timer_service.register_event_time_timer(VOID_NAMESPACE, timestamp)

    def register_processing_time_timer(self, timestamp: int) -> None:
        self._op.timer_service.register_processing_time_timer(VOID_NAMESPACE, timestamp)

    def delete_event_time_timer(self, timestamp: int) -> None:
        self._op.timer_service.delete_event_time_timer(VOID_NAMESPACE, timestamp)

    def delete_processing_time_timer(self, timestamp: int) -> None:
        self._op.timer_service.delete_processing_time_timer(VOID_NAMESPACE, timestamp)

    # state access for ProcessFunctions
    def get_state(self, descriptor: StateDescriptor):
        return self._op.keyed_backend.get_partitioned_state(VOID_NAMESPACE, descriptor)


class OnTimerContext(KeyedProcessFunctionContext):
    """(ref: ProcessFunction.OnTimerContext)"""

    def __init__(self, timer, op, time_domain: str):
        self._timer = timer
        self._op = op
        self._record = StreamRecord(None, timer.timestamp)
        self.time_domain = time_domain

    def timestamp(self):
        return self._timer.timestamp

    def get_current_key(self):
        return self._timer.key


class ProcessFunction(abc.ABC):
    """(ref: api/functions/ProcessFunction.java)"""

    @abc.abstractmethod
    def process_element(self, value, ctx, out) -> None: ...

    def on_timer(self, timestamp: int, ctx, out) -> None:  # noqa: B027
        pass


KeyedProcessFunction = ProcessFunction  # same shape; keyed ctx at runtime


# ---------------------------------------------------------------------
# Two-input (co-) operators (ref: api/operators/co/)
# ---------------------------------------------------------------------

class TwoInputStreamOperator(StreamOperator):
    @abc.abstractmethod
    def process_element1(self, record: StreamRecord) -> None: ...

    @abc.abstractmethod
    def process_element2(self, record: StreamRecord) -> None: ...

    def process_element(self, record):
        raise RuntimeError("two-input operator: use process_element1/2")

    def process_watermark1(self, watermark: Watermark) -> None:
        self._wm1 = watermark.timestamp
        self._combine_watermarks()

    def process_watermark2(self, watermark: Watermark) -> None:
        self._wm2 = watermark.timestamp
        self._combine_watermarks()

    def _combine_watermarks(self):
        """min-combine the two input watermarks
        (ref: AbstractStreamOperator.processWatermark1/2)."""
        wm1 = getattr(self, "_wm1", MIN_TIMESTAMP)
        wm2 = getattr(self, "_wm2", MIN_TIMESTAMP)
        combined = min(wm1, wm2)
        if combined > self.current_watermark:
            self.process_watermark(Watermark(combined))


class CoStreamMap(TwoInputStreamOperator, AbstractUdfStreamOperator):
    """(ref: CoStreamMap.java) — user_function is a CoMapFunction."""

    def __init__(self, fn):
        AbstractUdfStreamOperator.__init__(self, fn)

    def process_element1(self, record):
        self.output.collect(record.replace(self.user_function.map1(record.value)))

    def process_element2(self, record):
        self.output.collect(record.replace(self.user_function.map2(record.value)))


class CoStreamFlatMap(TwoInputStreamOperator, AbstractUdfStreamOperator):
    """(ref: CoStreamFlatMap.java)"""

    def __init__(self, fn):
        AbstractUdfStreamOperator.__init__(self, fn)

    def process_element1(self, record):
        out = self.user_function.flat_map1(record.value)
        if out is not None:
            for v in out:
                self.output.collect(record.replace(v))

    def process_element2(self, record):
        out = self.user_function.flat_map2(record.value)
        if out is not None:
            for v in out:
                self.output.collect(record.replace(v))


class CoProcessOperator(TwoInputStreamOperator, AbstractUdfStreamOperator):
    """(ref: CoProcessOperator.java / KeyedCoProcessOperator.java)"""

    def __init__(self, fn):
        AbstractUdfStreamOperator.__init__(self, fn)
        self.key_selector2: Optional[KeySelector] = None

    def open(self):
        AbstractUdfStreamOperator.open(self)
        self._collector = TimestampedCollector(self.output)

    def set_key_context2(self, record: StreamRecord) -> None:
        if self.key_selector2 is not None and self.keyed_backend is not None:
            self.keyed_backend.set_current_key(
                self.key_selector2.get_key(record.value))

    def process_element1(self, record):
        self._collector.set_absolute_timestamp(record.timestamp)
        ctx = KeyedProcessFunctionContext(record, self)
        self.user_function.process_element1(record.value, ctx, self._collector)

    def process_element2(self, record):
        self._collector.set_absolute_timestamp(record.timestamp)
        ctx = KeyedProcessFunctionContext(record, self)
        self.user_function.process_element2(record.value, ctx, self._collector)

    def on_event_time(self, timer):
        self._collector.set_absolute_timestamp(timer.timestamp)
        ctx = OnTimerContext(timer, self, "event")
        if hasattr(self.user_function, "on_timer"):
            self.user_function.on_timer(timer.timestamp, ctx, self._collector)

    def on_processing_time(self, timer):
        self._collector.set_absolute_timestamp(None)
        ctx = OnTimerContext(timer, self, "processing")
        if hasattr(self.user_function, "on_timer"):
            self.user_function.on_timer(timer.timestamp, ctx, self._collector)
