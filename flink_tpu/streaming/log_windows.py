"""Log-structured window engines — the combiner tier.

The reference's windowed-aggregation hot path is one random
read-modify-write of keyed state per record (heap:
``HeapAggregatingState.add`` → ``stateTable.transform``,
HeapAggregatingState.java:80-89; RocksDB: a get/deserialize/add/put
round trip, RocksDBAggregatingState.java:108-131).  At multi-GB state
that mechanism is memory-latency-bound on every substrate — the
compiled host baseline and the XLA scatter path both measure in the
single-digit M updates/s (BENCH_NOTES.md).

These engines restructure the work the TPU-first way (SURVEY.md §7
"per-record semantics vs batched execution"): **ingest appends** the
record's aggregate *cells* to a per-window log at memcpy speed, and
the **fire sorts the log and reduces each key's run densely** —
adaptive LSD radix sort + segmented reduction (native/host_runtime.cpp
``ft_*_log_fire``), with an optional on-device finish
(``finish_tier="device"``) that runs the transcendental estimate phase
as one jitted scan over the compacted cells.  It is the same
pre-aggregation seam the reference exposes as chained combiners
(AggregateUtil.scala:1028): state per window is bounded by
min(events, keys x m) via periodic log compaction, and a window's
state snapshot is its (compacted) log — smaller than a dense register
file whenever events/window < keys x m.

Engines:
- :class:`LogStructuredTumblingWindows` — config #1/#2 shapes.
- :class:`LogStructuredSlidingWindows` — pane logs at slide
  granularity; a window fire concatenates its panes' logs (the merge
  is free — the sort regroups across panes).  One log append per
  record regardless of the overlap factor, where the reference writes
  every record into size/slide window states
  (SlidingEventTimeWindows.assignWindows).
- :class:`LogStructuredSessionWindows` — sort by (key, ts), split
  runs at gaps (TimeWindow.intersects is inclusive: abutting windows
  merge), close sessions behind the watermark; each closed session's
  Count-Min sketch builds in an L1-resident scratch — the sort makes
  the working set session-local instead of all-keys-live.

Scope: integer-keyed streams (the key rides in the log; grouping is
exact) and mergeable aggregates with a cell decomposition —
HyperLogLog (cell = (register, rank), combine = max), Sum
(cell = value, combine = add), DDSketch quantiles (cell = bucket,
combine = add), Count-Min (sessions).  Other aggregates use the
device-resident scatter engines (vectorized.py), which also remain
the multi-chip path (parallel/mesh_windows.py).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import flink_tpu.native as nat
from flink_tpu.runtime.device_stats import TELEMETRY

_perf_ns = time.perf_counter_ns
from flink_tpu.ops.device_agg import DeviceAggregateFunction, SumAggregate
from flink_tpu.ops.hashing import split_hash64_np
from flink_tpu.ops.sketches import (
    CountMinSketchAggregate,
    HyperLogLogAggregate,
    QuantileSketchAggregate,
)


def _is_single_window(starts: np.ndarray) -> bool:
    """One vectorized pass deciding the common replayed-log shape
    (every record in one window) without np.unique's sort — shared by
    the generic and string tumbling engines."""
    return bool(len(starts)) and starts[0] == starts[-1] \
        and bool((starts == starts[0]).all())


class _WindowLog:
    """Columnar append log for one window (or pane).  ``version``
    counts mutations — an unchanged version means the snapshot chunk
    hash can be reused (incremental-checkpoint seam)."""

    __slots__ = ("keys", "cols", "count", "version", "compacted_size")

    def __init__(self):
        self.keys: List[np.ndarray] = []
        self.cols: List[Tuple[np.ndarray, ...]] = []
        self.count = 0
        self.version = 0
        #: cell count right after the last compaction — compaction
        #: re-arms only once the log has grown well past it, so a log
        #: whose compacted floor sits above the threshold (many keys x
        #: buckets) cannot re-sort itself on every ingest batch
        self.compacted_size = 0

    def append(self, keys: np.ndarray, *cols: np.ndarray) -> None:
        self.keys.append(keys)
        self.cols.append(cols)
        self.count += len(keys)
        self.version += 1

    def concat(self) -> Tuple[np.ndarray, Tuple[np.ndarray, ...]]:
        keys = (self.keys[0] if len(self.keys) == 1
                else np.concatenate(self.keys))
        n_cols = len(self.cols[0])
        cols = tuple(
            (self.cols[0][j] if len(self.cols) == 1
             else np.concatenate([c[j] for c in self.cols]))
            for j in range(n_cols))
        self.keys = [keys]
        self.cols = [cols]
        return keys, cols

    def compact(self, mode) -> None:
        keys, cols = self.concat()
        ck, ccols = mode.compact(keys, cols)
        self.keys = [ck]
        self.cols = [ccols]
        self.count = len(ck)
        self.compacted_size = self.count

    def should_compact(self, threshold: int) -> bool:
        return (self.count > threshold
                and self.count >= 2 * self.compacted_size)


class _SumTabLog:
    """Adaptive sum window state (the hash-combiner tier): a dense
    C++ key->sum table while the distinct-key count stays
    cache-resident (the per-record probe+add is then L1/L2-local —
    the word-count shape), spilling to the ordinary cell log when
    cardinality outgrows it (the sort+reduce fire then wins).  Same
    interface as _WindowLog."""

    __slots__ = ("tab", "log", "max_distinct", "version")

    def __init__(self, max_distinct: int = 1 << 19):
        self.tab = nat.NativeSumTable()  # starts small, grows
        self.log: Optional[_WindowLog] = None
        self.max_distinct = max_distinct
        self.version = 0

    @property
    def count(self) -> int:
        return self.tab.n if self.log is None else self.log.count

    def append(self, keys: np.ndarray, values: np.ndarray) -> None:
        self.version += 1
        if self.log is None:
            values = np.asarray(values, np.float64)
            consumed = self.tab.ingest(keys, values, self.max_distinct)
            if consumed == len(keys):
                return
            # cardinality outgrew the table: spill to log form and
            # free the native table (it is never consulted again)
            self.log = _WindowLog()
            tk, tsums = self.tab.export()
            self.log.append(tk, tsums)
            self.tab = None
            keys, values = keys[consumed:], values[consumed:]
        self.log.append(keys, np.asarray(values, np.float64))

    def concat(self):
        if self.log is None:
            tk, tsums = self.tab.export()
            return tk, (tsums,)
        return self.log.concat()

    def compact(self, mode) -> None:
        if self.log is not None:
            self.log.compact(mode)

    def should_compact(self, threshold: int) -> bool:
        return self.log is not None \
            and self.log.should_compact(threshold)


# ---------------------------------------------------------------------
# per-aggregate cell decompositions
# ---------------------------------------------------------------------

class _HllMode:
    name = "hll"
    can_compact = True

    @staticmethod
    def upgrade_cols(cols):
        return cols

    def new_log(self):
        return _WindowLog()

    def __init__(self, agg: HyperLogLogAggregate, finish_tier: str):
        if agg.precision > 16:
            raise ValueError("log engine supports precision <= 16 "
                             "(u16 register cells)")
        self.agg = agg
        if finish_tier == "auto":
            # startup link micro-probe, not a hardcoded host default:
            # tunnel-class links lose 3.5x on the device finish,
            # pod-class links win it (ops/link_probe.py calibration)
            from flink_tpu.ops.link_probe import recommended_finish_tier
            finish_tier = recommended_finish_tier()
        self.finish_tier = finish_tier
        self._jit_finish = None

    def make_cols(self, values, value_hashes):
        if value_hashes is None:
            from flink_tpu.streaming.vectorized import hash_keys_np
            value_hashes = hash_keys_np(values)
        vh = np.asarray(value_hashes)
        if nat.available() and vh.dtype == np.uint64:
            # one fused C++ pass (clz rank + masked register) — the
            # numpy path below costs ~8 passes incl. a float log2
            return nat.hll_make_cells(vh, self.agg.precision)
        hi, lo = split_hash64_np(vh)
        ranks, regs = self.agg.compress_value_hash(hi, lo)
        return (np.ascontiguousarray(regs, np.uint16),
                np.ascontiguousarray(ranks, np.uint8))

    def compact(self, keys, cols):
        ck, cr, crk, _ = nat.hll_log_compact(keys, cols[0], cols[1],
                                             self.agg.precision)
        return ck, (cr, crk)

    def fire(self, keys, cols):
        if self.finish_tier == "device":
            ck, cr, crk, ends = nat.hll_log_compact(
                keys, cols[0], cols[1], self.agg.precision)
            return ck[ends - 1], self._device_finish(crk, ends)
        return nat.hll_log_fire(keys, cols[0], cols[1], self.agg.precision)

    def _device_finish(self, ranks: np.ndarray, ends: np.ndarray):
        """One jitted pass over the compacted cells: exp2
        contributions, cumsum, per-key diff at run ends, estimate —
        the dense phase of the fire on the device (power-of-two jit
        shapes)."""
        import jax
        import jax.numpy as jnp

        if self._jit_finish is None:
            m = float(self.agg.m)
            alpha = self.agg.alpha

            def finish(ranks_p, ends_p, n_cells, n_keys):
                cell_live = jnp.arange(ranks_p.shape[0]) < n_cells
                inv = jnp.where(
                    cell_live,
                    jnp.exp2(-ranks_p.astype(jnp.float32)) - 1.0, 0.0)
                cs = jnp.cumsum(inv)
                key_live = jnp.arange(ends_p.shape[0]) < n_keys
                e = jnp.where(key_live, ends_p, 1)
                cum_at_end = cs[e - 1]
                prev = jnp.concatenate([jnp.zeros(1), cum_at_end[:-1]])
                seg = cum_at_end - prev
                prev_e = jnp.concatenate([jnp.zeros(1, e.dtype), e[:-1]])
                n_present = (e - prev_e).astype(jnp.float32)
                sum_inv = m + seg
                est = alpha * m * m / sum_inv
                zeros = m - n_present
                linear = m * (jnp.log(m) - jnp.log(jnp.maximum(zeros, 1.0)))
                return jnp.where((est <= 2.5 * m) & (zeros > 0),
                                 linear, est)

            self._jit_finish = jax.jit(finish)
        n_cells, n_keys = len(ranks), len(ends)
        pc = 1 << max(0, (n_cells - 1)).bit_length()
        pk = 1 << max(0, (n_keys - 1)).bit_length()
        ranks_p = np.zeros(pc, np.uint8)
        ranks_p[:n_cells] = ranks
        ends_p = np.ones(pk, np.int32)
        ends_p[:n_keys] = ends
        # explicit device_put: passing numpy args through jit stages
        # them through a much slower per-argument path on the tunnel
        # backend (measured 902 ms vs 14 ms for 20 MB — BENCH_NOTES
        # round 4); the put also starts the H2D before dispatch
        dev = jax.devices()[0]
        if TELEMETRY.enabled:
            t0 = _perf_ns()
            d_ranks = jax.device_put(ranks_p, dev)
            d_ends = jax.device_put(ends_p, dev)
            TELEMETRY.record_transfer(
                "h2d", ranks_p.nbytes + ends_p.nbytes, t0, _perf_ns(),
                "log.finish")
            t1 = _perf_ns()
            out = np.asarray(self._jit_finish(d_ranks, d_ends,
                                              np.int32(n_cells),
                                              np.int32(n_keys)))
            TELEMETRY.record_transfer("d2h", out.nbytes, t1, _perf_ns(),
                                      "log.finish")
            TELEMETRY.note_fire_read()
        else:
            out = np.asarray(self._jit_finish(jax.device_put(ranks_p, dev),
                                              jax.device_put(ends_p, dev),
                                              np.int32(n_cells),
                                              np.int32(n_keys)))
        return out[:n_keys].astype(np.float64)


class _SumMode:
    name = "sum"
    can_compact = True

    @staticmethod
    def upgrade_cols(cols):
        return cols

    def __init__(self, agg: SumAggregate, finish_tier: str):
        self.agg = agg

    def new_log(self):
        return _SumTabLog()

    def make_cols(self, values, value_hashes):
        return (np.asarray(values, np.float64),)

    def compact(self, keys, cols):
        ks, sums = nat.sum_log_fire(keys, cols[0])
        return ks, (sums,)

    def fire(self, keys, cols):
        ks, sums = nat.sum_log_fire(keys, cols[0])
        return ks, sums.astype(self.agg.value_dtype)


class _QuantileMode:
    name = "quantile"
    #: count-combining compaction: (key, bucket) duplicates collapse
    #: into count cells, bounding a window's log at keys x buckets
    #: cells regardless of event volume (the round-2 gap).  Cells are
    #: (bucket u16, count u32); raw appends carry count 1.
    can_compact = True

    def new_log(self):
        return _WindowLog()

    def __init__(self, agg: QuantileSketchAggregate, finish_tier: str):
        if agg.buckets > (1 << 16):
            raise ValueError("log engine supports <= 65536 buckets")
        self.agg = agg

    @staticmethod
    def upgrade_cols(cols):
        """Pre-count-cell checkpoints logged (bucket,) only — raw
        cells, weight 1."""
        if len(cols) == 1:
            return [cols[0], np.ones(len(cols[0]), np.uint32)]
        return cols

    def make_cols(self, values, value_hashes):
        # numpy twin of QuantileSketchAggregate._bucket_of (f32 math to
        # match the device kernel's bucketing)
        agg = self.agg
        v = np.asarray(values, np.float32)
        logs = np.log(np.maximum(v, np.float32(agg.min_value)),
                      dtype=np.float32) / np.float32(agg.log_gamma)
        b = 1 + np.floor(logs).astype(np.int32) - agg.offset
        b = np.clip(b, 1, agg.buckets - 1)
        b = np.where(v <= agg.min_value, 0, b)
        return (b.astype(np.uint16), np.ones(len(v), np.uint32))

    def compact(self, keys, cols):
        ck, cb, cc = nat.qsketch_log_compact(keys, cols[0], cols[1],
                                             self.agg.buckets)
        return ck, (cb, cc)

    def fire(self, keys, cols):
        agg = self.agg
        # the kernel computes gamma^(b-0.5) * mid_corr; folding
        # sqrt(gamma) into the correction yields the canonical
        # DDSketch estimate 2*gamma^b/(gamma+1) (symmetric +-alpha —
        # see QuantileSketchAggregate.result)
        mid_corr = 2.0 * float(np.sqrt(agg.gamma)) / (1.0 + agg.gamma)
        # never-compacted logs are all count-1 cells: the unweighted
        # kernel path carries the bucket inside the sorted record
        # (sequential walk, no per-cell gather) — one vectorized scan
        # decides, which is noise next to the sort it saves on
        counts = cols[1]
        if (counts == 1).all():
            counts = None
        ks, q = nat.qsketch_log_fire(keys, cols[0], agg.buckets,
                                     agg.quantiles, agg.log_gamma,
                                     agg.offset, mid_corr,
                                     counts=counts)
        return ks, q


def _as_u64_keys(engine, keys) -> np.ndarray:
    """Normalize integer keys to their uint64 bit pattern (exact
    grouping for signed and unsigned alike); the signedness is locked
    on the first batch — a later flip would silently reinterpret keys
    >= 2^63 emitted from earlier batches, so it is rejected."""
    keys = np.asarray(keys)
    if not np.issubdtype(keys.dtype, np.integer):
        raise TypeError("log engine requires integer keys "
                        "(the key rides in the log)")
    signed = bool(np.issubdtype(keys.dtype, np.signedinteger))
    if engine._keys_signed is None:
        engine._keys_signed = signed
    elif engine._keys_signed != signed:
        raise TypeError(
            "key dtype signedness changed mid-stream "
            f"(was {'signed' if engine._keys_signed else 'unsigned'}, "
            f"got {keys.dtype}); keep the key dtype stable")
    if signed:
        return keys.astype(np.int64, copy=False).view(np.uint64)
    return keys.astype(np.uint64, copy=False)


def _keys_out(engine, keys_u64: np.ndarray) -> np.ndarray:
    return (keys_u64.view(np.int64) if engine._keys_signed
            else keys_u64)


def _mode_for(agg: DeviceAggregateFunction, finish_tier: str):
    if isinstance(agg, HyperLogLogAggregate):
        return _HllMode(agg, finish_tier)
    if isinstance(agg, SumAggregate):
        return _SumMode(agg, finish_tier)
    if isinstance(agg, QuantileSketchAggregate):
        return _QuantileMode(agg, finish_tier)
    raise TypeError(
        "log-structured engines support HyperLogLog / Sum / "
        "QuantileSketch cell decompositions; use the vectorized "
        f"engines for {type(agg).__name__}")


# ---------------------------------------------------------------------
# engines
# ---------------------------------------------------------------------

class LogStructuredTumblingWindows:
    """Batched keyBy().window(Tumbling...).aggregate(agg), combiner
    tier.  Same engine interface as VectorizedTumblingWindows.

    finish_tier: "host" (C++ fused sort+reduce), "device" (C++
    sort/compact, then one jitted finish on TPU — HLL only), or
    "auto" (resolved by the one-shot H2D link micro-probe in
    flink_tpu/ops/link_probe.py: tunnel-attached chips run the finish
    on host, pod-attached chips on device — both sides measured, see
    BENCH_NOTES.md and the hll_device bench entry).
    """

    def __init__(self, aggregate: DeviceAggregateFunction,
                 window_size_ms: int,
                 compact_threshold: int = 64 << 20,
                 finish_tier: str = "auto",
                 emit=None):
        if not nat.available():
            raise RuntimeError(f"native runtime required: {nat.load_error()}")
        self.agg = aggregate
        self.mode = _mode_for(aggregate, finish_tier)
        self.size = window_size_ms
        #: how far past a (pane) start a record stays live — the
        #: sliding subclass widens this to the full window size
        self.lateness_horizon = window_size_ms
        self.compact_threshold = compact_threshold
        self.windows: Dict[int, _WindowLog] = {}
        self.watermark = -(2 ** 63)
        self.emit = emit
        self.emitted: List[Tuple[Any, Any, int, int]] = []
        self.emit_arrays = False
        self.fired: List[Tuple[np.ndarray, np.ndarray, int, int]] = []
        self.num_late_dropped = 0
        #: signed input keys ride as their uint64 bit pattern and view
        #: back at fire (locked on the first batch)
        self._keys_signed = None
        #: window start -> (log version, chunk hash) — skips
        #: re-hashing unchanged windows at snapshot time
        self._chunk_cache: Dict[int, Tuple[int, str]] = {}

    # ---- ingestion --------------------------------------------------
    def process_batch(self, keys, timestamps, values=None,
                      key_hashes=None, value_hashes=None) -> None:
        ts = np.asarray(timestamps, np.int64)
        keys = _as_u64_keys(self, keys)
        starts = ts - np.mod(ts, self.size)
        live = starts + self.lateness_horizon - 1 > self.watermark
        if not live.all():
            self.num_late_dropped += int((~live).sum())
            if not live.any():
                return
            keys, ts, starts = keys[live], ts[live], starts[live]
            if values is not None:
                values = np.asarray(values)[live]
            if value_hashes is not None:
                value_hashes = np.asarray(value_hashes)[live]

        cols = self.mode.make_cols(values, value_hashes)
        # skip np.unique's sort for the common single-window batch
        uniq_starts = (starts[:1] if _is_single_window(starts)
                       else np.unique(starts))
        for start in uniq_starts:
            log = self.windows.get(int(start))
            if log is None:
                log = self.windows[int(start)] = self.mode.new_log()
            if len(uniq_starts) == 1:
                log.append(keys, *cols)
            else:
                mask = starts == start
                log.append(keys[mask], *(c[mask] for c in cols))
            if self.mode.can_compact \
                    and log.should_compact(self.compact_threshold):
                log.compact(self.mode)

    def flush(self, grow_to: Optional[int] = None) -> None:
        """No device micro-batch to flush — kept for interface parity."""

    # ---- firing -----------------------------------------------------
    def advance_watermark(self, watermark: int) -> int:
        self.watermark = watermark
        fired = 0
        for start in sorted(self.windows):
            if start + self.size - 1 > watermark:
                continue
            log = self.windows.pop(start)
            if log.count == 0:
                continue
            keys, cols = log.concat()
            fired += self._fire_window(keys, cols, start, start + self.size)
        if TELEMETRY.enabled:
            TELEMETRY.note_windows_fired(fired)
        return fired

    def _fire_window(self, keys, cols, start: int, end: int) -> int:
        out_keys, results = self.mode.fire(keys, cols)
        self._emit(_keys_out(self, out_keys), results, start, end)
        return len(out_keys)

    def _emit(self, out_keys, results, start: int, end: int) -> None:
        if self.emit_arrays:
            self.fired.append((out_keys, results, start, end))
        elif self.emit is not None:
            for k, r in zip(out_keys, results):
                self.emit(k, r, start, end)
        else:
            self.emitted.extend(zip(out_keys, results,
                                    [start] * len(out_keys),
                                    [end] * len(out_keys)))

    # ---- checkpoint integration ------------------------------------
    def snapshot(self) -> dict:
        """Per-window compacted logs as content-addressed SharedChunks
        — the storage stores each distinct chunk once across retained
        checkpoints, so a window that received no records since the
        last checkpoint re-uploads ~0 bytes (round-2 verdict item 4;
        ref role: the RocksDB backend's per-SST incremental upload).
        A version cache skips re-hashing untouched windows; payloads
        stay attached so local-recovery restores never need the
        storage registry."""
        from flink_tpu.state.shared_registry import SharedChunk
        wins = {}
        live_starts = set()
        for start, log in self.windows.items():
            start = int(start)
            live_starts.add(start)
            cached = self._chunk_cache.get(start)
            keys, cols = log.concat()
            # ALWAYS copy: the payload may be stored by any retained
            # checkpoint (even one whose predecessor aborted before
            # registering), so it must never alias live arrays.  The
            # version cache only skips the re-HASH.
            payload = {"keys": keys.copy(),
                       "cols": [c.copy() for c in cols]}
            if cached is not None and cached[0] == log.version:
                wins[start] = SharedChunk(payload, chunk_hash=cached[1])
                continue
            chunk = SharedChunk(payload)
            self._chunk_cache[start] = (log.version, chunk.hash)
            wins[start] = chunk
        for start in list(self._chunk_cache):
            if start not in live_starts:
                del self._chunk_cache[start]
        return {"mode": self.mode.name, "size": self.size,
                "watermark": self.watermark,
                "num_late_dropped": self.num_late_dropped,
                "windows": wins,
                "keys_signed": self._keys_signed,
                # sliding subclass: without it a restored engine would
                # re-fire already-fired windows from pruned panes
                "fired_horizon": getattr(self, "_fired_horizon", None)}

    def restore(self, snap: dict) -> None:
        self.restore_many([snap])

    def restore_many(self, snaps, keep_fn=None) -> None:
        """Restore from one snapshot — or MERGE several after a
        parallelism change, keeping only the rows this subtask owns
        (`keep_fn`: uint64 key bit-patterns → bool mask, the
        key-group-range filter; ref StateAssignmentOperation.java's
        key-group re-split).  Merging is exact because a window's
        state IS its log: concatenation then fire-time sort/reduce
        equals any other grouping of the same rows."""
        from flink_tpu.state.shared_registry import SharedChunk
        self.watermark = max(s["watermark"] for s in snaps)
        self.num_late_dropped = sum(s["num_late_dropped"] for s in snaps)
        signed = {s["keys_signed"] for s in snaps
                  if s.get("keys_signed") is not None}
        if len(signed) > 1:
            raise ValueError("snapshots disagree on key signedness")
        self._keys_signed = signed.pop() if signed else None
        horizons = [s["fired_horizon"] for s in snaps
                    if s.get("fired_horizon") is not None]
        if horizons:
            self._fired_horizon = max(horizons)
        self.windows = {}
        self._chunk_cache = {}
        for snap in snaps:
            for start, w in snap["windows"].items():
                if isinstance(w, SharedChunk):  # un-resolved (local)
                    w = w.payload
                keys = np.asarray(w["keys"], np.uint64)
                cols = self.mode.upgrade_cols(
                    [np.asarray(c) for c in w["cols"]])
                if keep_fn is not None:
                    m = keep_fn(keys)
                    if not m.all():
                        keys = keys[m]
                        cols = [c[m] for c in cols]
                if not len(keys):
                    continue
                log = self.windows.get(int(start))
                if log is None:
                    log = self.windows[int(start)] = self.mode.new_log()
                log.append(keys, *cols)

    def block_until_ready(self) -> None:
        """Host-tier state is always materialized."""


class StringSumTumblingWindows:
    """Fused wordcount engine for STRING keys: one C++ pass per batch
    interns each word and accumulates its weight into a dense
    id-indexed per-window sum array (native ``ft_intern_sum``:
    phase-split hashing, first-probe and verify loops run with full
    instruction-level parallelism — the structural edge the batch
    interface has over the reference's per-record
    HeapAggregatingState.add, which serializes hash → probe → verify →
    add per record).  keyBy("word") .window(Tumbling) .aggregate(Sum)
    lands here (ref shape: SocketWindowWordCount.java:70-84).  Same
    engine interface as the other tiers; emits original word strings.
    """

    def __init__(self, aggregate, window_size_ms: int, emit=None):
        if not nat.available():
            raise RuntimeError(f"native runtime required: {nat.load_error()}")
        self.agg = aggregate
        self.size = window_size_ms
        self.lateness_horizon = window_size_ms
        self.interner = nat.NativeStringInterner()
        self.directory: List[str] = []          # id -> word
        self._dir_arr = None                    # cached np view
        self.windows: Dict[int, Any] = {}       # start -> NativeWordSums
        self.watermark = -(2 ** 63)
        self.emit = emit
        self.emitted: List[Tuple[Any, Any, int, int]] = []
        self.emit_arrays = False
        self.fired: List[Tuple[np.ndarray, np.ndarray, int, int]] = []
        self.num_late_dropped = 0

    def process_batch(self, keys, timestamps, values=None,
                      key_hashes=None, value_hashes=None) -> None:
        keys = np.asarray(keys)
        if keys.dtype.kind not in "US":
            keys = keys.astype(np.str_)
        ts = np.asarray(timestamps, np.int64)
        starts = ts - np.mod(ts, self.size)
        # single-window batch (the replayed-log shape): skip the
        # unique sort and the masks — they cost more than the fused
        # kernel saves
        if _is_single_window(starts) \
                and int(starts[0]) + self.lateness_horizon - 1 \
                > self.watermark:
            self._ingest(int(starts[0]), keys, values)
            return
        live = starts + self.lateness_horizon - 1 > self.watermark
        if not live.all():
            self.num_late_dropped += int((~live).sum())
            if not live.any():
                return
            keys, starts = keys[live], starts[live]
            if values is not None:
                values = np.asarray(values)[live]
        for start in np.unique(starts).tolist():
            m = starts == start
            self._ingest(int(start),
                         keys if m.all() else keys[m],
                         None if values is None
                         else (values if m.all()
                               else np.asarray(values)[m]))

    def _ingest(self, start: int, w_keys, w_vals) -> None:
        ws = self.windows.get(start)
        if ws is None:
            ws = self.windows[start] = nat.NativeWordSums()
        first_idx = ws.add(self.interner, w_keys, w_vals)
        if len(first_idx):
            self.directory.extend(w_keys[first_idx].tolist())
            self._dir_arr = None

    def flush(self, grow_to=None) -> None:
        """Interface parity."""

    def advance_watermark(self, watermark: int) -> int:
        self.watermark = watermark
        fired = 0
        for start in sorted(self.windows):
            if start + self.size - 1 > watermark:
                continue
            ws = self.windows.pop(start)
            ids, sums = ws.fire()
            if not len(ids):
                continue
            if self._dir_arr is None:
                self._dir_arr = np.asarray(self.directory, dtype=object)
            words = self._dir_arr[ids]
            results = sums.astype(self.agg.value_dtype, copy=False)
            end = start + self.size
            if self.emit_arrays:
                self.fired.append((words, results, start, end))
            elif self.emit is not None:
                for k, r in zip(words, results):
                    self.emit(k, r, start, end)
            else:
                self.emitted.extend(zip(words, results,
                                        [start] * len(ids),
                                        [end] * len(ids)))
            fired += len(ids)
        return fired

    def snapshot(self) -> dict:
        wins = {}
        for start, ws in self.windows.items():
            ids, sums = ws.fire()       # export...
            ws.load(ids, sums)          # ...and restore in place
            wins[int(start)] = {"ids": ids, "sums": sums}
        return {"mode": "string_sum", "size": self.size,
                "watermark": self.watermark,
                "num_late_dropped": self.num_late_dropped,
                "directory": list(self.directory),
                "windows": wins}

    def restore(self, snap: dict) -> None:
        self.watermark = snap["watermark"]
        self.num_late_dropped = snap["num_late_dropped"]
        self.directory = list(snap["directory"])
        self._dir_arr = None
        self.interner = nat.NativeStringInterner(
            max(16, 2 * len(self.directory)))
        if self.directory:
            # dense first-seen ids: re-interning the directory in
            # order reproduces every id
            self.interner.intern(np.asarray(self.directory))
        self.windows = {}
        for start, w in snap["windows"].items():
            ws = nat.NativeWordSums()
            ws.load(np.asarray(w["ids"], np.int64),
                    np.asarray(w["sums"], np.float64))
            self.windows[int(start)] = ws

    def restore_many(self, snaps, keep_fn=None) -> None:
        """Merge snapshots after a parallelism change: ids are dense
        PER-SUBTASK, so each snapshot's ids translate back to words
        through its own directory and re-intern here; sums are
        additive, so re-adding merges exactly.  keep_fn filters WORD
        arrays to this subtask's key groups."""
        if len(snaps) == 1 and keep_fn is None:
            self.restore(snaps[0])
            return
        self.watermark = max(s["watermark"] for s in snaps)
        self.num_late_dropped = sum(s["num_late_dropped"] for s in snaps)
        self.directory = []
        self._dir_arr = None
        self.interner = nat.NativeStringInterner()
        self.windows = {}
        for snap in snaps:
            directory = np.asarray(snap["directory"], dtype=object)
            for start, w in snap["windows"].items():
                ids = np.asarray(w["ids"], np.int64)
                if not len(ids):
                    continue
                words = directory[ids].astype(np.str_)
                sums = np.asarray(w["sums"], np.float64)
                if keep_fn is not None:
                    m = keep_fn(words)
                    if not m.any():
                        continue
                    if not m.all():
                        words, sums = words[m], sums[m]
                self._ingest(int(start), words, sums)

    def block_until_ready(self) -> None:
        """Host-tier state is always materialized."""


class LogStructuredSlidingWindows(LogStructuredTumblingWindows):
    """Sliding windows composed from slide-granularity pane logs.

    Ingest appends each record ONCE to its pane's log; a window's fire
    concatenates the size/slide pane logs — the sort+reduce regroups
    keys across panes, so pane merging costs nothing beyond the fire
    itself.  Semantics match WindowOperator + SlidingEventTimeWindows
    with lateness 0 (same fire/prune rules as
    VectorizedSlidingWindows)."""

    def __init__(self, aggregate: DeviceAggregateFunction,
                 window_size_ms: int, slide_ms: int,
                 compact_threshold: int = 64 << 20,
                 finish_tier: str = "auto", emit=None):
        if window_size_ms % slide_ms != 0:
            raise ValueError("window size must be a multiple of the slide")
        super().__init__(aggregate, slide_ms, compact_threshold,
                         finish_tier, emit)
        self.window_size = window_size_ms
        self.slide = slide_ms
        self.lateness_horizon = window_size_ms
        self._fired_horizon = -(2 ** 63)

    def advance_watermark(self, watermark: int) -> int:
        prev = self._fired_horizon
        self._fired_horizon = watermark
        self.watermark = watermark
        fired = 0
        if not self.windows:
            return 0
        min_pane = min(self.windows)
        max_pane = max(self.windows)
        hi = min(watermark - self.window_size + 1, max_pane)
        start_from = max(min_pane - self.window_size + self.slide,
                         prev - self.window_size + 2)
        first = -(-start_from // self.slide) * self.slide
        if first <= hi:
            for W in range(first, hi + 1, self.slide):
                logs = [self.windows[p]
                        for p in range(W, W + self.window_size, self.slide)
                        if p in self.windows and self.windows[p].count]
                if not logs:
                    continue
                parts = [lg.concat() for lg in logs]
                keys = (parts[0][0] if len(parts) == 1 else
                        np.concatenate([p[0] for p in parts]))
                n_cols = len(parts[0][1])
                cols = tuple(
                    (parts[0][1][j] if len(parts) == 1 else
                     np.concatenate([p[1][j] for p in parts]))
                    for j in range(n_cols))
                fired += self._fire_window(keys, cols, W,
                                           W + self.window_size)
        # prune panes no future window needs
        for P in sorted(self.windows):
            if P + self.window_size - 1 > watermark:
                break
            del self.windows[P]
        if TELEMETRY.enabled:
            TELEMETRY.note_windows_fired(fired)
        return fired


class LogStructuredSessionWindows:
    """Session windows (gap-merged, EventTimeSessionWindows /
    MergingWindowSet.java:156 semantics) + Count-Min totals over an
    event log.

    Ingest appends (key, ts, weight, value-hash); the watermark fire
    sorts by (key, ts), splits runs at gaps (inclusive — abutting
    windows merge, TimeWindow.intersects), closes sessions with
    end-1 <= watermark (each closed session's Count-Min builds in an
    L1-resident scratch) and retains open sessions' events.
    """

    def __init__(self, aggregate: CountMinSketchAggregate, gap_ms: int,
                 emit=None):
        if not isinstance(aggregate, CountMinSketchAggregate):
            raise TypeError("session log engine aggregates Count-Min")
        if not nat.available():
            raise RuntimeError(f"native runtime required: {nat.load_error()}")
        self.agg = aggregate
        self.gap = gap_ms
        self.watermark = -(2 ** 63)
        self.emit = emit
        self.emitted: List[Tuple[Any, Any, int, int]] = []
        self.emit_arrays = False
        self.fired: List[Tuple[np.ndarray, np.ndarray, int, int]] = []
        self.num_late_dropped = 0
        self._keys_signed = None
        self._log_keys: List[np.ndarray] = []
        self._log_ts: List[np.ndarray] = []
        self._log_w: List[np.ndarray] = []
        self._log_vh: List[np.ndarray] = []
        #: open-session rows carried from the last fire, in (key, ts)
        #: order exactly as the kernel returned them — passed back
        #: verbatim (the kernel merges them as a key-major stream;
        #: re-sorting here would corrupt the merge)
        self._ret: Optional[Tuple[np.ndarray, ...]] = None

    def process_batch(self, keys, timestamps, values=None,
                      key_hashes=None, value_hashes=None) -> None:
        ts = np.asarray(timestamps, np.int64)
        keys = _as_u64_keys(self, keys)
        # lateness 0: an event whose own window [ts, ts+gap) has
        # end-1 <= watermark is late.  (A post-merge refinement — the
        # event might still touch a LIVE session — cannot apply here:
        # the kernel keeps no host-visible open-session rows to test
        # against, and closed sessions already fired, so accepting it
        # could change an emitted result.  The vectorized engine DOES
        # apply it: GenericLogSessionWindows._revive_late keeps a
        # merge-chained straggler exactly as the reference's
        # merge-then-isWindowLate order does, WindowOperator.java:
        # 308-343.  This engine's stricter drop remains within the
        # reference's lateness-0 contract for events that merge into
        # nothing open.)
        live = ts + self.gap - 1 > self.watermark
        if not live.all():
            self.num_late_dropped += int((~live).sum())
            if not live.any():
                return
            keys, ts = keys[live], ts[live]
            if values is not None:
                values = np.asarray(values)[live]
            if value_hashes is not None:
                value_hashes = np.asarray(value_hashes)[live]
        if value_hashes is None:
            from flink_tpu.streaming.vectorized import hash_keys_np
            value_hashes = hash_keys_np(values)
        # per-event int truncation, matching the device tier
        # (CountMinSketchAggregate.update casts each weight to int32)
        # so both engines implement one semantics for fractional
        # weights (round-2 advisor finding)
        w = (np.ones(len(keys), np.float32) if values is None
             else np.asarray(values).astype(np.int32).astype(np.float32))
        self._log_keys.append(keys)
        self._log_ts.append(ts)
        self._log_w.append(w)
        self._log_vh.append(np.asarray(value_hashes, np.uint64))

    def flush(self, grow_to=None) -> None:
        """Interface parity."""

    def advance_watermark(self, watermark: int) -> int:
        self.watermark = watermark
        if not self._log_keys and self._ret is None:
            return 0
        cat = (lambda xs, dt: xs[0] if len(xs) == 1
               else (np.concatenate(xs) if xs
                     else np.empty(0, dt)))
        keys = cat(self._log_keys, np.uint64)
        ts = cat(self._log_ts, np.int64)
        w = cat(self._log_w, np.float32)
        vh = cat(self._log_vh, np.uint64)
        # the kernel merges the retained set (key-major, verbatim from
        # the last fire) with the ts-sorted feed itself — no host-side
        # merge/sort pass exists on this path, and retained rows are
        # never re-sorted across fires
        ok, os_, oe, ot, retained = nat.session_log_fire(
            keys, ts, w, vh, self.gap, watermark,
            self.agg.depth, self.agg.width, retained=self._ret)
        self._ret = retained if len(retained[0]) else None
        self._log_keys, self._log_ts = [], []
        self._log_w, self._log_vh = [], []
        totals = ot.astype(np.int64)
        ok = _keys_out(self, ok)
        if self.emit_arrays:
            if len(ok):
                self.fired.append((ok, totals, os_, oe))
        elif self.emit is not None:
            for k, t, s, e in zip(ok, totals, os_, oe):
                self.emit(k, t, int(s), int(e))
        else:
            self.emitted.extend(
                (k, t, int(s), int(e))
                for k, t, s, e in zip(ok, totals, os_, oe))
        return len(ok)

    def snapshot(self) -> dict:
        ret = self._ret or (np.empty(0, np.uint64),
                            np.empty(0, np.int64),
                            np.empty(0, np.float32),
                            np.empty(0, np.uint64))
        cat = (lambda xs, extra: np.concatenate([extra, *xs])
               if xs else extra.copy())
        return {"watermark": self.watermark,
                "num_late_dropped": self.num_late_dropped,
                "keys_signed": self._keys_signed,
                "keys": cat(self._log_keys, ret[0]),
                "ts": cat(self._log_ts, ret[1]),
                "w": cat(self._log_w, ret[2]),
                "vh": cat(self._log_vh, ret[3])}

    def restore(self, snap: dict) -> None:
        self.restore_many([snap])

    def restore_many(self, snaps, keep_fn=None) -> None:
        """Restore/merge retained open-session events, filtered to
        this subtask's key groups on rescale (sessions are per-key, so
        a key-partitioned split of the event log is exact)."""
        self.watermark = max(s["watermark"] for s in snaps)
        self.num_late_dropped = sum(s["num_late_dropped"] for s in snaps)
        signed = {s["keys_signed"] for s in snaps
                  if s.get("keys_signed") is not None}
        if len(signed) > 1:
            raise ValueError("snapshots disagree on key signedness")
        self._keys_signed = signed.pop() if signed else None
        self._log_keys, self._log_ts = [], []
        self._log_w, self._log_vh = [], []
        self._ret = None
        for snap in snaps:
            keys = np.asarray(snap["keys"], np.uint64)
            if not len(keys):
                continue
            m = keep_fn(keys) if keep_fn is not None else None
            if m is not None and not m.any():
                continue
            sel = (lambda a: a) if m is None or m.all() \
                else (lambda a, m=m: np.asarray(a)[m])
            self._log_keys.append(sel(keys))
            self._log_ts.append(sel(snap["ts"]))
            self._log_w.append(sel(snap["w"]))
            self._log_vh.append(sel(snap["vh"]))

    def block_until_ready(self) -> None:
        """Host-tier state is always materialized."""
