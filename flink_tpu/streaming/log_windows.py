"""Log-structured tumbling-window engine — the combiner tier.

The reference's windowed-aggregation hot path is one random
read-modify-write of keyed state per record (heap:
``HeapAggregatingState.add`` → ``stateTable.transform``,
HeapAggregatingState.java:80-89; RocksDB: a get/deserialize/add/put
round trip, RocksDBAggregatingState.java:108-131).  At multi-GB state
that mechanism is memory-latency-bound on every substrate — the
compiled host baseline and the XLA scatter path both measure in the
single-digit M updates/s (BENCH_NOTES.md).

This engine restructures the work the TPU-first way (SURVEY.md §7
"per-record semantics vs batched execution"): **ingest appends** the
record's aggregate *cells* to a per-window log at memcpy speed, and
the **fire sorts the log and reduces each key's run densely** —
adaptive LSD radix sort + segmented reduction (native/host_runtime.cpp
``ft_hll_log_*`` / ``ft_sum_log_fire``), with an optional on-device
finish (`finish_tier="device"`) that runs the transcendental estimate
phase as one jitted scan over the compacted cells.  It is the same
pre-aggregation seam the reference exposes as chained combiners
(AggregateUtil.scala:1028): state per window is bounded by
min(events, keys x m) via periodic log compaction, and a window's
state snapshot is its (compacted) log — smaller than a dense register
file whenever events/window < keys x m.

Scope: integer-keyed streams (the key rides in the log; grouping is
exact, no hash collisions) and the mergeable aggregates with a cell
decomposition — HyperLogLog (cell = (register, rank), combine = max)
and Sum (cell = value, combine = add).  Other aggregates use the
device-resident scatter engine (vectorized.py), which also remains
the multi-chip path (parallel/mesh_windows.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import flink_tpu.native as nat
from flink_tpu.ops.device_agg import DeviceAggregateFunction, SumAggregate
from flink_tpu.ops.hashing import split_hash64_np
from flink_tpu.ops.sketches import HyperLogLogAggregate


class _WindowLog:
    """Columnar append log for one window."""

    __slots__ = ("keys", "cols", "count")

    def __init__(self):
        self.keys: List[np.ndarray] = []
        self.cols: List[Tuple[np.ndarray, ...]] = []
        self.count = 0

    def append(self, keys: np.ndarray, *cols: np.ndarray) -> None:
        self.keys.append(keys)
        self.cols.append(cols)
        self.count += len(keys)

    def concat(self) -> Tuple[np.ndarray, Tuple[np.ndarray, ...]]:
        keys = (self.keys[0] if len(self.keys) == 1
                else np.concatenate(self.keys))
        n_cols = len(self.cols[0])
        cols = tuple(
            (self.cols[0][j] if len(self.cols) == 1
             else np.concatenate([c[j] for c in self.cols]))
            for j in range(n_cols))
        self.keys = [keys]
        self.cols = [cols]
        return keys, cols


class LogStructuredTumblingWindows:
    """Batched keyBy().window(Tumbling...).aggregate(agg), combiner
    tier.  Same engine interface as VectorizedTumblingWindows.

    finish_tier: "host" (C++ fused sort+estimate), "device" (C++
    sort/compact, then one jitted exp2/cumsum finish on TPU), or
    "auto" (host — on tunnel-attached chips the 34 MB/window D2H of
    the scan exceeds the host finish; flip to device on pod hosts).
    """

    def __init__(self, aggregate: DeviceAggregateFunction,
                 window_size_ms: int,
                 compact_threshold: int = 64 << 20,
                 finish_tier: str = "auto",
                 emit=None):
        if isinstance(aggregate, HyperLogLogAggregate):
            if aggregate.precision > 16:
                raise ValueError("log engine supports precision <= 16 "
                                 "(u16 register cells)")
            self._mode = "hll"
        elif isinstance(aggregate, SumAggregate):
            self._mode = "sum"
        else:
            raise TypeError(
                "LogStructuredTumblingWindows supports HyperLogLog and Sum "
                "cell decompositions; use VectorizedTumblingWindows for "
                f"{type(aggregate).__name__}")
        if not nat.available():
            raise RuntimeError(f"native runtime required: {nat.load_error()}")
        self.agg = aggregate
        self.size = window_size_ms
        self.compact_threshold = compact_threshold
        self.finish_tier = finish_tier
        self.windows: Dict[int, _WindowLog] = {}
        self.watermark = -(2 ** 63)
        self.emit = emit
        self.emitted: List[Tuple[Any, Any, int, int]] = []
        self.emit_arrays = False
        self.fired: List[Tuple[np.ndarray, np.ndarray, int, int]] = []
        self.num_late_dropped = 0
        self._jit_finish = None

    # ---- ingestion --------------------------------------------------
    def process_batch(self, keys, timestamps, values=None,
                      key_hashes=None, value_hashes=None) -> None:
        ts = np.asarray(timestamps, np.int64)
        keys = np.asarray(keys)
        if not np.issubdtype(keys.dtype, np.integer):
            raise TypeError("log engine requires integer keys "
                            "(the key rides in the log)")
        keys = keys.astype(np.uint64, copy=False)
        starts = ts - np.mod(ts, self.size)
        live = starts + self.size - 1 > self.watermark
        if not live.all():
            self.num_late_dropped += int((~live).sum())
            if not live.any():
                return
            keys, ts, starts = keys[live], ts[live], starts[live]
            if values is not None:
                values = np.asarray(values)[live]
            if value_hashes is not None:
                value_hashes = np.asarray(value_hashes)[live]

        if self._mode == "hll":
            if value_hashes is None:
                from flink_tpu.streaming.vectorized import hash_keys_np
                value_hashes = hash_keys_np(values)
            hi, lo = split_hash64_np(value_hashes)
            ranks, regs = self.agg.compress_value_hash(hi, lo)
            cols = (np.ascontiguousarray(regs, np.uint16),
                    np.ascontiguousarray(ranks, np.uint8))
        else:
            cols = (np.asarray(values, np.float64),)

        uniq_starts = np.unique(starts)
        for start in uniq_starts:
            log = self.windows.get(int(start))
            if log is None:
                log = self.windows[int(start)] = _WindowLog()
            if len(uniq_starts) == 1:
                log.append(keys, *cols)
            else:
                mask = starts == start
                log.append(keys[mask], *(c[mask] for c in cols))
            if log.count > self.compact_threshold:
                self._compact(log)

    def flush(self, grow_to: Optional[int] = None) -> None:
        """No device micro-batch to flush — kept for interface parity."""

    def _compact(self, log: _WindowLog) -> None:
        keys, cols = log.concat()
        if self._mode == "hll":
            ck, cr, crk, _ = nat.hll_log_compact(
                keys, cols[0], cols[1], self.agg.precision)
            log.keys = [ck]
            log.cols = [(cr, crk)]
            log.count = len(ck)
        else:
            ks, sums = nat.sum_log_fire(keys, cols[0])
            log.keys = [ks]
            log.cols = [(sums,)]
            log.count = len(ks)

    # ---- firing -----------------------------------------------------
    def advance_watermark(self, watermark: int) -> int:
        self.watermark = watermark
        fired = 0
        for start in sorted(self.windows):
            if start + self.size - 1 > watermark:
                continue
            log = self.windows.pop(start)
            if log.count == 0:
                continue
            keys, cols = log.concat()
            if self._mode == "hll":
                out_keys, results = self._fire_hll(keys, cols)
            else:
                out_keys, results = nat.sum_log_fire(keys, cols[0])
                results = results.astype(self.agg.value_dtype)
            end = start + self.size
            if self.emit_arrays:
                self.fired.append((out_keys, results, start, end))
            elif self.emit is not None:
                for k, r in zip(out_keys, results):
                    self.emit(k, r, start, end)
            else:
                self.emitted.extend(zip(out_keys, results,
                                        [start] * len(out_keys),
                                        [end] * len(out_keys)))
            fired += len(out_keys)
        return fired

    def _fire_hll(self, keys, cols):
        if self.finish_tier == "device":
            ck, cr, crk, ends = nat.hll_log_compact(
                keys, cols[0], cols[1], self.agg.precision)
            uniq = ck[ends - 1]
            return uniq, self._device_finish(crk, ends)
        return nat.hll_log_fire(keys, cols[0], cols[1], self.agg.precision)

    def _device_finish(self, ranks: np.ndarray, ends: np.ndarray):
        """One jitted pass over the compacted cells: exp2 contributions,
        cumsum, per-key diff at run ends, estimate — the dense phase of
        the fire on the device (pads to power-of-two jit shapes)."""
        import jax
        import jax.numpy as jnp

        if self._jit_finish is None:
            m = float(self.agg.m)
            alpha = self.agg.alpha

            def finish(ranks_p, ends_p, n_cells, n_keys):
                cell_live = jnp.arange(ranks_p.shape[0]) < n_cells
                inv = jnp.where(
                    cell_live,
                    jnp.exp2(-ranks_p.astype(jnp.float32)) - 1.0, 0.0)
                cs = jnp.cumsum(inv)
                key_live = jnp.arange(ends_p.shape[0]) < n_keys
                e = jnp.where(key_live, ends_p, 1)
                cum_at_end = cs[e - 1]
                prev = jnp.concatenate([jnp.zeros(1), cum_at_end[:-1]])
                seg = cum_at_end - prev
                prev_e = jnp.concatenate(
                    [jnp.zeros(1, e.dtype), e[:-1]])
                n_present = (e - prev_e).astype(jnp.float32)
                sum_inv = m + seg
                est = alpha * m * m / sum_inv
                zeros = m - n_present
                linear = m * (jnp.log(m) - jnp.log(jnp.maximum(zeros, 1.0)))
                return jnp.where((est <= 2.5 * m) & (zeros > 0),
                                 linear, est)

            self._jit_finish = jax.jit(finish, static_argnums=())
        n_cells, n_keys = len(ranks), len(ends)
        pc = 1 << max(0, (n_cells - 1)).bit_length()
        pk = 1 << max(0, (n_keys - 1)).bit_length()
        ranks_p = np.zeros(pc, np.uint8)
        ranks_p[:n_cells] = ranks
        ends_p = np.ones(pk, np.int32)
        ends_p[:n_keys] = ends
        out = np.asarray(self._jit_finish(ranks_p, ends_p,
                                          np.int32(n_cells),
                                          np.int32(n_keys)))
        return out[:n_keys].astype(np.float64)

    # ---- checkpoint integration ------------------------------------
    def snapshot(self) -> dict:
        wins = {}
        for start, log in self.windows.items():
            keys, cols = log.concat()
            wins[int(start)] = {"keys": keys.copy(),
                                "cols": [c.copy() for c in cols]}
        return {"mode": self._mode, "size": self.size,
                "watermark": self.watermark,
                "num_late_dropped": self.num_late_dropped,
                "windows": wins}

    def restore(self, snap: dict) -> None:
        self.watermark = snap["watermark"]
        self.num_late_dropped = snap["num_late_dropped"]
        self.windows = {}
        for start, w in snap["windows"].items():
            log = _WindowLog()
            log.append(np.asarray(w["keys"], np.uint64),
                       *(np.asarray(c) for c in w["cols"]))
            self.windows[int(start)] = log

    def block_until_ready(self) -> None:
        """Host-tier state is always materialized."""
