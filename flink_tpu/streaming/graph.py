"""StreamGraph → JobGraph translation with operator chaining.

Re-designs flink-streaming-java/.../api/graph/: StreamGraphGenerator
(transformation tree → StreamGraph), StreamingJobGraphGenerator.java:80
(createChain :212-242, isChainable :228) and the jobgraph model
(flink-runtime/.../jobgraph/JobGraph.java, JobVertex, OperatorID).

A StreamNode carries an *operator factory* — a zero-arg callable
returning a fresh operator instance — because each parallel subtask
(and each restart) needs its own instance.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, List, Optional

from flink_tpu.streaming.partitioners import (
    ForwardPartitioner,
    StreamPartitioner,
)


class StreamNode:
    def __init__(
        self,
        node_id: int,
        name: str,
        operator_factory: Callable[[], Any],
        parallelism: int = 1,
        max_parallelism: int = 128,
        is_source: bool = False,
        key_selector=None,
        state_backend: Optional[str] = None,
        uid: Optional[str] = None,
        chaining_strategy: str = "always",  # always | head | never
        time_characteristic: str = "event",
        buffer_timeout: int = -1,
    ):
        self.id = node_id
        self.name = name
        self.operator_factory = operator_factory
        self.parallelism = parallelism
        self.max_parallelism = max_parallelism
        self.is_source = is_source
        self.key_selector = key_selector
        self.state_backend = state_backend
        self.uid = uid or f"op-{node_id}-{name}"
        self.chaining_strategy = chaining_strategy
        self.time_characteristic = time_characteristic
        self.buffer_timeout = buffer_timeout

    def __repr__(self):
        return f"StreamNode({self.id}:{self.name} p={self.parallelism})"


class StreamEdge:
    def __init__(self, source_id: int, target_id: int,
                 partitioner: StreamPartitioner, type_number: int = 0,
                 side_output_tag=None):
        self.source_id = source_id
        self.target_id = target_id
        self.partitioner = partitioner
        #: which logical input of the target (0 = first/only, 1 = second)
        self.type_number = type_number
        self.side_output_tag = side_output_tag
        #: iteration back edge (DataStream.iterate): excluded from EOS
        #: and barrier propagation and from chaining
        self.is_feedback = False
        #: wire-codec tier the type-flow prover predicted for this
        #: edge's elements ("col" | "pickle"), or None when the
        #: schema was inconclusive (netchannel decides at runtime)
        self.predicted_codec_tier = None

    def __repr__(self):
        return (f"StreamEdge({self.source_id}->{self.target_id} "
                f"{self.partitioner!r} in{self.type_number})")


class StreamGraph:
    """(ref: StreamGraph.java)"""

    def __init__(self, job_name: str = "job"):
        self.job_name = job_name
        self.nodes: Dict[int, StreamNode] = {}
        self.edges: List[StreamEdge] = []
        self._id_counter = itertools.count(1)

    def new_node_id(self) -> int:
        return next(self._id_counter)

    def add_node(self, node: StreamNode) -> StreamNode:
        self.nodes[node.id] = node
        return node

    def add_edge(self, edge: StreamEdge) -> None:
        self.edges.append(edge)

    def in_edges(self, node_id: int) -> List[StreamEdge]:
        return [e for e in self.edges if e.target_id == node_id]

    def out_edges(self, node_id: int) -> List[StreamEdge]:
        return [e for e in self.edges if e.source_id == node_id]

    def sources(self) -> List[StreamNode]:
        return [n for n in self.nodes.values() if n.is_source]


# ---------------------------------------------------------------------
# JobGraph (chained)
# ---------------------------------------------------------------------

class JobVertex:
    """One schedulable vertex = a chain of StreamNodes
    (ref: JobVertex.java + the chain built by createChain)."""

    def __init__(self, vertex_id: int, chain: List[StreamNode],
                 chain_edges: List[StreamEdge]):
        self.id = vertex_id
        #: topologically ordered: chain[0] is the head (receives input)
        self.chain = chain
        #: intra-chain edges (all ForwardPartitioner)
        self.chain_edges = chain_edges
        self.name = " -> ".join(n.name for n in chain)

    @property
    def head(self) -> StreamNode:
        return self.chain[0]

    @property
    def parallelism(self) -> int:
        return self.head.parallelism

    @property
    def is_source(self) -> bool:
        return self.head.is_source

    def __repr__(self):
        return f"JobVertex({self.id}: {self.name} p={self.parallelism})"


class JobEdge:
    def __init__(self, source_vertex_id: int, target_vertex_id: int,
                 partitioner: StreamPartitioner, type_number: int = 0,
                 side_output_tag=None, source_node_id: int = -1,
                 is_feedback: bool = False):
        self.source_vertex_id = source_vertex_id
        self.target_vertex_id = target_vertex_id
        self.partitioner = partitioner
        self.type_number = type_number
        self.side_output_tag = side_output_tag
        #: which node inside the source chain emits this edge
        self.source_node_id = source_node_id
        self.is_feedback = is_feedback
        #: carried over from the StreamEdge by create_job_graph
        self.predicted_codec_tier = None


class JobGraph:
    """(ref: JobGraph.java)"""

    def __init__(self, job_name: str):
        self.job_name = job_name
        self.vertices: Dict[int, JobVertex] = {}
        self.edges: List[JobEdge] = []
        self.checkpoint_config: Optional[dict] = None

    def in_edges(self, vertex_id: int) -> List[JobEdge]:
        return [e for e in self.edges if e.target_vertex_id == vertex_id]

    def out_edges(self, vertex_id: int) -> List[JobEdge]:
        return [e for e in self.edges if e.source_vertex_id == vertex_id]

    def topological_vertices(self) -> List[JobVertex]:
        order: List[JobVertex] = []
        visited = set()

        def visit(vid: int):
            if vid in visited:
                return
            visited.add(vid)
            for e in self.in_edges(vid):
                if not e.is_feedback:   # back edges would cycle
                    visit(e.source_vertex_id)
            order.append(self.vertices[vid])

        for vid in self.vertices:
            visit(vid)
        return order


def chain_rejection_reasons(edge: StreamEdge,
                            graph: StreamGraph) -> List[str]:
    """Why this edge cannot be operator-chained — empty list means
    chainable.  The boolean gate (:func:`is_chainable`) and the
    pre-flight linter's FT130 diagnostic share this single source of
    truth."""
    up = graph.nodes[edge.source_id]
    down = graph.nodes[edge.target_id]
    reasons: List[str] = []
    if not isinstance(edge.partitioner, ForwardPartitioner):
        reasons.append(
            f"partitioner is {type(edge.partitioner).__name__}, "
            "not forward")
    if edge.is_feedback:
        reasons.append("iteration feedback edge")
    if up.parallelism != down.parallelism:
        reasons.append(
            f"parallelism mismatch ({up.parallelism} -> "
            f"{down.parallelism})")
    if len(graph.in_edges(down.id)) != 1:
        reasons.append(
            f"downstream has {len(graph.in_edges(down.id))} inputs")
    if down.chaining_strategy != "always":
        reasons.append(
            f"downstream chaining strategy is "
            f"'{down.chaining_strategy}'")
    if up.chaining_strategy == "never":
        reasons.append("upstream chaining strategy is 'never'")
    if edge.side_output_tag is not None:
        reasons.append("side-output edge")
    return reasons


def is_chainable(edge: StreamEdge, graph: StreamGraph) -> bool:
    """(ref: StreamingJobGraphGenerator.isChainable :228): forward
    partitioner, same parallelism, single input, chaining allowed."""
    return not chain_rejection_reasons(edge, graph)


def create_job_graph(stream_graph: StreamGraph) -> JobGraph:
    """Greedy chain construction from sources
    (ref: createChain :212-242)."""
    jg = JobGraph(stream_graph.job_name)
    node_to_vertex: Dict[int, int] = {}
    vertex_counter = itertools.count(1)

    def build_chain(head_id: int) -> int:
        if head_id in node_to_vertex:
            return node_to_vertex[head_id]
        chain = [stream_graph.nodes[head_id]]
        chain_edges: List[StreamEdge] = []
        cur = head_id
        while True:
            outs = stream_graph.out_edges(cur)
            if len(outs) != 1:
                break
            e = outs[0]
            if not is_chainable(e, stream_graph):
                break
            chain_edges.append(e)
            cur = e.target_id
            chain.append(stream_graph.nodes[cur])
        vid = next(vertex_counter)
        v = JobVertex(vid, chain, chain_edges)
        jg.vertices[vid] = v
        for n in chain:
            node_to_vertex[n.id] = vid
        return vid

    # heads = sources + any node with a non-chainable incoming edge
    heads = [n.id for n in stream_graph.sources()]
    for e in stream_graph.edges:
        if not is_chainable(e, stream_graph):
            heads.append(e.target_id)
    for h in heads:
        build_chain(h)
    # any node not reached (isolated or multi-output tails) becomes its own head
    for nid in stream_graph.nodes:
        if nid not in node_to_vertex:
            build_chain(nid)

    # cross-chain edges
    chained_edge_ids = {id(e) for v in jg.vertices.values() for e in v.chain_edges}
    for e in stream_graph.edges:
        if id(e) in chained_edge_ids:
            continue
        je = JobEdge(
            node_to_vertex[e.source_id], node_to_vertex[e.target_id],
            e.partitioner, e.type_number, e.side_output_tag,
            source_node_id=e.source_id, is_feedback=e.is_feedback)
        je.predicted_codec_tier = getattr(e, "predicted_codec_tier",
                                          None)
        jg.edges.append(je)
    return jg
