"""Windows, assigners, triggers, evictors.

Re-designs flink-streaming-java/.../api/windowing/ (SURVEY.md §2.3
"Windowing" row — the complete assigner/trigger/evictor inventory).
Window semantics follow the reference exactly: a TimeWindow covers
[start, end); maxTimestamp = end - 1; tumbling/sliding starts align to
`timestamp - (timestamp - offset) % slide`.
"""

from __future__ import annotations

import abc
from typing import Any, Callable, Iterable, List, Optional, Tuple

from flink_tpu.streaming.elements import MAX_TIMESTAMP


class Time:
    """Duration helper (ref: api/windowing/time/Time.java) — value in ms."""

    __slots__ = ("milliseconds",)

    def __init__(self, milliseconds: int):
        self.milliseconds = int(milliseconds)

    @staticmethod
    def milliseconds_of(ms) -> "Time":
        return Time(ms)

    @staticmethod
    def seconds(s) -> "Time":
        return Time(s * 1000)

    @staticmethod
    def minutes(m) -> "Time":
        return Time(m * 60 * 1000)

    @staticmethod
    def hours(h) -> "Time":
        return Time(h * 60 * 60 * 1000)

    @staticmethod
    def days(d) -> "Time":
        return Time(d * 24 * 60 * 60 * 1000)

    def to_milliseconds(self) -> int:
        return self.milliseconds

    def __repr__(self):
        return f"Time({self.milliseconds}ms)"


def _ms(t) -> int:
    if isinstance(t, Time):
        return t.milliseconds
    return int(t)


# ---------------------------------------------------------------------
# Windows (ref: api/windowing/windows/)
# ---------------------------------------------------------------------

class Window(abc.ABC):
    @abc.abstractmethod
    def max_timestamp(self) -> int:
        ...


class TimeWindow(Window):
    """[start, end) (ref: TimeWindow.java)."""

    __slots__ = ("start", "end")

    def __init__(self, start: int, end: int):
        self.start = start
        self.end = end

    def max_timestamp(self) -> int:
        return self.end - 1

    def intersects(self, other: "TimeWindow") -> bool:
        return self.start <= other.end and self.end >= other.start

    def cover(self, other: "TimeWindow") -> "TimeWindow":
        return TimeWindow(min(self.start, other.start), max(self.end, other.end))

    @staticmethod
    def get_window_start_with_offset(timestamp: int, offset: int, window_size: int) -> int:
        """(ref: TimeWindow.java getWindowStartWithOffset)"""
        return timestamp - (timestamp - offset + window_size) % window_size

    # namespace identity: (start, end) — tuples serialize naturally
    def __eq__(self, other):
        return (isinstance(other, TimeWindow) and self.start == other.start
                and self.end == other.end)

    def __hash__(self):
        return hash((self.start, self.end))

    def __lt__(self, other):
        return (self.start, self.end) < (other.start, other.end)

    def __repr__(self):
        return f"TimeWindow[{self.start}, {self.end})"

    def to_namespace(self) -> Tuple[int, int]:
        return (self.start, self.end)

    @staticmethod
    def from_namespace(ns: Tuple[int, int]) -> "TimeWindow":
        return TimeWindow(ns[0], ns[1])


class GlobalWindow(Window):
    """Singleton window covering everything (ref: GlobalWindow.java)."""

    _instance: Optional["GlobalWindow"] = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def max_timestamp(self) -> int:
        return MAX_TIMESTAMP

    def __eq__(self, other):
        return isinstance(other, GlobalWindow)

    def __hash__(self):
        return hash("GlobalWindow")

    def __repr__(self):
        return "GlobalWindow"

    def to_namespace(self):
        return ("__global__",)

    @staticmethod
    def from_namespace(ns) -> "GlobalWindow":
        return GlobalWindow()


# ---------------------------------------------------------------------
# Trigger results & context (ref: triggers/TriggerResult.java, Trigger.java)
# ---------------------------------------------------------------------

class TriggerResult:
    CONTINUE = 0
    FIRE = 1
    PURGE = 2
    FIRE_AND_PURGE = 3

    @staticmethod
    def is_fire(r: int) -> bool:
        return r in (TriggerResult.FIRE, TriggerResult.FIRE_AND_PURGE)

    @staticmethod
    def is_purge(r: int) -> bool:
        return r in (TriggerResult.PURGE, TriggerResult.FIRE_AND_PURGE)


class TriggerContext(abc.ABC):
    """What a trigger may do (ref: Trigger.TriggerContext): timers +
    partitioned trigger state."""

    @abc.abstractmethod
    def register_event_time_timer(self, time: int) -> None: ...

    @abc.abstractmethod
    def register_processing_time_timer(self, time: int) -> None: ...

    @abc.abstractmethod
    def delete_event_time_timer(self, time: int) -> None: ...

    @abc.abstractmethod
    def delete_processing_time_timer(self, time: int) -> None: ...

    @abc.abstractmethod
    def get_current_watermark(self) -> int: ...

    @abc.abstractmethod
    def get_current_processing_time(self) -> int: ...

    @abc.abstractmethod
    def get_partitioned_state(self, descriptor): ...


class Trigger(abc.ABC):
    """(ref: Trigger.java)"""

    def on_element(self, element, timestamp: int, window, ctx: TriggerContext) -> int:
        return TriggerResult.CONTINUE

    def on_event_time(self, time: int, window, ctx: TriggerContext) -> int:
        return TriggerResult.CONTINUE

    def on_processing_time(self, time: int, window, ctx: TriggerContext) -> int:
        return TriggerResult.CONTINUE

    def can_merge(self) -> bool:
        return False

    def on_merge(self, window, ctx) -> None:
        raise NotImplementedError(f"{type(self).__name__} cannot merge")

    def clear(self, window, ctx: TriggerContext) -> None:  # noqa: B027
        pass


class EventTimeTrigger(Trigger):
    """FIRE when the watermark passes window.maxTimestamp
    (ref: EventTimeTrigger.java)."""

    def on_element(self, element, timestamp, window, ctx):
        if window.max_timestamp() <= ctx.get_current_watermark():
            return TriggerResult.FIRE  # late but in allowed lateness
        ctx.register_event_time_timer(window.max_timestamp())
        return TriggerResult.CONTINUE

    def on_event_time(self, time, window, ctx):
        return (TriggerResult.FIRE if time == window.max_timestamp()
                else TriggerResult.CONTINUE)

    def can_merge(self):
        return True

    def on_merge(self, window, ctx):
        if window.max_timestamp() > ctx.get_current_watermark():
            ctx.register_event_time_timer(window.max_timestamp())

    def clear(self, window, ctx):
        ctx.delete_event_time_timer(window.max_timestamp())

    def __repr__(self):
        return "EventTimeTrigger()"


class ProcessingTimeTrigger(Trigger):
    """(ref: ProcessingTimeTrigger.java)"""

    def on_element(self, element, timestamp, window, ctx):
        ctx.register_processing_time_timer(window.max_timestamp())
        return TriggerResult.CONTINUE

    def on_processing_time(self, time, window, ctx):
        return TriggerResult.FIRE

    def can_merge(self):
        return True

    def on_merge(self, window, ctx):
        ctx.register_processing_time_timer(window.max_timestamp())

    def clear(self, window, ctx):
        ctx.delete_processing_time_timer(window.max_timestamp())

    def __repr__(self):
        return "ProcessingTimeTrigger()"


class CountTrigger(Trigger):
    """FIRE every `max_count` elements (ref: CountTrigger.java) —
    per-(key, window) count kept in partitioned trigger state."""

    def __init__(self, max_count: int):
        self.max_count = max_count
        from flink_tpu.core.state import ReducingStateDescriptor
        self._desc = ReducingStateDescriptor(
            "trigger-count", lambda a, b: a + b)

    def on_element(self, element, timestamp, window, ctx):
        count = ctx.get_partitioned_state(self._desc)
        count.add(1)
        if count.get() >= self.max_count:
            count.clear()
            return TriggerResult.FIRE
        return TriggerResult.CONTINUE

    def can_merge(self):
        return True

    def on_merge(self, window, ctx):
        # fold merged windows' counts into the result window's count
        # (ref: CountTrigger.onMerge → ctx.mergePartitionedState)
        if hasattr(ctx, "merge_partitioned_state"):
            ctx.merge_partitioned_state(self._desc)

    def clear(self, window, ctx):
        ctx.get_partitioned_state(self._desc).clear()

    def __repr__(self):
        return f"CountTrigger({self.max_count})"


class PurgingTrigger(Trigger):
    """Wraps a trigger, turning FIRE into FIRE_AND_PURGE
    (ref: PurgingTrigger.java)."""

    def __init__(self, inner: Trigger):
        self.inner = inner

    @staticmethod
    def of(inner: Trigger) -> "PurgingTrigger":
        return PurgingTrigger(inner)

    def _wrap(self, r: int) -> int:
        return TriggerResult.FIRE_AND_PURGE if TriggerResult.is_fire(r) else r

    def on_element(self, element, timestamp, window, ctx):
        return self._wrap(self.inner.on_element(element, timestamp, window, ctx))

    def on_event_time(self, time, window, ctx):
        return self._wrap(self.inner.on_event_time(time, window, ctx))

    def on_processing_time(self, time, window, ctx):
        return self._wrap(self.inner.on_processing_time(time, window, ctx))

    def can_merge(self):
        return self.inner.can_merge()

    def on_merge(self, window, ctx):
        self.inner.on_merge(window, ctx)

    def clear(self, window, ctx):
        self.inner.clear(window, ctx)

    def __repr__(self):
        return f"PurgingTrigger({self.inner!r})"


class ContinuousEventTimeTrigger(Trigger):
    """FIRE periodically in event time while the window is open
    (ref: ContinuousEventTimeTrigger.java)."""

    def __init__(self, interval):
        self.interval = _ms(interval)
        from flink_tpu.core.state import ReducingStateDescriptor
        self._desc = ReducingStateDescriptor("fire-time", min)

    def on_element(self, element, timestamp, window, ctx):
        if window.max_timestamp() <= ctx.get_current_watermark():
            return TriggerResult.FIRE
        ctx.register_event_time_timer(window.max_timestamp())
        fire = ctx.get_partitioned_state(self._desc)
        if fire.get() is None:
            start = timestamp - (timestamp % self.interval)
            nxt = start + self.interval
            ctx.register_event_time_timer(nxt)
            fire.add(nxt)
        return TriggerResult.CONTINUE

    def on_event_time(self, time, window, ctx):
        if time == window.max_timestamp():
            return TriggerResult.FIRE
        fire = ctx.get_partitioned_state(self._desc)
        t = fire.get()
        if t is not None and t == time:
            fire.clear()
            fire.add(time + self.interval)
            ctx.register_event_time_timer(time + self.interval)
            return TriggerResult.FIRE
        return TriggerResult.CONTINUE

    def can_merge(self):
        return True

    def on_merge(self, window, ctx):
        if window.max_timestamp() > ctx.get_current_watermark():
            ctx.register_event_time_timer(window.max_timestamp())

    def clear(self, window, ctx):
        fire = ctx.get_partitioned_state(self._desc)
        t = fire.get()
        if t is not None:
            ctx.delete_event_time_timer(t)
        fire.clear()


class ContinuousProcessingTimeTrigger(Trigger):
    """(ref: ContinuousProcessingTimeTrigger.java)"""

    def __init__(self, interval):
        self.interval = _ms(interval)
        from flink_tpu.core.state import ReducingStateDescriptor
        self._desc = ReducingStateDescriptor("fire-time-proc", min)

    def on_element(self, element, timestamp, window, ctx):
        now = ctx.get_current_processing_time()
        fire = ctx.get_partitioned_state(self._desc)
        if fire.get() is None:
            start = now - (now % self.interval)
            nxt = start + self.interval
            ctx.register_processing_time_timer(nxt)
            fire.add(nxt)
        return TriggerResult.CONTINUE

    def on_processing_time(self, time, window, ctx):
        fire = ctx.get_partitioned_state(self._desc)
        t = fire.get()
        if t is not None and t == time:
            fire.clear()
            fire.add(time + self.interval)
            ctx.register_processing_time_timer(time + self.interval)
            return TriggerResult.FIRE
        return TriggerResult.CONTINUE

    def can_merge(self):
        return True

    def on_merge(self, window, ctx):
        pass

    def clear(self, window, ctx):
        fire = ctx.get_partitioned_state(self._desc)
        t = fire.get()
        if t is not None:
            ctx.delete_processing_time_timer(t)
        fire.clear()


class DeltaTrigger(Trigger):
    """FIRE when delta(last_fired_element, current) > threshold
    (ref: DeltaTrigger.java)."""

    def __init__(self, threshold: float, delta_function: Callable[[Any, Any], float]):
        self.threshold = threshold
        self.delta_function = delta_function
        from flink_tpu.core.state import ValueStateDescriptor
        self._desc = ValueStateDescriptor("delta-last")

    def on_element(self, element, timestamp, window, ctx):
        last = ctx.get_partitioned_state(self._desc)
        if last.value() is None:
            last.update(element)
            return TriggerResult.CONTINUE
        if self.delta_function(last.value(), element) > self.threshold:
            last.update(element)
            return TriggerResult.FIRE
        return TriggerResult.CONTINUE

    def clear(self, window, ctx):
        ctx.get_partitioned_state(self._desc).clear()


# ---------------------------------------------------------------------
# Window assigners (ref: api/windowing/assigners/)
# ---------------------------------------------------------------------

class WindowAssigner(abc.ABC):
    @abc.abstractmethod
    def assign_windows(self, element, timestamp: int, ctx) -> Iterable[Window]:
        ...

    @abc.abstractmethod
    def get_default_trigger(self) -> Trigger:
        ...

    @abc.abstractmethod
    def is_event_time(self) -> bool:
        ...

    def is_merging(self) -> bool:
        return False

    def window_type(self):
        return TimeWindow


class TumblingEventTimeWindows(WindowAssigner):
    """(ref: TumblingEventTimeWindows.java)"""

    def __init__(self, size, offset=0):
        self.size = _ms(size)
        self.offset = _ms(offset)
        if not (0 <= self.offset < self.size):
            raise ValueError("offset must satisfy 0 <= offset < size")

    @staticmethod
    def of(size, offset=0) -> "TumblingEventTimeWindows":
        return TumblingEventTimeWindows(size, offset)

    def assign_windows(self, element, timestamp, ctx):
        if timestamp is None:
            raise ValueError(
                "record has no timestamp — event-time windowing requires "
                "timestamp assignment (assign_timestamps_and_watermarks)")
        start = TimeWindow.get_window_start_with_offset(timestamp, self.offset, self.size)
        return [TimeWindow(start, start + self.size)]

    def get_default_trigger(self):
        return EventTimeTrigger()

    def is_event_time(self):
        return True

    def __repr__(self):
        return f"TumblingEventTimeWindows({self.size})"


class TumblingProcessingTimeWindows(WindowAssigner):
    """(ref: TumblingProcessingTimeWindows.java)"""

    def __init__(self, size, offset=0):
        self.size = _ms(size)
        self.offset = _ms(offset)

    @staticmethod
    def of(size, offset=0) -> "TumblingProcessingTimeWindows":
        return TumblingProcessingTimeWindows(size, offset)

    def assign_windows(self, element, timestamp, ctx):
        now = ctx.get_current_processing_time()
        start = TimeWindow.get_window_start_with_offset(now, self.offset, self.size)
        return [TimeWindow(start, start + self.size)]

    def get_default_trigger(self):
        return ProcessingTimeTrigger()

    def is_event_time(self):
        return False


class SlidingEventTimeWindows(WindowAssigner):
    """(ref: SlidingEventTimeWindows.java)"""

    def __init__(self, size, slide, offset=0):
        self.size = _ms(size)
        self.slide = _ms(slide)
        self.offset = _ms(offset)

    @staticmethod
    def of(size, slide, offset=0) -> "SlidingEventTimeWindows":
        return SlidingEventTimeWindows(size, slide, offset)

    def assign_windows(self, element, timestamp, ctx):
        if timestamp is None:
            raise ValueError("record has no timestamp for event-time windowing")
        windows = []
        last_start = TimeWindow.get_window_start_with_offset(
            timestamp, self.offset, self.slide)
        start = last_start
        while start > timestamp - self.size:
            windows.append(TimeWindow(start, start + self.size))
            start -= self.slide
        return windows

    def get_default_trigger(self):
        return EventTimeTrigger()

    def is_event_time(self):
        return True

    def __repr__(self):
        return f"SlidingEventTimeWindows({self.size}/{self.slide})"


class SlidingProcessingTimeWindows(WindowAssigner):
    """(ref: SlidingProcessingTimeWindows.java)"""

    def __init__(self, size, slide, offset=0):
        self.size = _ms(size)
        self.slide = _ms(slide)
        self.offset = _ms(offset)

    @staticmethod
    def of(size, slide, offset=0) -> "SlidingProcessingTimeWindows":
        return SlidingProcessingTimeWindows(size, slide, offset)

    def assign_windows(self, element, timestamp, ctx):
        now = ctx.get_current_processing_time()
        windows = []
        last_start = TimeWindow.get_window_start_with_offset(now, self.offset, self.slide)
        start = last_start
        while start > now - self.size:
            windows.append(TimeWindow(start, start + self.size))
            start -= self.slide
        return windows

    def get_default_trigger(self):
        return ProcessingTimeTrigger()

    def is_event_time(self):
        return False


class _SessionWindowsBase(WindowAssigner):
    def is_merging(self):
        return True


class EventTimeSessionWindows(_SessionWindowsBase):
    """(ref: EventTimeSessionWindows.java)"""

    def __init__(self, gap):
        self.gap = _ms(gap)

    @staticmethod
    def with_gap(gap) -> "EventTimeSessionWindows":
        return EventTimeSessionWindows(gap)

    def assign_windows(self, element, timestamp, ctx):
        if timestamp is None:
            raise ValueError("record has no timestamp for event-time windowing")
        return [TimeWindow(timestamp, timestamp + self.gap)]

    def get_default_trigger(self):
        return EventTimeTrigger()

    def is_event_time(self):
        return True


class ProcessingTimeSessionWindows(_SessionWindowsBase):
    """(ref: ProcessingTimeSessionWindows.java)"""

    def __init__(self, gap):
        self.gap = _ms(gap)

    @staticmethod
    def with_gap(gap) -> "ProcessingTimeSessionWindows":
        return ProcessingTimeSessionWindows(gap)

    def assign_windows(self, element, timestamp, ctx):
        now = ctx.get_current_processing_time()
        return [TimeWindow(now, now + self.gap)]

    def get_default_trigger(self):
        return ProcessingTimeTrigger()

    def is_event_time(self):
        return False


class DynamicEventTimeSessionWindows(_SessionWindowsBase):
    """Per-element gap (ref: DynamicEventTimeSessionWindows.java +
    SessionWindowTimeGapExtractor)."""

    def __init__(self, gap_extractor: Callable[[Any], int]):
        self.gap_extractor = gap_extractor

    @staticmethod
    def with_dynamic_gap(extractor) -> "DynamicEventTimeSessionWindows":
        return DynamicEventTimeSessionWindows(extractor)

    def assign_windows(self, element, timestamp, ctx):
        gap = self.gap_extractor(element)
        if gap <= 0:
            raise ValueError("session gap must be positive")
        return [TimeWindow(timestamp, timestamp + gap)]

    def get_default_trigger(self):
        return EventTimeTrigger()

    def is_event_time(self):
        return True


class DynamicProcessingTimeSessionWindows(_SessionWindowsBase):
    """(ref: DynamicProcessingTimeSessionWindows.java)"""

    def __init__(self, gap_extractor: Callable[[Any], int]):
        self.gap_extractor = gap_extractor

    @staticmethod
    def with_dynamic_gap(extractor) -> "DynamicProcessingTimeSessionWindows":
        return DynamicProcessingTimeSessionWindows(extractor)

    def assign_windows(self, element, timestamp, ctx):
        now = ctx.get_current_processing_time()
        gap = self.gap_extractor(element)
        if gap <= 0:
            raise ValueError("session gap must be positive")
        return [TimeWindow(now, now + gap)]

    def get_default_trigger(self):
        return ProcessingTimeTrigger()

    def is_event_time(self):
        return False


class GlobalWindows(WindowAssigner):
    """Everything into one window; fires only with an explicit trigger
    (ref: GlobalWindows.java — default NeverTrigger)."""

    class NeverTrigger(Trigger):
        def can_merge(self):
            return True

        def on_merge(self, window, ctx):
            pass

    @staticmethod
    def create() -> "GlobalWindows":
        return GlobalWindows()

    def assign_windows(self, element, timestamp, ctx):
        return [GlobalWindow()]

    def get_default_trigger(self):
        return GlobalWindows.NeverTrigger()

    def is_event_time(self):
        return False

    def window_type(self):
        return GlobalWindow


# ---------------------------------------------------------------------
# Evictors (ref: api/windowing/evictors/)
# ---------------------------------------------------------------------

class Evictor(abc.ABC):
    """Operates on the raw element buffer of an EvictingWindowOperator.
    Elements are (timestamp, value) pairs."""

    @abc.abstractmethod
    def evict_before(self, elements: List[Tuple[int, Any]], size: int,
                     window, current_time: int) -> List[Tuple[int, Any]]:
        ...

    def evict_after(self, elements: List[Tuple[int, Any]], size: int,
                    window, current_time: int) -> List[Tuple[int, Any]]:
        return elements


class CountEvictor(Evictor):
    """Keep only the last `max_count` elements (ref: CountEvictor.java)."""

    def __init__(self, max_count: int):
        self.max_count = max_count

    @staticmethod
    def of(max_count: int) -> "CountEvictor":
        return CountEvictor(max_count)

    def evict_before(self, elements, size, window, current_time):
        if size <= self.max_count:
            return elements
        return elements[size - self.max_count:]


class TimeEvictor(Evictor):
    """Keep only elements within `window_size` of the max timestamp
    (ref: TimeEvictor.java)."""

    def __init__(self, window_size):
        self.window_size = _ms(window_size)

    @staticmethod
    def of(window_size) -> "TimeEvictor":
        return TimeEvictor(window_size)

    def evict_before(self, elements, size, window, current_time):
        if not elements:
            return elements
        has_ts = any(ts is not None for ts, _ in elements)
        if not has_ts:
            return elements
        max_ts = max(ts for ts, _ in elements if ts is not None)
        cutoff = max_ts - self.window_size
        return [(ts, v) for ts, v in elements if ts is None or ts > cutoff]


class DeltaEvictor(Evictor):
    """Evict elements whose delta to the newest exceeds threshold
    (ref: DeltaEvictor.java)."""

    def __init__(self, threshold: float, delta_function: Callable[[Any, Any], float]):
        self.threshold = threshold
        self.delta_function = delta_function

    @staticmethod
    def of(threshold, delta_function) -> "DeltaEvictor":
        return DeltaEvictor(threshold, delta_function)

    def evict_before(self, elements, size, window, current_time):
        if not elements:
            return elements
        newest = elements[-1][1]
        return [(ts, v) for ts, v in elements
                if self.delta_function(v, newest) < self.threshold]
