"""WindowOperator: windowed keyed aggregation with triggers, allowed
lateness, and merging session windows.

Re-designs flink-streaming-java/.../runtime/operators/windowing/
WindowOperator.java:97 — processElement :291-421, onEventTime :424,
onProcessingTime :472, emitWindowContents :544, cleanup timers
:596-626, lateness :576-589 — and MergingWindowSet.java:54,119,156.
Window state is keyed state under namespace = window
(WindowOperator.java:387), so ALL backends (heap and TPU) serve it
unchanged; on the TPU backend a window-fire is a device gather and
`add` is a micro-batched scatter.

EvictingWindowOperator keeps the raw elements in a ListState and runs
the Evictor before/after the window function
(ref: EvictingWindowOperator.java).
"""

from __future__ import annotations

import abc
import contextlib
from typing import Any, Iterable, List, Optional, Tuple

import numpy as np

from flink_tpu.core.state import (
    AggregatingStateDescriptor,
    ListStateDescriptor,
    ReducingStateDescriptor,
    StateDescriptor,
    ValueStateDescriptor,
)
from flink_tpu.runtime.device_stats import TELEMETRY
from flink_tpu.runtime.tracing import get_tracer
from flink_tpu.state.introspect import INTROSPECTION
from flink_tpu.streaming.elements import MAX_TIMESTAMP, StreamRecord
from flink_tpu.streaming.operators import (
    AbstractUdfStreamOperator,
    Output,
    OutputTag,
    TimestampedCollector,
)
from flink_tpu.streaming.windowing import (
    SlidingEventTimeWindows,
    Trigger,
    TriggerContext,
    TriggerResult,
    TumblingEventTimeWindows,
    WindowAssigner,
)


# ---------------------------------------------------------------------
# Window functions (ref: runtime/operators/windowing/functions/)
# ---------------------------------------------------------------------

class ProcessWindowFunction(abc.ABC):
    """(ref: ProcessWindowFunction.java) — full access to window
    metadata; elements is the window contents iterable."""

    @abc.abstractmethod
    def process(self, key, context: "WindowContext", elements: Iterable, out) -> None:
        ...

    def clear(self, context: "WindowContext") -> None:  # noqa: B027
        pass


class WindowFunction(abc.ABC):
    """(ref: WindowFunction.java) — apply(key, window, inputs, out)."""

    @abc.abstractmethod
    def apply(self, key, window, inputs: Iterable, out) -> None:
        ...


class PassThroughWindowFunction(WindowFunction):
    """Emit the (single) pre-aggregated value
    (ref: PassThroughWindowFunction.java)."""

    def apply(self, key, window, inputs, out):
        out.collect(inputs)


class WindowContext:
    """(ref: ProcessWindowFunction.Context)"""

    def __init__(self, window, op: "WindowOperator"):
        self.window = window
        self._op = op

    def current_processing_time(self) -> int:
        return self._op.processing_time_service.get_current_processing_time()

    def current_watermark(self) -> int:
        return self._op.timer_service.current_watermark

    def window_state(self, descriptor: StateDescriptor):
        """Per-(key, window) state."""
        return self._op.keyed_backend.get_partitioned_state(
            self._op._namespace_of(self.window), descriptor)

    def global_state(self, descriptor: StateDescriptor):
        """Per-key state shared across windows."""
        from flink_tpu.state.backend import VOID_NAMESPACE
        return self._op.keyed_backend.get_partitioned_state(VOID_NAMESPACE, descriptor)

    def output(self, tag: OutputTag, value) -> None:
        self._op.output.collect_side(
            tag, StreamRecord(value, self.window.max_timestamp()))


class _InternalWindowFunction:
    """Normalizes the three user-function shapes to one call."""

    def __init__(self, fn, single_value: bool):
        self.fn = fn
        #: True when window contents are a single pre-aggregated value
        self.single_value = single_value

    def process(self, key, window, op, contents, collector) -> None:
        if self.fn is None:
            collector.collect(contents)
        elif isinstance(self.fn, ProcessWindowFunction):
            elements = [contents] if self.single_value else contents
            self.fn.process(key, WindowContext(window, op), elements, collector)
        elif isinstance(self.fn, WindowFunction):
            elements = [contents] if self.single_value else contents
            self.fn.apply(key, window, elements, collector)
        else:  # plain callable(key, window, elements) -> iterable
            elements = [contents] if self.single_value else contents
            result = self.fn(key, window, elements)
            if result is not None:
                for v in result:
                    collector.collect(v)

    def clear(self, key, window, op) -> None:
        if isinstance(self.fn, ProcessWindowFunction):
            self.fn.clear(WindowContext(window, op))


# ---------------------------------------------------------------------
# MergingWindowSet (ref: MergingWindowSet.java)
# ---------------------------------------------------------------------

class MergingWindowSet:
    """Per-key mapping window → state window for merging (session)
    assigners.  When windows merge, one pre-existing state window is
    kept as the merge target and the others' state is folded into it —
    so state never has to be re-namespaced (ref: MergingWindowSet.java:54)."""

    def __init__(self, mapping_state):
        #: ValueState holding {window_namespace: state_window_namespace}
        self._mapping_state = mapping_state
        m = mapping_state.value()
        self.mapping: dict = dict(m) if m else {}

    def persist(self) -> None:
        if self.mapping:
            self._mapping_state.update(dict(self.mapping))
        else:
            self._mapping_state.clear()

    def get_state_window(self, window):
        return self.mapping.get(window)

    def retire_window(self, window) -> None:
        if window in self.mapping:
            del self.mapping[window]

    def add_window(self, new_window, merge_callback):
        """Add `new_window`, eagerly merging all transitively
        intersecting windows.  merge_callback(merge_result,
        merged_windows, state_window_result, merged_state_windows) is
        invoked when a merge happens (ref: addWindow :119)."""
        windows = list(self.mapping.keys()) + [new_window]
        merge_result = new_window
        to_merge = []
        changed = True
        while changed:
            changed = False
            for w in windows:
                if w is merge_result or w in to_merge:
                    continue
                if w.intersects(merge_result):
                    merge_result = merge_result.cover(w)
                    to_merge.append(w)
                    changed = True
        # to_merge = pre-existing windows (and possibly none) swallowed
        to_merge_existing = [w for w in to_merge if w in self.mapping]
        if not to_merge_existing and new_window not in self.mapping:
            # brand-new non-overlapping window: its own state window
            self.mapping[new_window] = new_window
            return new_window
        if not to_merge_existing:
            return new_window  # exact duplicate of an existing window
        # keep the first existing window's state window as target
        state_window_result = self.mapping[to_merge_existing[0]]
        merged_state_windows = []
        for w in to_merge_existing:
            sw = self.mapping.pop(w)
            if sw != state_window_result:
                merged_state_windows.append(sw)
        self.mapping[merge_result] = state_window_result
        merged_windows = to_merge_existing + (
            [new_window] if new_window not in to_merge_existing else [])
        # don't fire the callback for a no-op (new window already covered
        # by one existing window and nothing else merged)
        if len(to_merge_existing) > 1 or (
                merge_result != to_merge_existing[0]) or merged_state_windows:
            if merge_result not in to_merge_existing or merged_state_windows:
                merge_callback(merge_result, merged_windows,
                               state_window_result, merged_state_windows)
        return merge_result


# ---------------------------------------------------------------------
# WindowOperator
# ---------------------------------------------------------------------

class _WindowTriggerContext(TriggerContext):
    """(ref: WindowOperator.Context :649)"""

    def __init__(self, op: "WindowOperator"):
        self._op = op
        self.window = None

    def register_event_time_timer(self, time):
        self._op.timer_service.register_event_time_timer(
            self._op._namespace_of(self.window), time)

    def register_processing_time_timer(self, time):
        self._op.timer_service.register_processing_time_timer(
            self._op._namespace_of(self.window), time)

    def delete_event_time_timer(self, time):
        self._op.timer_service.delete_event_time_timer(
            self._op._namespace_of(self.window), time)

    def delete_processing_time_timer(self, time):
        self._op.timer_service.delete_processing_time_timer(
            self._op._namespace_of(self.window), time)

    def get_current_watermark(self):
        return self._op.timer_service.current_watermark

    def get_current_processing_time(self):
        return self._op.processing_time_service.get_current_processing_time()

    def get_partitioned_state(self, descriptor):
        """Trigger state, scoped (key, window)."""
        return self._op.keyed_backend.get_partitioned_state(
            self._op._namespace_of(self.window), descriptor)

    #: set before trigger.on_merge fires (ref: OnMergeContext)
    merged_windows = ()

    def merge_partitioned_state(self, descriptor):
        """Merge per-window trigger state of the merged windows into
        the merge result's namespace (ref:
        Trigger.OnMergeContext#mergePartitionedState)."""
        state = self._op.keyed_backend.get_or_create_keyed_state(descriptor)
        if hasattr(state, "merge_namespaces"):
            state.merge_namespaces(
                self._op._namespace_of(self.window),
                [self._op._namespace_of(w) for w in self.merged_windows])


class _AssignerContext:
    """(ref: WindowAssigner.WindowAssignerContext)"""

    def __init__(self, op: "WindowOperator"):
        self._op = op

    def get_current_processing_time(self):
        return self._op.processing_time_service.get_current_processing_time()


class _FireBufferOutput(Output):
    """Captures the main-stream records emitted during ONE batched
    fire sweep so they can be re-emitted as a single RecordBatch.
    Watermarks, side outputs, and latency markers pass straight
    through to the real output (a side tag has no ordering contract
    against the main stream)."""

    __slots__ = ("_inner", "records")

    def __init__(self, inner: Output):
        self._inner = inner
        self.records: List[StreamRecord] = []

    def collect(self, record: StreamRecord) -> None:
        self.records.append(record)

    def emit_watermark(self, watermark) -> None:
        self._inner.emit_watermark(watermark)

    def collect_side(self, tag: OutputTag, record: StreamRecord) -> None:
        self._inner.collect_side(tag, record)

    def emit_latency_marker(self, marker) -> None:
        self._inner.emit_latency_marker(marker)


class WindowOperator(AbstractUdfStreamOperator):
    """One-input keyed window operator."""

    MAPPING_STATE_NAME = "window-merge-mapping"

    def __init__(
        self,
        assigner: WindowAssigner,
        state_descriptor: StateDescriptor,
        window_function=None,
        trigger: Optional[Trigger] = None,
        allowed_lateness: int = 0,
        late_data_tag: Optional[OutputTag] = None,
        single_value_contents: Optional[bool] = None,
    ):
        super().__init__(window_function)
        self.assigner = assigner
        self.state_descriptor = state_descriptor
        self.trigger = trigger or assigner.get_default_trigger()
        if allowed_lateness < 0:
            raise ValueError("allowed lateness must be >= 0")
        if assigner.is_merging() and not self.trigger.can_merge():
            raise ValueError(
                f"trigger {self.trigger!r} cannot merge but assigner "
                f"{assigner!r} is a merging assigner")
        self.allowed_lateness = allowed_lateness
        self.late_data_tag = late_data_tag
        if single_value_contents is None:
            single_value_contents = isinstance(
                state_descriptor,
                (ReducingStateDescriptor, AggregatingStateDescriptor))
        self._internal_fn = _InternalWindowFunction(
            window_function, single_value_contents)
        # metrics (ref: numLateRecordsDropped, WindowOperator.java:138)
        self.num_late_records_dropped = 0

    # ---- lifecycle --------------------------------------------------
    def open(self):
        super().open()
        # structural demotions, known AOT: merging assigners, custom
        # triggers and evictors are inherently per-row; plain
        # tumbling/sliding event-time windows with their default
        # trigger take the vectorized process_batch path (the
        # columnar.ratio gauge and linter FT184 surface the reason)
        self._batch_demote_reason = self._batch_eligibility()
        self.columnar_fallback_reason = self._batch_demote_reason
        self._emit_batch_hist = None
        if self.metrics is not None:
            # eager so monitoring sees the zero (ref: the counter is
            # constructed in WindowOperator.open, not on first drop);
            # reset = fresh execution attempt (restart replays must not
            # accumulate into the previous attempt's count)
            self.metrics.counter("numLateRecordsDropped").count = 0
            self._emit_batch_hist = self.metrics.histogram("emitBatchSize")
        self.window_state = self.keyed_backend.get_or_create_keyed_state(
            self.state_descriptor)
        self.trigger_ctx = _WindowTriggerContext(self)
        self.assigner_ctx = _AssignerContext(self)
        self.collector = TimestampedCollector(self.output)
        if self.assigner.is_merging():
            self._mapping_desc = ValueStateDescriptor(self.MAPPING_STATE_NAME)

    # namespace encoding: window -> hashable tuple (state namespaces)
    def _namespace_of(self, window):
        return window.to_namespace()

    def _state_value(self, record: StreamRecord):
        """What goes into window state for one record; the evicting
        variant stores (timestamp, value) pairs."""
        return record.value

    # ---- element path (ref: processElement :291-421) ----------------
    def process_element(self, record: StreamRecord):
        windows = self.assigner.assign_windows(
            record.value, record.timestamp, self.assigner_ctx)
        skipped = True
        if self.assigner.is_merging():
            skipped = self._process_merging(record, windows, skipped)
        else:
            for window in windows:
                if self._is_window_late(window):
                    continue
                skipped = False
                ns = self._namespace_of(window)
                self.window_state.set_current_namespace(ns)
                self.window_state.add(self._state_value(record))
                if INTROSPECTION.enabled:
                    INTROSPECTION.note_row(
                        self.state_descriptor.name,
                        self.keyed_backend.current_key,
                        self.keyed_backend.max_parallelism)
                self.trigger_ctx.window = window
                result = self.trigger.on_element(
                    record.value, record.timestamp, window, self.trigger_ctx)
                self._react(result, window)
                self._register_cleanup_timer(window)
        if skipped and self._is_element_late(record):
            if self.late_data_tag is not None:
                self.output.collect_side(self.late_data_tag, record)
            else:
                self.num_late_records_dropped += 1
                if self.metrics is not None:
                    self.metrics.counter("numLateRecordsDropped").inc()

    # ---- batch path -------------------------------------------------
    def _batch_eligibility(self) -> Optional[str]:
        """Structural reason this operator must take the per-row path,
        or None when process_batch can vectorize.  Called at open();
        uses only constructor state."""
        if self.assigner.is_merging():
            return "merging window assigner is per-row"
        if not isinstance(self.assigner,
                          (TumblingEventTimeWindows, SlidingEventTimeWindows)):
            return (f"no vectorized assignment for "
                    f"{type(self.assigner).__name__}")
        if type(self.trigger) is not type(self.assigner.get_default_trigger()):
            return (f"custom trigger {type(self.trigger).__name__} "
                    f"is per-row")
        return None

    def _batch_keys(self, batch, values) -> list:
        """Key column for a batch as a python list — bit-identical to
        what set_key_context would have extracted per row (same idiom
        as the generic engine's _batch_keys)."""
        from flink_tpu.core.functions import _FieldKeySelector
        sel = self.key_selector
        if isinstance(sel, _FieldKeySelector) \
                and type(sel._field) is int and not batch.is_scalar:
            col = batch.cols.get(f"f{sel._field}")
            if col is not None:
                return np.asarray(col).tolist()
        return [sel.get_key(v) for v in values]

    def process_batch(self, batch) -> None:
        """Columnar ingest: assign tumbling/sliding panes for the whole
        batch in numpy, group rows by (pane start), and feed each
        sub-batch into the backend's add_batch — one vectorized state
        write per (window, batch) instead of one per row.

        Exactness: the watermark is FIXED for the whole batch, so a
        window either fires immediately for ALL its in-batch rows
        (max_timestamp <= watermark, the allowed-lateness grace path)
        or for NONE of them.  Fire-now rows are replayed through the
        scalar per-element path in row order — their incremental
        emissions are part of the operator's contract — while all
        CONTINUE panes (the overwhelming majority) go through the
        column path, which only accumulates state and registers
        dedup'd timers and therefore commutes with the replay."""
        n = len(batch)
        if n == 0:
            return
        reason = self._batch_demote_reason
        if reason is None and (
                batch.ts is None
                or (batch.ts_mask is not None and not batch.ts_mask.all())):
            reason = "rows without event timestamps"
        if reason is None and self.key_selector is None:
            reason = "no key selector bound"
        if reason is not None:
            self._note_boxed(n, reason)
            for record in batch.to_records():
                self.set_key_context(record)
                self.process_element(record)
            return
        self._process_batch_vectorized(batch, n)
        self._note_columnar(n)

    def process_batch_fused(self, batch, last_start=None) -> None:
        """Ingest a batch whose first-pane starts were already computed
        on device inside a fused chain program (chain_fusion) —
        identical to :meth:`process_batch` except the pane arithmetic
        is skipped.  Every boxing guard stays armed: when one trips,
        the precomputed column is simply dropped and the ordinary path
        (vectorized or per-row) runs."""
        n = len(batch)
        if n == 0:
            return
        if (last_start is None
                or self._batch_demote_reason is not None
                or batch.ts is None
                or (batch.ts_mask is not None and not batch.ts_mask.all())
                or self.key_selector is None):
            self.process_batch(batch)
            return
        self._process_batch_vectorized(batch, n, last_start=last_start)
        self._note_fused(n)

    def _process_batch_vectorized(self, batch, n: int,
                                  last_start=None) -> None:
        ts = np.asarray(batch.ts, np.int64)
        values = batch.row_values()
        keys = self._batch_keys(batch, values)
        wm = self.timer_service.current_watermark
        assigner = self.assigner
        size = assigner.size
        slide = getattr(assigner, "slide", size)
        offset = assigner.offset
        lateness = self.allowed_lateness
        state = self.window_state
        backend = self.keyed_backend
        # value column for device states: the aggregate's extract is
        # identity, so the raw column feeds the scatter directly
        vcol = None
        agg = getattr(state, "agg", None)
        if agg is not None and hasattr(agg, "extract_column"):
            c = agg.extract_column(batch.value_arrays())
            if isinstance(c, np.ndarray) and c.ndim == 1 and len(c) == n:
                vcol = c
        if last_start is None:
            last_start = ts - ((ts - offset) % slide)
        else:
            last_start = np.asarray(last_start, np.int64)
        npanes = -(-size // slide)  # ceil; 1 for tumbling
        assigned = np.zeros(n, bool)
        immediate = np.zeros(n, bool)
        idx_parts = []
        start_parts = []
        for p in range(npanes):
            starts = last_start - p * slide
            maxts = starts + (size - 1)
            live = starts > (ts - size)
            window_late = (maxts + lateness) <= wm
            ok = live & ~window_late
            if not ok.any():
                continue
            assigned |= ok
            fire_now = ok & (maxts <= wm)
            immediate |= fire_now
            vi = np.nonzero(ok & ~fire_now)[0]
            if vi.size:
                idx_parts.append(vi)
                start_parts.append(starts[vi])
        if idx_parts:
            all_idx = np.concatenate(idx_parts)
            all_starts = np.concatenate(start_parts)
            # group by window; WITHIN a window restore row order —
            # different rows reach the same sliding window at different
            # pane indexes, and both the state fold order and
            # same-timestamp timer order must match the scalar path's
            # row-major traversal
            order = np.lexsort((all_idx, all_starts))
            sidx = all_idx[order]
            sstarts = all_starts[order]
            bounds = np.nonzero(np.diff(sstarts))[0] + 1
            lo = 0
            for hi in [*bounds.tolist(), len(sidx)]:
                gidx = sidx[lo:hi]
                start = int(sstarts[lo])
                lo = hi
                ns = (start, start + size)
                gkeys = [keys[i] for i in gidx]
                if vcol is not None:
                    backend.add_batch(state, gkeys, ns, vcol[gidx],
                                      pre_extracted=True)
                else:
                    backend.add_batch(state, gkeys, ns,
                                      [values[i] for i in gidx])
                # first-occurrence order, NOT a set: same-timestamp
                # timers fire in registration order, and the scalar
                # path registers them in row order
                dkeys = dict.fromkeys(gkeys)
                maxt = start + size - 1
                # trigger timer (what EventTimeTrigger.on_element
                # registers on CONTINUE) + GC timer; the dedup set
                # makes re-registration free
                self.timer_service.register_event_time_timers_bulk(
                    ns, maxt, dkeys)
                cleanup = maxt + lateness
                if cleanup < MAX_TIMESTAMP:
                    self.timer_service.register_event_time_timers_bulk(
                        ns, cleanup, dkeys)
        if immediate.any():
            tlist = ts.tolist()
            for i in np.nonzero(immediate)[0]:
                backend.set_current_key(keys[i])
                self._replay_immediate(values[i], tlist[i], wm)
        dropped = ~assigned & ~immediate & ((ts + lateness) <= wm)
        if dropped.any():
            if self.late_data_tag is not None:
                tlist = ts.tolist()
                for i in np.nonzero(dropped)[0]:
                    self.output.collect_side(
                        self.late_data_tag,
                        StreamRecord(values[i], tlist[i]))
            else:
                cnt = int(dropped.sum())
                self.num_late_records_dropped += cnt
                if self.metrics is not None:
                    self.metrics.counter("numLateRecordsDropped").inc(cnt)

    def _replay_immediate(self, value, timestamp: int, wm: int) -> None:
        """Scalar replay for a row with >= 1 window already past the
        watermark: only those windows run here (add + trigger + emit,
        exactly process_element's per-window body); CONTINUE windows
        were vector-ingested."""
        record = StreamRecord(value, timestamp)
        for window in self.assigner.assign_windows(
                value, timestamp, self.assigner_ctx):
            if window.max_timestamp() > wm:
                continue  # handled by the column path
            if self._is_window_late(window):
                continue
            ns = self._namespace_of(window)
            self.window_state.set_current_namespace(ns)
            self.window_state.add(self._state_value(record))
            if INTROSPECTION.enabled:
                INTROSPECTION.note_row(
                    self.state_descriptor.name,
                    self.keyed_backend.current_key,
                    self.keyed_backend.max_parallelism)
            self.trigger_ctx.window = window
            result = self.trigger.on_element(
                value, timestamp, window, self.trigger_ctx)
            self._react(result, window)
            self._register_cleanup_timer(window)

    def _process_merging(self, record, windows, skipped):
        from flink_tpu.state.backend import VOID_NAMESPACE
        mapping_state = self.keyed_backend.get_partitioned_state(
            VOID_NAMESPACE, self._mapping_desc)
        merging = MergingWindowSet(mapping_state)

        def on_merge(merge_result, merged_windows, state_window, merged_state_windows):
            # fold merged state windows into the surviving one
            if merged_state_windows and hasattr(self.window_state, "merge_namespaces"):
                self.window_state.merge_namespaces(
                    self._namespace_of(state_window),
                    [self._namespace_of(w) for w in merged_state_windows])
            # trigger merges its per-window state FIRST (ref: the order
            # in WindowOperator's merge callback: onMerge, then clear
            # each merged window), then old windows' trigger state,
            # timers, and cleanup timers are dropped
            self.trigger_ctx.window = merge_result
            self.trigger_ctx.merged_windows = [
                w for w in merged_windows if w != merge_result]
            self.trigger.on_merge(merge_result, self.trigger_ctx)
            self.trigger_ctx.merged_windows = ()
            for w in merged_windows:
                if w == merge_result:
                    continue
                self.trigger_ctx.window = w
                self.trigger.clear(w, self.trigger_ctx)
                self._delete_cleanup_timer(w)

        for window in windows:
            actual = merging.add_window(window, on_merge)
            if self._is_window_late(actual):
                merging.retire_window(actual)
                continue
            skipped = False
            state_window = merging.get_state_window(actual)
            self.window_state.set_current_namespace(
                self._namespace_of(state_window))
            self.window_state.add(self._state_value(record))
            self.trigger_ctx.window = actual
            result = self.trigger.on_element(
                record.value, record.timestamp, actual, self.trigger_ctx)
            if TriggerResult.is_fire(result):
                contents = self._contents_for(actual, merging)
                if contents is not None:
                    self._emit(actual, contents)
            if TriggerResult.is_purge(result):
                self.window_state.clear()
            self._register_cleanup_timer(actual)
        merging.persist()
        return skipped

    # ---- timers (ref: onEventTime :424 / onProcessingTime :472) -----
    def on_event_time(self, timer):
        window = self._window_from_namespace(timer.namespace)
        self.trigger_ctx.window = window
        merging = None
        if self.assigner.is_merging():
            from flink_tpu.state.backend import VOID_NAMESPACE
            mapping_state = self.keyed_backend.get_partitioned_state(
                VOID_NAMESPACE, self._mapping_desc)
            merging = MergingWindowSet(mapping_state)
            state_window = merging.get_state_window(window)
            if state_window is None:
                return  # window was merged away; timer is stale
            self.window_state.set_current_namespace(
                self._namespace_of(state_window))
        else:
            self.window_state.set_current_namespace(self._namespace_of(window))

        result = self.trigger.on_event_time(timer.timestamp, window, self.trigger_ctx)
        if TriggerResult.is_fire(result):
            contents = self.window_state.get()
            if contents is not None:
                self._emit(window, contents)
        if TriggerResult.is_purge(result):
            self.window_state.clear()
        if self.assigner.is_event_time() and self._is_cleanup_time(window, timer.timestamp):
            self._clear_all_state(window, merging)
        if merging is not None:
            merging.persist()

    def on_processing_time(self, timer):
        window = self._window_from_namespace(timer.namespace)
        self.trigger_ctx.window = window
        merging = None
        if self.assigner.is_merging():
            from flink_tpu.state.backend import VOID_NAMESPACE
            mapping_state = self.keyed_backend.get_partitioned_state(
                VOID_NAMESPACE, self._mapping_desc)
            merging = MergingWindowSet(mapping_state)
            state_window = merging.get_state_window(window)
            if state_window is None:
                return
            self.window_state.set_current_namespace(
                self._namespace_of(state_window))
        else:
            self.window_state.set_current_namespace(self._namespace_of(window))

        result = self.trigger.on_processing_time(
            timer.timestamp, window, self.trigger_ctx)
        if TriggerResult.is_fire(result):
            contents = self.window_state.get()
            if contents is not None:
                self._emit(window, contents)
        if TriggerResult.is_purge(result):
            self.window_state.clear()
        if (not self.assigner.is_event_time()
                and self._is_cleanup_time(window, timer.timestamp)):
            self._clear_all_state(window, merging)
        if merging is not None:
            merging.persist()

    # ---- batched watermark fires ------------------------------------
    #: kill switch / A-B toggle: False pins the per-timer scalar fire
    #: path even for batch-eligible operators (the differential suite
    #: and the bench A/B flip this)
    batch_fires = True

    def process_watermark(self, watermark) -> None:
        """Watermark: the batch-eligible shape (tumbling/sliding
        event-time windows with their default trigger — the same
        structural test process_batch uses) takes the columnar fire
        sweep; everything else (merging assigners, custom triggers,
        evictors, processing-time assigners) keeps the per-timer drain
        in advance_watermark."""
        if (self.timer_service is None or not self.batch_fires
                or getattr(self, "_batch_demote_reason", "unopened")
                is not None):
            super().process_watermark(watermark)
            return
        self.current_watermark = watermark.timestamp
        self.on_watermark_batch(watermark.timestamp)
        self.output.emit_watermark(watermark)

    def on_watermark_batch(self, watermark: int) -> None:
        """Columnar fire: ONE timer sweep → vectorized
        EventTimeTrigger decision → ONE backend gather for every
        firing (key, window) → in-pop-order emit (one RecordBatch when
        the results columnarize) → ONE batch state clear + bulk
        cleanup-timer delete.

        Exactness vs the per-timer loop: the default EventTimeTrigger
        neither writes state nor registers timers from on_event_time,
        distinct (key, window) slots are independent, and within one
        slot the fire timer (max_timestamp) pops before the cleanup
        timer (max_timestamp + lateness) — with lateness 0 the two
        dedup into ONE timer that fires then cleans — so gathering
        every firing slot BEFORE the batch clear reads exactly what
        the interleaved scalar drain read, in the same order.  The
        differential suite (tests/test_fire_batch.py) pins the two
        paths bit-equal."""
        svc = self.timer_service
        ts_col, key_col, ns_col = svc.pop_due_event_time_timers(watermark)
        n = len(ts_col)
        if n == 0:
            return
        lateness = self.allowed_lateness
        tarr = np.fromiter(ts_col, np.int64, n)
        maxts = np.fromiter((ns[1] for ns in ns_col), np.int64, n) - 1
        # EventTimeTrigger.on_event_time: FIRE iff time == maxTimestamp
        fire = tarr == maxts
        if lateness == 0:
            cleanup = fire  # fire and cleanup are the SAME dedup'd timer
        else:
            # a cleanup timer at/after MAX_TIMESTAMP is never
            # registered, so int64 wraparound on an astronomical
            # lateness yields False — exactly "no cleanup timer"
            with np.errstate(over="ignore"):
                cleanup = tarr == maxts + lateness
        backend = self.keyed_backend
        emitted = 0
        fired_idx = np.nonzero(fire)[0]
        if fired_idx.size:
            rows = fired_idx.tolist()
            contents_col, found_mask, _path = backend.get_batch(
                self.window_state, [key_col[i] for i in rows], None,
                namespaces=[ns_col[i] for i in rows])
            emitted = self._emit_fired_columns(
                rows, key_col, ns_col, contents_col, found_mask)
        if TELEMETRY.enabled and emitted:
            TELEMETRY.note_windows_fired(emitted)
        cleanup_idx = np.nonzero(cleanup)[0]
        if cleanup_idx.size:
            rows = cleanup_idx.tolist()
            backend.clear_batch(
                self.window_state, [key_col[i] for i in rows], None,
                namespaces=[ns_col[i] for i in rows])
            if lateness:
                # EventTimeTrigger.clear: drop the max_timestamp fire
                # timer (with lateness 0 that timer IS the one just
                # swept — nothing left to delete)
                svc.delete_event_time_timers_bulk(
                    (int(maxts[i]), key_col[i], ns_col[i]) for i in rows)
            if isinstance(self._internal_fn.fn, ProcessWindowFunction):
                wt = self.assigner.window_type()
                for i in rows:
                    backend.set_current_key(key_col[i])
                    self._internal_fn.clear(
                        key_col[i], wt.from_namespace(ns_col[i]), self)

    def _emit_fired_columns(self, rows, key_col, ns_col, contents_col,
                            found_mask) -> int:
        """Run the window function over the gathered contents in pop
        order, buffering the emissions; flush as ONE RecordBatch when
        the rows columnarize (per-row records otherwise, same order).
        Returns the number of windows that emitted — the scalar path's
        windowsFired increments, applied in one note."""
        wt = self.assigner.window_type()
        backend = self.keyed_backend
        hist = self._emit_batch_hist
        # a device gather hands back an ndarray: unbox 0-d rows exactly
        # as scalar get() does (`out.item() if np.ndim(out) == 0`);
        # heap results are python objects and pass through untouched
        unbox = isinstance(contents_col, np.ndarray)
        buf = _FireBufferOutput(self.output)
        collector = TimestampedCollector(buf)
        tracer = get_tracer()
        span = (tracer.span("window.fire.batch") if tracer.enabled
                else contextlib.nullcontext())
        fired = 0
        with span:
            for j, i in enumerate(rows):
                if not found_mask[j]:
                    continue
                contents = contents_col[j]
                if unbox:
                    if np.ndim(contents) == 0:
                        contents = contents.item()
                elif contents is None:
                    continue
                window = wt.from_namespace(ns_col[i])
                backend.set_current_key(key_col[i])
                if hist is not None:
                    hist.update(len(contents)
                                if hasattr(contents, "__len__") else 1)
                collector.set_absolute_timestamp(window.max_timestamp())
                self._internal_fn.process(key_col[i], window, self,
                                          contents, collector)
                fired += 1
        records = buf.records
        if not records:
            return fired
        batch = None
        if len(records) > 1:
            from flink_tpu.streaming import columnar
            if columnar.PIPELINE_ENABLED:
                batch = columnar.batch_from_records(
                    [r.value for r in records],
                    [r.timestamp for r in records])
        if batch is not None:
            self.output.collect_batch(batch)
        else:
            collect = self.output.collect
            for r in records:
                collect(r)
        return fired

    # ---- helpers ----------------------------------------------------
    def _react(self, result: int, window) -> None:
        if TriggerResult.is_fire(result):
            contents = self.window_state.get()
            if contents is not None:
                self._emit(window, contents)
        if TriggerResult.is_purge(result):
            self.window_state.clear()

    def _contents_for(self, window, merging: Optional[MergingWindowSet]):
        if merging is not None:
            state_window = merging.get_state_window(window)
            if state_window is None:
                return None
            self.window_state.set_current_namespace(
                self._namespace_of(state_window))
        return self.window_state.get()

    def _emit(self, window, contents) -> None:
        """(ref: emitWindowContents :544 — output timestamp =
        window.maxTimestamp)"""
        if self._emit_batch_hist is not None:
            self._emit_batch_hist.update(
                len(contents) if hasattr(contents, "__len__") else 1)
        if TELEMETRY.enabled:
            # per-key timer fire: one emitted (key, window) result —
            # the denominator of the device ledger's transfer-tax ratio
            TELEMETRY.note_windows_fired(1)
        tracer = get_tracer()
        if tracer.enabled:
            with tracer.span("window.fire"):
                self.collector.set_absolute_timestamp(
                    window.max_timestamp())
                key = self.keyed_backend.current_key
                self._internal_fn.process(key, window, self, contents,
                                          self.collector)
            return
        self.collector.set_absolute_timestamp(window.max_timestamp())
        key = self.keyed_backend.current_key
        self._internal_fn.process(key, window, self, contents, self.collector)

    def _window_from_namespace(self, namespace):
        wt = self.assigner.window_type()
        return wt.from_namespace(namespace)

    def _cleanup_time(self, window) -> int:
        if self.assigner.is_event_time():
            # cap at MAX_TIMESTAMP — Python ints don't overflow, so an
            # explicit cap replaces the reference's wraparound check
            # (GlobalWindows + lateness must stay at "end of time")
            t = window.max_timestamp() + self.allowed_lateness
            return t if t < MAX_TIMESTAMP else MAX_TIMESTAMP
        return window.max_timestamp()

    def _is_cleanup_time(self, window, time: int) -> bool:
        return time == self._cleanup_time(window)

    def _register_cleanup_timer(self, window) -> None:
        cleanup = self._cleanup_time(window)
        if cleanup == MAX_TIMESTAMP:
            return  # end of time — nothing to GC (ref: :596-626)
        self.trigger_ctx.window = window
        if self.assigner.is_event_time():
            self.timer_service.register_event_time_timer(
                self._namespace_of(window), cleanup)
        else:
            self.timer_service.register_processing_time_timer(
                self._namespace_of(window), cleanup)

    def _delete_cleanup_timer(self, window) -> None:
        cleanup = self._cleanup_time(window)
        if cleanup == MAX_TIMESTAMP:
            return
        if self.assigner.is_event_time():
            self.timer_service.delete_event_time_timer(
                self._namespace_of(window), cleanup)
        else:
            self.timer_service.delete_processing_time_timer(
                self._namespace_of(window), cleanup)

    def _is_window_late(self, window) -> bool:
        """(ref: isWindowLate :576)"""
        return (self.assigner.is_event_time()
                and self._cleanup_time(window) <= self.timer_service.current_watermark)

    def _is_element_late(self, record: StreamRecord) -> bool:
        """(ref: isElementLate :589)"""
        return (self.assigner.is_event_time()
                and record.timestamp is not None
                and record.timestamp + self.allowed_lateness
                <= self.timer_service.current_watermark)

    def _clear_all_state(self, window, merging: Optional[MergingWindowSet]) -> None:
        """(ref: clearAllState :517)"""
        self.window_state.clear()
        self.trigger_ctx.window = window
        self.trigger.clear(window, self.trigger_ctx)
        key = self.keyed_backend.current_key
        self._internal_fn.clear(key, window, self)
        if merging is not None:
            merging.retire_window(window)


# ---------------------------------------------------------------------
# Evicting variant (ref: EvictingWindowOperator.java)
# ---------------------------------------------------------------------

class EvictingWindowOperator(WindowOperator):
    """Keeps raw (timestamp, value) pairs and applies the evictor
    around the window function."""

    def __init__(self, assigner, window_function, trigger=None,
                 evictor=None, allowed_lateness=0, late_data_tag=None,
                 pre_aggregator=None):
        if evictor is None:
            raise ValueError("EvictingWindowOperator requires an evictor")
        super().__init__(
            assigner,
            ListStateDescriptor("window-contents-evicting"),
            window_function,
            trigger,
            allowed_lateness,
            late_data_tag,
            single_value_contents=False,
        )
        self.evictor = evictor
        #: with an evictor, pre-aggregation is impossible (raw elements
        #: must be retained), so reduce/aggregate run at fire time over
        #: the surviving elements (ref: WindowedStream.reduce's
        #: evictor branch wrapping into ReduceApplyWindowFunction)
        self.pre_aggregator = pre_aggregator
        if pre_aggregator is not None:
            self._internal_fn = _InternalWindowFunction(
                window_function, single_value=True)

    def _batch_eligibility(self) -> Optional[str]:
        return "evictor retains raw per-row elements"

    def _state_value(self, record: StreamRecord):
        # store (timestamp, value) so time-based eviction works; the
        # raw record still flows to triggers and late-data side output
        return (record.timestamp, record.value)

    def _emit(self, window, contents) -> None:
        elements: List[Tuple[int, Any]] = list(contents)
        now = (self.timer_service.current_watermark
               if self.assigner.is_event_time()
               else self.processing_time_service.get_current_processing_time())
        kept = self.evictor.evict_before(elements, len(elements), window, now)
        self.collector.set_absolute_timestamp(window.max_timestamp())
        key = self.keyed_backend.current_key
        values = [v for _, v in kept]
        if self.pre_aggregator is not None:
            if values:
                self._internal_fn.process(
                    key, window, self, self.pre_aggregator(values),
                    self.collector)
        else:
            self._internal_fn.process(key, window, self, values, self.collector)
        after = self.evictor.evict_after(kept, len(kept), window, now)
        # write back the surviving elements
        self.window_state.update([(ts, v) for ts, v in after])
