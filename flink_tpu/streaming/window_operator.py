"""WindowOperator: windowed keyed aggregation with triggers, allowed
lateness, and merging session windows.

Re-designs flink-streaming-java/.../runtime/operators/windowing/
WindowOperator.java:97 — processElement :291-421, onEventTime :424,
onProcessingTime :472, emitWindowContents :544, cleanup timers
:596-626, lateness :576-589 — and MergingWindowSet.java:54,119,156.
Window state is keyed state under namespace = window
(WindowOperator.java:387), so ALL backends (heap and TPU) serve it
unchanged; on the TPU backend a window-fire is a device gather and
`add` is a micro-batched scatter.

EvictingWindowOperator keeps the raw elements in a ListState and runs
the Evictor before/after the window function
(ref: EvictingWindowOperator.java).
"""

from __future__ import annotations

import abc
from typing import Any, Iterable, List, Optional, Tuple

from flink_tpu.core.state import (
    AggregatingStateDescriptor,
    ListStateDescriptor,
    ReducingStateDescriptor,
    StateDescriptor,
    ValueStateDescriptor,
)
from flink_tpu.runtime.tracing import get_tracer
from flink_tpu.streaming.elements import MAX_TIMESTAMP, StreamRecord
from flink_tpu.streaming.operators import (
    AbstractUdfStreamOperator,
    OutputTag,
    TimestampedCollector,
)
from flink_tpu.streaming.windowing import (
    Trigger,
    TriggerContext,
    TriggerResult,
    WindowAssigner,
)


# ---------------------------------------------------------------------
# Window functions (ref: runtime/operators/windowing/functions/)
# ---------------------------------------------------------------------

class ProcessWindowFunction(abc.ABC):
    """(ref: ProcessWindowFunction.java) — full access to window
    metadata; elements is the window contents iterable."""

    @abc.abstractmethod
    def process(self, key, context: "WindowContext", elements: Iterable, out) -> None:
        ...

    def clear(self, context: "WindowContext") -> None:  # noqa: B027
        pass


class WindowFunction(abc.ABC):
    """(ref: WindowFunction.java) — apply(key, window, inputs, out)."""

    @abc.abstractmethod
    def apply(self, key, window, inputs: Iterable, out) -> None:
        ...


class PassThroughWindowFunction(WindowFunction):
    """Emit the (single) pre-aggregated value
    (ref: PassThroughWindowFunction.java)."""

    def apply(self, key, window, inputs, out):
        out.collect(inputs)


class WindowContext:
    """(ref: ProcessWindowFunction.Context)"""

    def __init__(self, window, op: "WindowOperator"):
        self.window = window
        self._op = op

    def current_processing_time(self) -> int:
        return self._op.processing_time_service.get_current_processing_time()

    def current_watermark(self) -> int:
        return self._op.timer_service.current_watermark

    def window_state(self, descriptor: StateDescriptor):
        """Per-(key, window) state."""
        return self._op.keyed_backend.get_partitioned_state(
            self._op._namespace_of(self.window), descriptor)

    def global_state(self, descriptor: StateDescriptor):
        """Per-key state shared across windows."""
        from flink_tpu.state.backend import VOID_NAMESPACE
        return self._op.keyed_backend.get_partitioned_state(VOID_NAMESPACE, descriptor)

    def output(self, tag: OutputTag, value) -> None:
        self._op.output.collect_side(
            tag, StreamRecord(value, self.window.max_timestamp()))


class _InternalWindowFunction:
    """Normalizes the three user-function shapes to one call."""

    def __init__(self, fn, single_value: bool):
        self.fn = fn
        #: True when window contents are a single pre-aggregated value
        self.single_value = single_value

    def process(self, key, window, op, contents, collector) -> None:
        if self.fn is None:
            collector.collect(contents)
        elif isinstance(self.fn, ProcessWindowFunction):
            elements = [contents] if self.single_value else contents
            self.fn.process(key, WindowContext(window, op), elements, collector)
        elif isinstance(self.fn, WindowFunction):
            elements = [contents] if self.single_value else contents
            self.fn.apply(key, window, elements, collector)
        else:  # plain callable(key, window, elements) -> iterable
            elements = [contents] if self.single_value else contents
            result = self.fn(key, window, elements)
            if result is not None:
                for v in result:
                    collector.collect(v)

    def clear(self, key, window, op) -> None:
        if isinstance(self.fn, ProcessWindowFunction):
            self.fn.clear(WindowContext(window, op))


# ---------------------------------------------------------------------
# MergingWindowSet (ref: MergingWindowSet.java)
# ---------------------------------------------------------------------

class MergingWindowSet:
    """Per-key mapping window → state window for merging (session)
    assigners.  When windows merge, one pre-existing state window is
    kept as the merge target and the others' state is folded into it —
    so state never has to be re-namespaced (ref: MergingWindowSet.java:54)."""

    def __init__(self, mapping_state):
        #: ValueState holding {window_namespace: state_window_namespace}
        self._mapping_state = mapping_state
        m = mapping_state.value()
        self.mapping: dict = dict(m) if m else {}

    def persist(self) -> None:
        if self.mapping:
            self._mapping_state.update(dict(self.mapping))
        else:
            self._mapping_state.clear()

    def get_state_window(self, window):
        return self.mapping.get(window)

    def retire_window(self, window) -> None:
        if window in self.mapping:
            del self.mapping[window]

    def add_window(self, new_window, merge_callback):
        """Add `new_window`, eagerly merging all transitively
        intersecting windows.  merge_callback(merge_result,
        merged_windows, state_window_result, merged_state_windows) is
        invoked when a merge happens (ref: addWindow :119)."""
        windows = list(self.mapping.keys()) + [new_window]
        merge_result = new_window
        to_merge = []
        changed = True
        while changed:
            changed = False
            for w in windows:
                if w is merge_result or w in to_merge:
                    continue
                if w.intersects(merge_result):
                    merge_result = merge_result.cover(w)
                    to_merge.append(w)
                    changed = True
        # to_merge = pre-existing windows (and possibly none) swallowed
        to_merge_existing = [w for w in to_merge if w in self.mapping]
        if not to_merge_existing and new_window not in self.mapping:
            # brand-new non-overlapping window: its own state window
            self.mapping[new_window] = new_window
            return new_window
        if not to_merge_existing:
            return new_window  # exact duplicate of an existing window
        # keep the first existing window's state window as target
        state_window_result = self.mapping[to_merge_existing[0]]
        merged_state_windows = []
        for w in to_merge_existing:
            sw = self.mapping.pop(w)
            if sw != state_window_result:
                merged_state_windows.append(sw)
        self.mapping[merge_result] = state_window_result
        merged_windows = to_merge_existing + (
            [new_window] if new_window not in to_merge_existing else [])
        # don't fire the callback for a no-op (new window already covered
        # by one existing window and nothing else merged)
        if len(to_merge_existing) > 1 or (
                merge_result != to_merge_existing[0]) or merged_state_windows:
            if merge_result not in to_merge_existing or merged_state_windows:
                merge_callback(merge_result, merged_windows,
                               state_window_result, merged_state_windows)
        return merge_result


# ---------------------------------------------------------------------
# WindowOperator
# ---------------------------------------------------------------------

class _WindowTriggerContext(TriggerContext):
    """(ref: WindowOperator.Context :649)"""

    def __init__(self, op: "WindowOperator"):
        self._op = op
        self.window = None

    def register_event_time_timer(self, time):
        self._op.timer_service.register_event_time_timer(
            self._op._namespace_of(self.window), time)

    def register_processing_time_timer(self, time):
        self._op.timer_service.register_processing_time_timer(
            self._op._namespace_of(self.window), time)

    def delete_event_time_timer(self, time):
        self._op.timer_service.delete_event_time_timer(
            self._op._namespace_of(self.window), time)

    def delete_processing_time_timer(self, time):
        self._op.timer_service.delete_processing_time_timer(
            self._op._namespace_of(self.window), time)

    def get_current_watermark(self):
        return self._op.timer_service.current_watermark

    def get_current_processing_time(self):
        return self._op.processing_time_service.get_current_processing_time()

    def get_partitioned_state(self, descriptor):
        """Trigger state, scoped (key, window)."""
        return self._op.keyed_backend.get_partitioned_state(
            self._op._namespace_of(self.window), descriptor)

    #: set before trigger.on_merge fires (ref: OnMergeContext)
    merged_windows = ()

    def merge_partitioned_state(self, descriptor):
        """Merge per-window trigger state of the merged windows into
        the merge result's namespace (ref:
        Trigger.OnMergeContext#mergePartitionedState)."""
        state = self._op.keyed_backend.get_or_create_keyed_state(descriptor)
        if hasattr(state, "merge_namespaces"):
            state.merge_namespaces(
                self._op._namespace_of(self.window),
                [self._op._namespace_of(w) for w in self.merged_windows])


class _AssignerContext:
    """(ref: WindowAssigner.WindowAssignerContext)"""

    def __init__(self, op: "WindowOperator"):
        self._op = op

    def get_current_processing_time(self):
        return self._op.processing_time_service.get_current_processing_time()


class WindowOperator(AbstractUdfStreamOperator):
    """One-input keyed window operator."""

    MAPPING_STATE_NAME = "window-merge-mapping"

    def __init__(
        self,
        assigner: WindowAssigner,
        state_descriptor: StateDescriptor,
        window_function=None,
        trigger: Optional[Trigger] = None,
        allowed_lateness: int = 0,
        late_data_tag: Optional[OutputTag] = None,
        single_value_contents: Optional[bool] = None,
    ):
        super().__init__(window_function)
        self.assigner = assigner
        self.state_descriptor = state_descriptor
        self.trigger = trigger or assigner.get_default_trigger()
        if allowed_lateness < 0:
            raise ValueError("allowed lateness must be >= 0")
        if assigner.is_merging() and not self.trigger.can_merge():
            raise ValueError(
                f"trigger {self.trigger!r} cannot merge but assigner "
                f"{assigner!r} is a merging assigner")
        self.allowed_lateness = allowed_lateness
        self.late_data_tag = late_data_tag
        if single_value_contents is None:
            single_value_contents = isinstance(
                state_descriptor,
                (ReducingStateDescriptor, AggregatingStateDescriptor))
        self._internal_fn = _InternalWindowFunction(
            window_function, single_value_contents)
        # metrics (ref: numLateRecordsDropped, WindowOperator.java:138)
        self.num_late_records_dropped = 0

    # ---- lifecycle --------------------------------------------------
    def open(self):
        super().open()
        # structural fallback, known AOT: triggers and per-(key,
        # window) namespaced state are inherently per-row — batches
        # reaching this operator box (the columnar.ratio gauge and
        # linter FT184 surface this reason)
        self.columnar_fallback_reason = "per-row window/trigger state"
        self._emit_batch_hist = None
        if self.metrics is not None:
            # eager so monitoring sees the zero (ref: the counter is
            # constructed in WindowOperator.open, not on first drop);
            # reset = fresh execution attempt (restart replays must not
            # accumulate into the previous attempt's count)
            self.metrics.counter("numLateRecordsDropped").count = 0
            self._emit_batch_hist = self.metrics.histogram("emitBatchSize")
        self.window_state = self.keyed_backend.get_or_create_keyed_state(
            self.state_descriptor)
        self.trigger_ctx = _WindowTriggerContext(self)
        self.assigner_ctx = _AssignerContext(self)
        self.collector = TimestampedCollector(self.output)
        if self.assigner.is_merging():
            self._mapping_desc = ValueStateDescriptor(self.MAPPING_STATE_NAME)

    # namespace encoding: window -> hashable tuple (state namespaces)
    def _namespace_of(self, window):
        return window.to_namespace()

    def _state_value(self, record: StreamRecord):
        """What goes into window state for one record; the evicting
        variant stores (timestamp, value) pairs."""
        return record.value

    # ---- element path (ref: processElement :291-421) ----------------
    def process_element(self, record: StreamRecord):
        windows = self.assigner.assign_windows(
            record.value, record.timestamp, self.assigner_ctx)
        skipped = True
        if self.assigner.is_merging():
            skipped = self._process_merging(record, windows, skipped)
        else:
            for window in windows:
                if self._is_window_late(window):
                    continue
                skipped = False
                ns = self._namespace_of(window)
                self.window_state.set_current_namespace(ns)
                self.window_state.add(self._state_value(record))
                self.trigger_ctx.window = window
                result = self.trigger.on_element(
                    record.value, record.timestamp, window, self.trigger_ctx)
                self._react(result, window)
                self._register_cleanup_timer(window)
        if skipped and self._is_element_late(record):
            if self.late_data_tag is not None:
                self.output.collect_side(self.late_data_tag, record)
            else:
                self.num_late_records_dropped += 1
                if self.metrics is not None:
                    self.metrics.counter("numLateRecordsDropped").inc()

    def _process_merging(self, record, windows, skipped):
        from flink_tpu.state.backend import VOID_NAMESPACE
        mapping_state = self.keyed_backend.get_partitioned_state(
            VOID_NAMESPACE, self._mapping_desc)
        merging = MergingWindowSet(mapping_state)

        def on_merge(merge_result, merged_windows, state_window, merged_state_windows):
            # fold merged state windows into the surviving one
            if merged_state_windows and hasattr(self.window_state, "merge_namespaces"):
                self.window_state.merge_namespaces(
                    self._namespace_of(state_window),
                    [self._namespace_of(w) for w in merged_state_windows])
            # trigger merges its per-window state FIRST (ref: the order
            # in WindowOperator's merge callback: onMerge, then clear
            # each merged window), then old windows' trigger state,
            # timers, and cleanup timers are dropped
            self.trigger_ctx.window = merge_result
            self.trigger_ctx.merged_windows = [
                w for w in merged_windows if w != merge_result]
            self.trigger.on_merge(merge_result, self.trigger_ctx)
            self.trigger_ctx.merged_windows = ()
            for w in merged_windows:
                if w == merge_result:
                    continue
                self.trigger_ctx.window = w
                self.trigger.clear(w, self.trigger_ctx)
                self._delete_cleanup_timer(w)

        for window in windows:
            actual = merging.add_window(window, on_merge)
            if self._is_window_late(actual):
                merging.retire_window(actual)
                continue
            skipped = False
            state_window = merging.get_state_window(actual)
            self.window_state.set_current_namespace(
                self._namespace_of(state_window))
            self.window_state.add(self._state_value(record))
            self.trigger_ctx.window = actual
            result = self.trigger.on_element(
                record.value, record.timestamp, actual, self.trigger_ctx)
            if TriggerResult.is_fire(result):
                contents = self._contents_for(actual, merging)
                if contents is not None:
                    self._emit(actual, contents)
            if TriggerResult.is_purge(result):
                self.window_state.clear()
            self._register_cleanup_timer(actual)
        merging.persist()
        return skipped

    # ---- timers (ref: onEventTime :424 / onProcessingTime :472) -----
    def on_event_time(self, timer):
        window = self._window_from_namespace(timer.namespace)
        self.trigger_ctx.window = window
        merging = None
        if self.assigner.is_merging():
            from flink_tpu.state.backend import VOID_NAMESPACE
            mapping_state = self.keyed_backend.get_partitioned_state(
                VOID_NAMESPACE, self._mapping_desc)
            merging = MergingWindowSet(mapping_state)
            state_window = merging.get_state_window(window)
            if state_window is None:
                return  # window was merged away; timer is stale
            self.window_state.set_current_namespace(
                self._namespace_of(state_window))
        else:
            self.window_state.set_current_namespace(self._namespace_of(window))

        result = self.trigger.on_event_time(timer.timestamp, window, self.trigger_ctx)
        if TriggerResult.is_fire(result):
            contents = self.window_state.get()
            if contents is not None:
                self._emit(window, contents)
        if TriggerResult.is_purge(result):
            self.window_state.clear()
        if self.assigner.is_event_time() and self._is_cleanup_time(window, timer.timestamp):
            self._clear_all_state(window, merging)
        if merging is not None:
            merging.persist()

    def on_processing_time(self, timer):
        window = self._window_from_namespace(timer.namespace)
        self.trigger_ctx.window = window
        merging = None
        if self.assigner.is_merging():
            from flink_tpu.state.backend import VOID_NAMESPACE
            mapping_state = self.keyed_backend.get_partitioned_state(
                VOID_NAMESPACE, self._mapping_desc)
            merging = MergingWindowSet(mapping_state)
            state_window = merging.get_state_window(window)
            if state_window is None:
                return
            self.window_state.set_current_namespace(
                self._namespace_of(state_window))
        else:
            self.window_state.set_current_namespace(self._namespace_of(window))

        result = self.trigger.on_processing_time(
            timer.timestamp, window, self.trigger_ctx)
        if TriggerResult.is_fire(result):
            contents = self.window_state.get()
            if contents is not None:
                self._emit(window, contents)
        if TriggerResult.is_purge(result):
            self.window_state.clear()
        if (not self.assigner.is_event_time()
                and self._is_cleanup_time(window, timer.timestamp)):
            self._clear_all_state(window, merging)
        if merging is not None:
            merging.persist()

    # ---- helpers ----------------------------------------------------
    def _react(self, result: int, window) -> None:
        if TriggerResult.is_fire(result):
            contents = self.window_state.get()
            if contents is not None:
                self._emit(window, contents)
        if TriggerResult.is_purge(result):
            self.window_state.clear()

    def _contents_for(self, window, merging: Optional[MergingWindowSet]):
        if merging is not None:
            state_window = merging.get_state_window(window)
            if state_window is None:
                return None
            self.window_state.set_current_namespace(
                self._namespace_of(state_window))
        return self.window_state.get()

    def _emit(self, window, contents) -> None:
        """(ref: emitWindowContents :544 — output timestamp =
        window.maxTimestamp)"""
        if self._emit_batch_hist is not None:
            self._emit_batch_hist.update(
                len(contents) if hasattr(contents, "__len__") else 1)
        tracer = get_tracer()
        if tracer.enabled:
            with tracer.span("window.fire"):
                self.collector.set_absolute_timestamp(
                    window.max_timestamp())
                key = self.keyed_backend.current_key
                self._internal_fn.process(key, window, self, contents,
                                          self.collector)
            return
        self.collector.set_absolute_timestamp(window.max_timestamp())
        key = self.keyed_backend.current_key
        self._internal_fn.process(key, window, self, contents, self.collector)

    def _window_from_namespace(self, namespace):
        wt = self.assigner.window_type()
        return wt.from_namespace(namespace)

    def _cleanup_time(self, window) -> int:
        if self.assigner.is_event_time():
            # cap at MAX_TIMESTAMP — Python ints don't overflow, so an
            # explicit cap replaces the reference's wraparound check
            # (GlobalWindows + lateness must stay at "end of time")
            t = window.max_timestamp() + self.allowed_lateness
            return t if t < MAX_TIMESTAMP else MAX_TIMESTAMP
        return window.max_timestamp()

    def _is_cleanup_time(self, window, time: int) -> bool:
        return time == self._cleanup_time(window)

    def _register_cleanup_timer(self, window) -> None:
        cleanup = self._cleanup_time(window)
        if cleanup == MAX_TIMESTAMP:
            return  # end of time — nothing to GC (ref: :596-626)
        self.trigger_ctx.window = window
        if self.assigner.is_event_time():
            self.timer_service.register_event_time_timer(
                self._namespace_of(window), cleanup)
        else:
            self.timer_service.register_processing_time_timer(
                self._namespace_of(window), cleanup)

    def _delete_cleanup_timer(self, window) -> None:
        cleanup = self._cleanup_time(window)
        if cleanup == MAX_TIMESTAMP:
            return
        if self.assigner.is_event_time():
            self.timer_service.delete_event_time_timer(
                self._namespace_of(window), cleanup)
        else:
            self.timer_service.delete_processing_time_timer(
                self._namespace_of(window), cleanup)

    def _is_window_late(self, window) -> bool:
        """(ref: isWindowLate :576)"""
        return (self.assigner.is_event_time()
                and self._cleanup_time(window) <= self.timer_service.current_watermark)

    def _is_element_late(self, record: StreamRecord) -> bool:
        """(ref: isElementLate :589)"""
        return (self.assigner.is_event_time()
                and record.timestamp is not None
                and record.timestamp + self.allowed_lateness
                <= self.timer_service.current_watermark)

    def _clear_all_state(self, window, merging: Optional[MergingWindowSet]) -> None:
        """(ref: clearAllState :517)"""
        self.window_state.clear()
        self.trigger_ctx.window = window
        self.trigger.clear(window, self.trigger_ctx)
        key = self.keyed_backend.current_key
        self._internal_fn.clear(key, window, self)
        if merging is not None:
            merging.retire_window(window)


# ---------------------------------------------------------------------
# Evicting variant (ref: EvictingWindowOperator.java)
# ---------------------------------------------------------------------

class EvictingWindowOperator(WindowOperator):
    """Keeps raw (timestamp, value) pairs and applies the evictor
    around the window function."""

    def __init__(self, assigner, window_function, trigger=None,
                 evictor=None, allowed_lateness=0, late_data_tag=None,
                 pre_aggregator=None):
        if evictor is None:
            raise ValueError("EvictingWindowOperator requires an evictor")
        super().__init__(
            assigner,
            ListStateDescriptor("window-contents-evicting"),
            window_function,
            trigger,
            allowed_lateness,
            late_data_tag,
            single_value_contents=False,
        )
        self.evictor = evictor
        #: with an evictor, pre-aggregation is impossible (raw elements
        #: must be retained), so reduce/aggregate run at fire time over
        #: the surviving elements (ref: WindowedStream.reduce's
        #: evictor branch wrapping into ReduceApplyWindowFunction)
        self.pre_aggregator = pre_aggregator
        if pre_aggregator is not None:
            self._internal_fn = _InternalWindowFunction(
                window_function, single_value=True)

    def _state_value(self, record: StreamRecord):
        # store (timestamp, value) so time-based eviction works; the
        # raw record still flows to triggers and late-data side output
        return (record.timestamp, record.value)

    def _emit(self, window, contents) -> None:
        elements: List[Tuple[int, Any]] = list(contents)
        now = (self.timer_service.current_watermark
               if self.assigner.is_event_time()
               else self.processing_time_service.get_current_processing_time())
        kept = self.evictor.evict_before(elements, len(elements), window, now)
        self.collector.set_absolute_timestamp(window.max_timestamp())
        key = self.keyed_backend.current_key
        values = [v for _, v in kept]
        if self.pre_aggregator is not None:
            if values:
                self._internal_fn.process(
                    key, window, self, self.pre_aggregator(values),
                    self.collector)
        else:
            self._internal_fn.process(key, window, self, values, self.collector)
        after = self.evictor.evict_after(kept, len(kept), window, now)
        # write back the surviving elements
        self.window_state.update([(ts, v) for ts, v in after])
