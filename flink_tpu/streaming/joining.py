"""Windowed join / coGroup (ref: DataStream.join :709 / coGroup :701 +
api/datastream/{JoinedStreams,CoGroupedStreams}.java).

Same construction as the reference: both inputs map into tagged
carriers, union, key by the respective key selectors, and a window
apply over the buffered window contents splits the tags back apart
(CoGroupedStreams.java's TaggedUnion + UnionKeySelector).  join =
coGroup with a cartesian pairing of the two groups
(JoinedStreams.java's FlatJoinCoGroupFunction).
"""

from __future__ import annotations

from typing import Any, Callable

from flink_tpu.core.functions import as_key_selector


class JoinedStreams:
    """stream1.join(stream2).where(k1).equal_to(k2).window(w).apply(f)"""

    def __init__(self, first, second, cogroup: bool = False):
        self.first = first
        self.second = second
        self._cogroup = cogroup

    def where(self, key_selector) -> "_Where":
        return _Where(self, as_key_selector(key_selector))


class CoGroupedStreams(JoinedStreams):
    def __init__(self, first, second):
        super().__init__(first, second, cogroup=True)


class _Where:
    def __init__(self, joined: JoinedStreams, ks1):
        self.joined = joined
        self.ks1 = ks1

    def equal_to(self, key_selector) -> "_EqualTo":
        return _EqualTo(self.joined, self.ks1,
                        as_key_selector(key_selector))


class _EqualTo:
    def __init__(self, joined, ks1, ks2):
        self.joined = joined
        self.ks1 = ks1
        self.ks2 = ks2

    def window(self, assigner) -> "_WithWindow":
        return _WithWindow(self.joined, self.ks1, self.ks2, assigner)


class _WithWindow:
    def __init__(self, joined, ks1, ks2, assigner):
        self.joined = joined
        self.ks1 = ks1
        self.ks2 = ks2
        self.assigner = assigner
        self._trigger = None
        self._evictor = None
        self._lateness = 0

    def trigger(self, trigger) -> "_WithWindow":
        self._trigger = trigger
        return self

    def evictor(self, evictor) -> "_WithWindow":
        self._evictor = evictor
        return self

    def allowed_lateness(self, lateness) -> "_WithWindow":
        self._lateness = lateness
        return self

    def apply(self, fn: Callable[..., Any], name: str = None):
        """join: fn(left, right) per pair; coGroup: fn(lefts, rights)
        returning an iterable of outputs."""
        joined = self.joined
        keyed = _tagged_union_keyed(joined.first, joined.second,
                                    self.ks1, self.ks2, "join")
        win = keyed.window(self.assigner)
        if self._trigger is not None:
            win = win.trigger(self._trigger)
        if self._evictor is not None:
            win = win.evictor(self._evictor)
        if self._lateness:
            win = win.allowed_lateness(self._lateness)
        cogroup = joined._cogroup

        def window_fn(key, window, elements):
            lefts = [v for t, v in elements if t == 0]
            rights = [v for t, v in elements if t == 1]
            if cogroup:
                out = fn(lefts, rights)
                return list(out) if out is not None else []
            return [fn(a, b) for a in lefts for b in rights]

        return win.apply(window_fn,
                         name=name or ("co_group" if cogroup else "join"))


def _tagged_union_keyed(first, second, ks1, ks2, prefix: str):
    """TaggedUnion construction shared by the window join and the
    interval join (CoGroupedStreams.java's TaggedUnion +
    UnionKeySelector): both inputs map into (tag, value) carriers,
    union, and key by the side's key selector."""
    tagged1 = first.map(lambda v: (0, v), name=f"{prefix}_tag_left")
    tagged2 = second.map(lambda v: (1, v), name=f"{prefix}_tag_right")
    return tagged1.union(tagged2).key_by(
        lambda tv: ks1.get_key(tv[1]) if tv[0] == 0
        else ks2.get_key(tv[1]))


# ---------------------------------------------------------------------
# Interval (time-bounded stream-stream) join
# (ref: the Table layer's windowed join — WindowJoinUtil.scala bounds
# analysis + the time-bounded join ProcessFunction family; surfaced in
# later reference versions as DataStream.intervalJoin)
# ---------------------------------------------------------------------

class IntervalJoinedStreams:
    """left.interval_join(right).where(k1).equal_to(k2)
    .between(lower_ms, upper_ms).apply(fn): emits fn(l, r) for every
    pair with r.ts - l.ts in [lower, upper] and equal keys, with the
    pair's max timestamp; state is cleaned by event-time timers."""

    def __init__(self, first, second):
        self.first = first
        self.second = second

    def where(self, key_selector):
        return _IvWhere(self, as_key_selector(key_selector))


class _IvWhere:
    def __init__(self, joined, ks1):
        self.joined = joined
        self.ks1 = ks1

    def equal_to(self, key_selector):
        return _IvEqual(self.joined, self.ks1,
                        as_key_selector(key_selector))


class _IvEqual:
    def __init__(self, joined, ks1, ks2):
        self.joined = joined
        self.ks1 = ks1
        self.ks2 = ks2

    def between(self, lower_ms: int, upper_ms: int):
        if lower_ms > upper_ms:
            raise ValueError("interval join: lower bound > upper bound")
        return _IvBetween(self.joined, self.ks1, self.ks2,
                          lower_ms, upper_ms)


class _IvBetween:
    def __init__(self, joined, ks1, ks2, lower, upper):
        self.joined = joined
        self.ks1 = ks1
        self.ks2 = ks2
        self.lower = lower
        self.upper = upper

    def apply(self, fn, name: str = None):
        from flink_tpu.core.state import ValueStateDescriptor
        from flink_tpu.streaming.operators import ProcessFunction

        lower, upper = self.lower, self.upper
        left_desc = ValueStateDescriptor("iv_join_left")
        right_desc = ValueStateDescriptor("iv_join_right")

        class _IvJoinFn(ProcessFunction):
            def process_element(self, value, ctx, out):
                tag, v = value
                ts = ctx.timestamp()
                mine = left_desc if tag == 0 else right_desc
                other = right_desc if tag == 0 else left_desc
                buf = ctx.get_state(mine).value() or {}
                buf.setdefault(ts, []).append(v)
                ctx.get_state(mine).update(buf)
                # this row stays joinable until the watermark passes
                # the last other-side timestamp it could pair with
                cleanup = ts + (upper if tag == 0 else -lower)
                ctx.register_event_time_timer(max(cleanup, ts))
                obuf = ctx.get_state(other).value() or {}
                if tag == 0:
                    lo, hi = ts + lower, ts + upper
                else:
                    lo, hi = ts - upper, ts - lower
                for ots, rows in obuf.items():
                    if lo <= ots <= hi:
                        out.set_absolute_timestamp(max(ts, ots))
                        for o in rows:
                            out.collect(fn(v, o) if tag == 0
                                        else fn(o, v))

            def on_timer(self, timestamp, ctx, out):
                wm = timestamp
                for desc, horizon in ((left_desc, upper),
                                      (right_desc, -lower)):
                    st = ctx.get_state(desc)
                    buf = st.value()
                    if not buf:
                        continue
                    kept = {t: r for t, r in buf.items()
                            if t + horizon > wm}
                    if len(kept) != len(buf):
                        st.update(kept)

        joined = self.joined
        keyed = _tagged_union_keyed(joined.first, joined.second,
                                    self.ks1, self.ks2, "iv_join")
        return keyed.process(_IvJoinFn(), name=name or "interval_join")
