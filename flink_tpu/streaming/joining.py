"""Windowed join / coGroup (ref: DataStream.join :709 / coGroup :701 +
api/datastream/{JoinedStreams,CoGroupedStreams}.java).

Same construction as the reference: both inputs map into tagged
carriers, union, key by the respective key selectors, and a window
apply over the buffered window contents splits the tags back apart
(CoGroupedStreams.java's TaggedUnion + UnionKeySelector).  join =
coGroup with a cartesian pairing of the two groups
(JoinedStreams.java's FlatJoinCoGroupFunction).
"""

from __future__ import annotations

from typing import Any, Callable

from flink_tpu.core.functions import as_key_selector


class JoinedStreams:
    """stream1.join(stream2).where(k1).equal_to(k2).window(w).apply(f)"""

    def __init__(self, first, second, cogroup: bool = False):
        self.first = first
        self.second = second
        self._cogroup = cogroup

    def where(self, key_selector) -> "_Where":
        return _Where(self, as_key_selector(key_selector))


class CoGroupedStreams(JoinedStreams):
    def __init__(self, first, second):
        super().__init__(first, second, cogroup=True)


class _Where:
    def __init__(self, joined: JoinedStreams, ks1):
        self.joined = joined
        self.ks1 = ks1

    def equal_to(self, key_selector) -> "_EqualTo":
        return _EqualTo(self.joined, self.ks1,
                        as_key_selector(key_selector))


class _EqualTo:
    def __init__(self, joined, ks1, ks2):
        self.joined = joined
        self.ks1 = ks1
        self.ks2 = ks2

    def window(self, assigner) -> "_WithWindow":
        return _WithWindow(self.joined, self.ks1, self.ks2, assigner)


class _WithWindow:
    def __init__(self, joined, ks1, ks2, assigner):
        self.joined = joined
        self.ks1 = ks1
        self.ks2 = ks2
        self.assigner = assigner
        self._trigger = None
        self._evictor = None
        self._lateness = 0

    def trigger(self, trigger) -> "_WithWindow":
        self._trigger = trigger
        return self

    def evictor(self, evictor) -> "_WithWindow":
        self._evictor = evictor
        return self

    def allowed_lateness(self, lateness) -> "_WithWindow":
        self._lateness = lateness
        return self

    def apply(self, fn: Callable[..., Any], name: str = None):
        """join: fn(left, right) per pair; coGroup: fn(lefts, rights)
        returning an iterable of outputs."""
        joined = self.joined
        ks1, ks2 = self.ks1, self.ks2
        tagged1 = joined.first.map(lambda v: (0, v), name="join_tag_left")
        tagged2 = joined.second.map(lambda v: (1, v), name="join_tag_right")
        unioned = tagged1.union(tagged2)
        keyed = unioned.key_by(
            lambda tv: ks1.get_key(tv[1]) if tv[0] == 0
            else ks2.get_key(tv[1]))
        win = keyed.window(self.assigner)
        if self._trigger is not None:
            win = win.trigger(self._trigger)
        if self._evictor is not None:
            win = win.evictor(self._evictor)
        if self._lateness:
            win = win.allowed_lateness(self._lateness)
        cogroup = joined._cogroup

        def window_fn(key, window, elements):
            lefts = [v for t, v in elements if t == 0]
            rights = [v for t, v in elements if t == 1]
            if cogroup:
                out = fn(lefts, rights)
                return list(out) if out is not None else []
            return [fn(a, b) for a in lefts for b in rights]

        return win.apply(window_fn,
                         name=name or ("co_group" if cogroup else "join"))
