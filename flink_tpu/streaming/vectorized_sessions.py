"""Batched session windows with device-side sketch merging.

Re-designs the reference's merging-window machinery
(MergingWindowSet.java:54,119,156 + WindowOperator.processElement's
merge path :291-421) for batched execution.  Flink merges session
windows per record: assign [ts, ts+gap), probe the merging window set,
rewrite namespace pointers, merge state.  Here the per-RECORD work is
vectorized and only per-SESSION work runs on the host — typically
orders of magnitude rarer:

  1. sort the batch by (key_hash, timestamp) — numpy argsort;
  2. session-break flags (new key, or gap exceeded) → cumsum gives a
     batch-session id per record — one vector pass;
  3. scatter-aggregate records into one fresh device slot per
     BATCH-session (same update kernel as the tumbling engine);
  4. merge batch-sessions into the live session table on the host
     (intervals per key, few per key), coalescing overlapping live
     sessions; all accumulator merges are batched into device
     merge_slots calls (agg.merge_slots — the device twin of
     AggregateFunction.merge, which is why only mergeable aggregates
     (HLL, Count-Min, t-digest, sum/min/max/count) run here, exactly
     the set the reference requires for merging windows).

Lateness-0 semantics match WindowOperator + EventTimeSessionWindows:
a record (batch-session) is late only if it overlaps no live session
AND its own window end <= watermark — the post-merge lateness check
(WindowOperator.java:336-355's mergeWindows → isWindowLate order).
Differentially tested against the scalar WindowOperator.
"""

from __future__ import annotations

import bisect
import heapq
import time

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.ops.device_agg import DeviceAggregateFunction
from flink_tpu.ops.hashing import split_hash64_np
from flink_tpu.runtime.device_stats import TELEMETRY
from flink_tpu.runtime.tracing import traced_jit

_perf_ns = time.perf_counter_ns
from flink_tpu.streaming.vectorized import (
    _ScratchMergeMixin,
    _SlotArena,
    hash_keys_np,
    make_masked_update,
    pad_pow2,
)


class _Session:
    """One live session: [start, end) with end = last_ts + gap."""

    __slots__ = ("start", "end", "slot", "key")

    def __init__(self, start: int, end: int, slot: int, key):
        self.start = start
        self.end = end
        self.slot = slot
        self.key = key


class VectorizedSessionWindows(_ScratchMergeMixin):
    """Batched keyBy().window(EventTimeSessionWindows).aggregate(agg)."""

    def __init__(self, aggregate: DeviceAggregateFunction, gap_ms: int,
                 initial_capacity: int = 1 << 16,
                 emit: Optional[Callable[[Any, Any, int, int], None]] = None):
        self.agg = aggregate
        self.gap = gap_ms
        self.capacity = initial_capacity
        self.state = aggregate.init_state(initial_capacity)
        self.arena = _SlotArena(initial_capacity)
        #: key_hash -> list of live _Session (kept sorted by start)
        self.table: Dict[int, List[_Session]] = {}
        self.watermark = -(2**63)
        self.emit = emit
        self.emitted: List[Tuple[Any, Any, int, int]] = []
        self.num_late_dropped = 0
        #: (end, key_hash) min-heap driving watermark expiry — entries
        #: go stale when merges extend a session; pops revalidate
        #: against the live table
        self._expiry_heap: List[Tuple[int, int]] = []

        self._jit_update = make_masked_update(self.agg)
        self._jit_merge = traced_jit(self.agg.merge_slots,
                                     name="session.merge", donate_argnums=0)
        self._jit_result = traced_jit(self.agg.result,
                                      name="session.result")
        self._jit_clear = traced_jit(self.agg.clear_slots,
                                     name="session.clear", donate_argnums=0)

    # ---- device helpers (power-of-two padded) -----------------------
    def _clear_release(self, slots: List[int]) -> None:
        if not slots:
            return
        arr = np.asarray(slots, np.int64)
        padded = pad_pow2(arr.astype(np.int32), arr[0])
        self.state = self._jit_clear(self.state, jnp.asarray(padded))
        self.arena.release(arr)

    # ---- ingestion --------------------------------------------------
    def process_batch(self, keys, timestamps: np.ndarray,
                      values: Optional[np.ndarray] = None,
                      key_hashes: Optional[np.ndarray] = None,
                      value_hashes: Optional[np.ndarray] = None) -> None:
        ts = np.asarray(timestamps, np.int64)
        n = len(ts)
        if n == 0:
            return
        kh = key_hashes if key_hashes is not None else hash_keys_np(keys)
        keys_arr = keys if isinstance(keys, np.ndarray) else np.asarray(
            keys, dtype=object)
        if self.agg.needs_value_hash and value_hashes is None:
            value_hashes = hash_keys_np(values)

        # 1-2. sort by (key_hash, ts); break where key changes or the
        # gap is exceeded → batch-session ids
        order = np.lexsort((ts, kh))
        kh_s = kh[order]
        ts_s = ts[order]
        brk = np.ones(n, bool)
        if n > 1:
            same_key = kh_s[1:] == kh_s[:-1]
            # <=: abutting [a, a+g) / [a+g, a+2g) windows intersect and
            # merge (TimeWindow.intersects is inclusive — ref:
            # TimeWindow.java intersects, test_session_bridge_merge)
            within_gap = (ts_s[1:] - ts_s[:-1]) <= self.gap
            brk[1:] = ~(same_key & within_gap)
        sess_id = np.cumsum(brk) - 1          # per sorted record
        n_sessions = int(sess_id[-1]) + 1
        first_of = np.nonzero(brk)[0]         # first sorted idx per session
        # per-session extents
        sess_start = ts_s[first_of]
        last_of = np.empty(n_sessions, np.int64)
        last_of[:-1] = first_of[1:] - 1
        last_of[-1] = n - 1
        sess_end = ts_s[last_of] + self.gap
        sess_kh = kh_s[first_of]

        # post-merge lateness: a batch-session is late iff it overlaps
        # no live session AND ends at/before the watermark.  Vectorized
        # pre-filter: with time-ordered input the candidate set is
        # empty, so the per-session Python probe below runs only for
        # genuinely late stragglers
        live_mask = np.ones(n_sessions, bool)
        candidates = np.nonzero(sess_end - 1 <= self.watermark)[0]
        for i in candidates.tolist():
            sessions = self.table.get(int(sess_kh[i]))
            if not sessions or not any(
                    s.start <= sess_end[i] and sess_start[i] <= s.end
                    for s in sessions):
                live_mask[i] = False
        if not live_mask.all():
            dropped_sessions = np.nonzero(~live_mask)[0]
            dropped_records = np.isin(sess_id, dropped_sessions)
            self.num_late_dropped += int(dropped_records.sum())

        # 3. one fresh slot per live batch-session; scatter records
        slot_of_session = np.full(n_sessions, -1, np.int64)
        live_sessions = np.nonzero(live_mask)[0]
        if len(live_sessions) == 0:
            return
        slot_of_session[live_sessions] = self.arena.alloc(len(live_sessions))
        self._ensure_state_capacity()
        rec_slots = slot_of_session[sess_id]
        keep = rec_slots >= 0
        rs = rec_slots[keep].astype(np.int32)
        padded = 1 << max(0, (len(rs) - 1)).bit_length()
        slots_p = np.zeros(padded, np.int32)
        slots_p[:len(rs)] = rs
        if self.agg.needs_value:
            v_sorted = np.asarray(values, self.agg.value_dtype)[order][keep]
            vals_p = np.zeros(padded, self.agg.value_dtype)
            vals_p[:len(rs)] = v_sorted
        else:
            vals_p = np.zeros(1, self.agg.value_dtype)
        if self.agg.needs_value_hash:
            vh_sorted = np.asarray(value_hashes)[order][keep]
            hi0, lo0 = split_hash64_np(vh_sorted)
            hi0, lo0 = self.agg.compress_value_hash(hi0, lo0)
            hi_p = np.zeros(padded, hi0.dtype)
            lo_p = np.zeros(padded, lo0.dtype)
            hi_p[:len(rs)] = hi0
            lo_p[:len(rs)] = lo0
        else:
            hi_p = np.zeros(1, np.uint32)
            lo_p = np.zeros(1, np.uint32)
        if TELEMETRY.enabled:
            t0 = _perf_ns()
            self.state = self._jit_update(self.state, slots_p, vals_p,
                                          hi_p, lo_p, np.int32(len(rs)))
            TELEMETRY.record_transfer(
                "h2d",
                slots_p.nbytes + vals_p.nbytes + hi_p.nbytes + lo_p.nbytes,
                t0, _perf_ns(), "session.flush")
            TELEMETRY.note_flush(len(rs))
        else:
            self.state = self._jit_update(self.state, slots_p, vals_p,
                                          hi_p, lo_p, np.int32(len(rs)))

        # 4. merge batch-sessions into the live table (host work is per
        # session, device merges batched)
        merge_dst: List[int] = []
        merge_src: List[int] = []
        free_after: List[int] = []
        keys_sorted = keys_arr[order]
        heap_push = heapq.heappush
        expiry = self._expiry_heap
        for i in live_sessions.tolist():
            khash = int(sess_kh[i])
            s_new = int(sess_start[i])
            e_new = int(sess_end[i])
            slot_new = int(slot_of_session[i])
            key_obj = keys_sorted[first_of[i]]
            sessions = self.table.setdefault(khash, [])
            overlapping = [s for s in sessions
                           if s.start <= e_new and s_new <= s.end]
            if not overlapping:
                bisect.insort(sessions,
                              _Session(s_new, e_new, slot_new, key_obj),
                              key=lambda s: s.start)
                heap_push(expiry, (e_new, khash))
                continue
            # coalesce: keep the first live session as the survivor,
            # fold the batch slot and any other overlapped sessions in
            survivor = overlapping[0]
            survivor.start = min(survivor.start, s_new)
            survivor.end = max(survivor.end, e_new)
            merge_dst.append(survivor.slot)
            merge_src.append(slot_new)
            free_after.append(slot_new)
            for other in overlapping[1:]:
                survivor.start = min(survivor.start, other.start)
                survivor.end = max(survivor.end, other.end)
                merge_dst.append(survivor.slot)
                merge_src.append(other.slot)
                free_after.append(other.slot)
                sessions.remove(other)
            heap_push(expiry, (survivor.end, khash))
        self._merge_tiled(merge_dst, merge_src)
        self._clear_release(free_after)

    # ---- firing -----------------------------------------------------
    def advance_watermark(self, watermark: int) -> int:
        self.watermark = watermark
        fired = 0
        fire_slots: List[int] = []
        fire_meta: List[Tuple[Any, int, int]] = []
        # expiry-heap walk: only keys whose (possibly stale) minimum
        # session end is due are visited — an advance that retires
        # nothing is O(1), not O(keys) (merge-extended sessions leave
        # stale heap entries behind; revalidation against the live
        # table makes them harmless)
        expiry = self._expiry_heap
        seen: set = set()
        while expiry and expiry[0][0] - 1 <= watermark:
            _, khash = heapq.heappop(expiry)
            if khash in seen:
                continue
            seen.add(khash)
            sessions = self.table.get(khash)
            if not sessions:
                continue
            remaining = []
            for s in sessions:
                if s.end - 1 <= watermark:
                    fire_slots.append(s.slot)
                    fire_meta.append((s.key, s.start, s.end))
                else:
                    remaining.append(s)
            if remaining:
                self.table[khash] = remaining
            else:
                del self.table[khash]
        if not fire_slots:
            return 0
        arr = np.asarray(fire_slots, np.int32)
        padded = pad_pow2(arr, arr[0])
        if TELEMETRY.enabled:
            t0 = _perf_ns()
            results = np.asarray(self._jit_result(
                self.state, jnp.asarray(padded)))[:len(arr)]
            TELEMETRY.record_transfer("d2h", results.nbytes,
                                      t0, _perf_ns(), "session.fire")
            TELEMETRY.note_fire_read()
        else:
            results = np.asarray(self._jit_result(
                self.state, jnp.asarray(padded)))[:len(arr)]
        for (key, start, end), res in zip(fire_meta, results):
            if self.emit is not None:
                self.emit(key, res, start, end)
            else:
                self.emitted.append((key, res, start, end))
            fired += 1
        self._clear_release(fire_slots)
        if TELEMETRY.enabled:
            TELEMETRY.note_windows_fired(fired)
        return fired

    def block_until_ready(self) -> None:
        jax.tree_util.tree_map(lambda a: a.block_until_ready(), self.state)


    # ---- checkpoint integration -------------------------------------
    def snapshot(self) -> dict:
        from flink_tpu.streaming.vectorized import _snapshot_arena
        return {
            "state": {k: np.asarray(v) for k, v in self.state.items()},
            "capacity": self.capacity,
            "arena": _snapshot_arena(self.arena),
            "watermark": self.watermark,
            "num_late_dropped": self.num_late_dropped,
            "table": {kh: [(s.start, s.end, s.slot, s.key) for s in lst]
                      for kh, lst in self.table.items()},
            "scratch": self._scratch_slot_id,
        }

    def restore(self, snap: dict) -> None:
        from flink_tpu.streaming.vectorized import _restore_arena
        self.capacity = snap["capacity"]
        self.state = {k: jnp.asarray(v) for k, v in snap["state"].items()}
        self.arena = _restore_arena(snap["arena"])
        self.watermark = snap["watermark"]
        self.num_late_dropped = snap["num_late_dropped"]
        self.table = {kh: [_Session(s, e, slot, key)
                           for (s, e, slot, key) in lst]
                      for kh, lst in snap["table"].items()}
        # rebuild the expiry heap from the restored live sessions
        self._expiry_heap = [(s.end, kh)
                             for kh, lst in self.table.items()
                             for s in lst]
        heapq.heapify(self._expiry_heap)
        if snap.get("scratch") is not None:
            self._scratch_slot_id = snap["scratch"]
