"""StreamElement model.

Re-designs flink-streaming-java/.../runtime/streamrecord/: the four
element kinds flowing through operator pipelines — records, watermarks,
stream status, latency markers — plus checkpoint barriers, which in the
reference travel the network data plane (io/network/api/
CheckpointBarrier.java) and here flow in-band through the same channel
abstraction.

Timestamps are int milliseconds (event time), matching the reference's
long-millis convention; MAX_WATERMARK flushes all event-time state at
end of input (ref: Watermark.MAX_WATERMARK).
"""

from __future__ import annotations

from typing import Any, Optional

MAX_TIMESTAMP = 2**63 - 1
MIN_TIMESTAMP = -(2**63)


class StreamElement:
    __slots__ = ()

    is_record = False
    is_watermark = False
    is_stream_status = False
    is_latency_marker = False
    is_barrier = False
    is_batch = False


class StreamRecord(StreamElement):
    """(ref: StreamRecord.java — value + optional timestamp)"""

    __slots__ = ("value", "timestamp")

    is_record = True

    def __init__(self, value: Any, timestamp: Optional[int] = None):
        self.value = value
        self.timestamp = timestamp

    @property
    def has_timestamp(self) -> bool:
        return self.timestamp is not None

    def replace(self, value, timestamp=None) -> "StreamRecord":
        return StreamRecord(value, timestamp if timestamp is not None else self.timestamp)

    def __repr__(self):
        return f"Record({self.value!r} @ {self.timestamp})"

    def __eq__(self, other):
        return (isinstance(other, StreamRecord) and self.value == other.value
                and self.timestamp == other.timestamp)

    def __hash__(self):
        return hash((self.value if not isinstance(self.value, (list, dict)) else id(self.value),
                     self.timestamp))


class RecordBatch(StreamElement):
    """A batch of rows as named numpy columns (+ event timestamps) —
    a FIRST-CLASS stream element: it flows through channels and
    operator chains like a record, amortizing per-element costs over
    thousands of rows (the Python analogue of the reference's codegen
    / Blink vectorized execution closing the per-record
    interpretation gap).

    Column convention for generic pipelines: a single column named
    ``"v"`` means scalar rows (row value = the cell); any other
    column set means tuple rows in column order (``"f0".."fk"`` when
    machine-built).  ``ts`` is an optional int64 row-timestamp
    column; ``ts_mask`` (optional bool column, True = valid) carries
    None-timestamp validity so boxing a batch reproduces the exact
    per-record timestamps.

    Batches are IMMUTABLE by contract once emitted: the router may
    share one batch across broadcast channels and sub-batches are
    gathered views/copies — operators must build new batches instead
    of writing columns in place.
    """

    __slots__ = ("cols", "ts", "ts_mask", "routing")

    is_batch = True

    def __init__(self, cols, ts=None, ts_mask=None, routing=None):
        #: {name: np.ndarray} — all the same length
        self.cols = cols
        #: int64 ndarray of per-row event timestamps, or None
        self.ts = ts
        #: bool ndarray (True = row HAS a timestamp), or None when
        #: every row's validity equals ``ts is not None``
        self.ts_mask = ts_mask
        #: optional uint64 ndarray of precomputed per-row routing
        #: hashes (splitmix64 of the key column, exactly what
        #: ``KeyGroupStreamPartitioner`` would compute).  Only a
        #: producer that KNOWS the downstream key selector may set
        #: this; ``take``/``with_cols`` deliberately drop it because
        #: a gather or column rewrite invalidates row↔hash pairing.
        self.routing = routing

    def __len__(self) -> int:
        return len(next(iter(self.cols.values()))) if self.cols else 0

    @property
    def is_scalar(self) -> bool:
        """True when rows are the single column's cells (not 1-tuples)."""
        return len(self.cols) == 1 and "v" in self.cols

    def rows(self):
        """Iterate row tuples over ALL columns in column order (the
        table-tier contract; scalar batches yield 1-tuples here)."""
        arrays = list(self.cols.values())
        return zip(*[a.tolist() for a in arrays])

    def row_values(self):
        """Row values as the operators see them: the cell for scalar
        batches, a tuple over columns otherwise."""
        arrays = list(self.cols.values())
        if self.is_scalar:
            return arrays[0].tolist()
        return list(zip(*[a.tolist() for a in arrays]))

    def value_arrays(self):
        """The columns a vectorized kernel consumes: one ndarray for
        scalar batches, a tuple of ndarrays (in column order) for
        tuple batches."""
        arrays = tuple(self.cols.values())
        if self.is_scalar:
            return arrays[0]
        return arrays

    def timestamps(self):
        """Per-row Optional[int] timestamps (exact boxing parity)."""
        n = len(self)
        if self.ts is None:
            return [None] * n
        stamps = self.ts.tolist()
        if self.ts_mask is None:
            return stamps
        return [t if valid else None
                for t, valid in zip(stamps, self.ts_mask.tolist())]

    def to_records(self):
        """Box into per-row StreamRecords — identical to what the
        row-at-a-time path would have produced for the same rows."""
        values = self.row_values()
        if self.ts is None:
            return [StreamRecord(v) for v in values]
        if self.ts_mask is None:
            return [StreamRecord(v, t)
                    for v, t in zip(values, self.ts.tolist())]
        stamps = self.ts.tolist()
        return [StreamRecord(v, stamps[i] if valid else None)
                for i, (v, valid)
                in enumerate(zip(values, self.ts_mask.tolist()))]

    def take(self, index):
        """Gather rows by bool mask or index array → new batch."""
        return RecordBatch(
            {k: v[index] for k, v in self.cols.items()},
            self.ts[index] if self.ts is not None else None,
            self.ts_mask[index] if self.ts_mask is not None else None)

    def with_cols(self, cols):
        """New batch with replaced columns, same timestamps."""
        return RecordBatch(cols, self.ts, self.ts_mask)

    def __repr__(self):
        return (f"RecordBatch({list(self.cols)} x {len(self)}"
                f"{' +ts' if self.ts is not None else ''})")


class Watermark(StreamElement):
    """Event-time progress marker (ref: Watermark.java): asserts no
    records with timestamp <= this will follow."""

    __slots__ = ("timestamp",)

    is_watermark = True

    def __init__(self, timestamp: int):
        self.timestamp = timestamp

    def __repr__(self):
        return f"Watermark({self.timestamp})"

    def __eq__(self, other):
        return isinstance(other, Watermark) and self.timestamp == other.timestamp

    def __hash__(self):
        return hash(("wm", self.timestamp))


MAX_WATERMARK = Watermark(MAX_TIMESTAMP)


class StreamStatus(StreamElement):
    """ACTIVE/IDLE channel status so idle inputs don't hold back the
    watermark (ref: StreamStatus.java)."""

    __slots__ = ("status",)

    is_stream_status = True

    ACTIVE = 0
    IDLE = 1

    def __init__(self, status: int):
        self.status = status

    @property
    def is_active(self) -> bool:
        return self.status == StreamStatus.ACTIVE

    def __repr__(self):
        return "StreamStatus(ACTIVE)" if self.is_active else "StreamStatus(IDLE)"

    def __eq__(self, other):
        return isinstance(other, StreamStatus) and self.status == other.status


ACTIVE_STATUS = StreamStatus(StreamStatus.ACTIVE)
IDLE_STATUS = StreamStatus(StreamStatus.IDLE)


class LatencyMarker(StreamElement):
    """Periodic source-emitted marker for latency histograms
    (ref: LatencyMarker.java:32)."""

    __slots__ = ("marked_time", "operator_id", "subtask_index")

    is_latency_marker = True

    def __init__(self, marked_time: int, operator_id: str, subtask_index: int):
        self.marked_time = marked_time
        self.operator_id = operator_id
        self.subtask_index = subtask_index

    def __repr__(self):
        return f"LatencyMarker({self.marked_time} from {self.operator_id}/{self.subtask_index})"


class CheckpointBarrier(StreamElement):
    """In-band barrier (ref: io/network/api/CheckpointBarrier.java).
    options: 'exactly_once' aligns channels; 'at_least_once' does not;
    savepoints carry a savepoint path."""

    __slots__ = ("checkpoint_id", "timestamp", "options")

    is_barrier = True

    def __init__(self, checkpoint_id: int, timestamp: int, options: Optional[dict] = None):
        self.checkpoint_id = checkpoint_id
        self.timestamp = timestamp
        self.options = options or {}

    def __repr__(self):
        return f"Barrier(#{self.checkpoint_id})"

    def __eq__(self, other):
        return (isinstance(other, CheckpointBarrier)
                and self.checkpoint_id == other.checkpoint_id)


class EndOfStream(StreamElement):
    """End-of-input sentinel propagated through operator chains (the
    reference signals this via channel close; an explicit element keeps
    the single-process runtime simple)."""

    __slots__ = ()

    def __repr__(self):
        return "EndOfStream"


END_OF_STREAM = EndOfStream()
