"""StreamElement model.

Re-designs flink-streaming-java/.../runtime/streamrecord/: the four
element kinds flowing through operator pipelines — records, watermarks,
stream status, latency markers — plus checkpoint barriers, which in the
reference travel the network data plane (io/network/api/
CheckpointBarrier.java) and here flow in-band through the same channel
abstraction.

Timestamps are int milliseconds (event time), matching the reference's
long-millis convention; MAX_WATERMARK flushes all event-time state at
end of input (ref: Watermark.MAX_WATERMARK).
"""

from __future__ import annotations

from typing import Any, Optional

MAX_TIMESTAMP = 2**63 - 1
MIN_TIMESTAMP = -(2**63)


class StreamElement:
    __slots__ = ()

    is_record = False
    is_watermark = False
    is_stream_status = False
    is_latency_marker = False
    is_barrier = False


class StreamRecord(StreamElement):
    """(ref: StreamRecord.java — value + optional timestamp)"""

    __slots__ = ("value", "timestamp")

    is_record = True

    def __init__(self, value: Any, timestamp: Optional[int] = None):
        self.value = value
        self.timestamp = timestamp

    @property
    def has_timestamp(self) -> bool:
        return self.timestamp is not None

    def replace(self, value, timestamp=None) -> "StreamRecord":
        return StreamRecord(value, timestamp if timestamp is not None else self.timestamp)

    def __repr__(self):
        return f"Record({self.value!r} @ {self.timestamp})"

    def __eq__(self, other):
        return (isinstance(other, StreamRecord) and self.value == other.value
                and self.timestamp == other.timestamp)

    def __hash__(self):
        return hash((self.value if not isinstance(self.value, (list, dict)) else id(self.value),
                     self.timestamp))


class Watermark(StreamElement):
    """Event-time progress marker (ref: Watermark.java): asserts no
    records with timestamp <= this will follow."""

    __slots__ = ("timestamp",)

    is_watermark = True

    def __init__(self, timestamp: int):
        self.timestamp = timestamp

    def __repr__(self):
        return f"Watermark({self.timestamp})"

    def __eq__(self, other):
        return isinstance(other, Watermark) and self.timestamp == other.timestamp

    def __hash__(self):
        return hash(("wm", self.timestamp))


MAX_WATERMARK = Watermark(MAX_TIMESTAMP)


class StreamStatus(StreamElement):
    """ACTIVE/IDLE channel status so idle inputs don't hold back the
    watermark (ref: StreamStatus.java)."""

    __slots__ = ("status",)

    is_stream_status = True

    ACTIVE = 0
    IDLE = 1

    def __init__(self, status: int):
        self.status = status

    @property
    def is_active(self) -> bool:
        return self.status == StreamStatus.ACTIVE

    def __repr__(self):
        return "StreamStatus(ACTIVE)" if self.is_active else "StreamStatus(IDLE)"

    def __eq__(self, other):
        return isinstance(other, StreamStatus) and self.status == other.status


ACTIVE_STATUS = StreamStatus(StreamStatus.ACTIVE)
IDLE_STATUS = StreamStatus(StreamStatus.IDLE)


class LatencyMarker(StreamElement):
    """Periodic source-emitted marker for latency histograms
    (ref: LatencyMarker.java:32)."""

    __slots__ = ("marked_time", "operator_id", "subtask_index")

    is_latency_marker = True

    def __init__(self, marked_time: int, operator_id: str, subtask_index: int):
        self.marked_time = marked_time
        self.operator_id = operator_id
        self.subtask_index = subtask_index

    def __repr__(self):
        return f"LatencyMarker({self.marked_time} from {self.operator_id}/{self.subtask_index})"


class CheckpointBarrier(StreamElement):
    """In-band barrier (ref: io/network/api/CheckpointBarrier.java).
    options: 'exactly_once' aligns channels; 'at_least_once' does not;
    savepoints carry a savepoint path."""

    __slots__ = ("checkpoint_id", "timestamp", "options")

    is_barrier = True

    def __init__(self, checkpoint_id: int, timestamp: int, options: Optional[dict] = None):
        self.checkpoint_id = checkpoint_id
        self.timestamp = timestamp
        self.options = options or {}

    def __repr__(self):
        return f"Barrier(#{self.checkpoint_id})"

    def __eq__(self, other):
        return (isinstance(other, CheckpointBarrier)
                and self.checkpoint_id == other.checkpoint_id)


class EndOfStream(StreamElement):
    """End-of-input sentinel propagated through operator chains (the
    reference signals this via channel close; an explicit element keeps
    the single-process runtime simple)."""

    __slots__ = ()

    def __repr__(self):
        return "EndOfStream"


END_OF_STREAM = EndOfStream()
