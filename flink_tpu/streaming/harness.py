"""Operator test harness.

Re-designs the reference's workhorse test infrastructure
(AbstractStreamOperatorTestHarness.java:90,
KeyedOneInputStreamOperatorTestHarness.java, TestProcessingTimeService):
host a single operator in a fake task environment, push records and
watermarks, advance fake processing time, snapshot/restore, and
inspect emitted elements — no cluster required (SURVEY.md §4.2).
Shipped in the main package (not tests/) so downstream users test
their own operators the same way.
"""

from __future__ import annotations

from typing import Any, List, Optional

from flink_tpu.core.functions import as_key_selector
from flink_tpu.core.keygroups import KeyGroupRange
from flink_tpu.state.loader import load_state_backend
from flink_tpu.state.operator_state import OperatorStateBackend
from flink_tpu.streaming.elements import StreamRecord, Watermark
from flink_tpu.streaming.operators import (
    CollectorOutput,
    StreamOperator,
    TwoInputStreamOperator,
)
from flink_tpu.streaming.timers import TestProcessingTimeService


class OneInputStreamOperatorTestHarness:
    def __init__(
        self,
        operator: StreamOperator,
        key_selector=None,
        state_backend: str = "heap",
        max_parallelism: int = 128,
        key_group_range: Optional[KeyGroupRange] = None,
    ):
        self.operator = operator
        self.output = CollectorOutput()
        self.processing_time_service = TestProcessingTimeService()
        self.max_parallelism = max_parallelism
        if key_group_range is None:
            key_group_range = KeyGroupRange(0, max_parallelism - 1)
        if key_selector is not None:
            key_selector = as_key_selector(key_selector)
            self.keyed_backend = load_state_backend(
                state_backend, key_group_range, max_parallelism)
        else:
            self.keyed_backend = None
        operator.setup(
            self.output,
            keyed_backend=self.keyed_backend,
            operator_state_backend=OperatorStateBackend(),
            processing_time_service=self.processing_time_service,
            key_selector=key_selector,
            max_parallelism=max_parallelism,
        )
        self._open = False

    # ---- lifecycle --------------------------------------------------
    def open(self) -> None:
        self.operator.open()
        self._open = True

    def close(self) -> None:
        if self._open:
            self.operator.close()
            self._open = False

    # ---- drive ------------------------------------------------------
    def process_element(self, value, timestamp: Optional[int] = None) -> None:
        record = value if isinstance(value, StreamRecord) else StreamRecord(value, timestamp)
        self.operator.set_key_context(record)
        self.operator.process_element(record)

    def process_batch(self, batch) -> None:
        """Feed a RecordBatch to the operator's columnar path."""
        self.operator.process_batch(batch)

    def process_watermark(self, timestamp) -> None:
        wm = timestamp if isinstance(timestamp, Watermark) else Watermark(timestamp)
        self.operator.process_watermark(wm)

    def set_processing_time(self, now: int) -> None:
        self.processing_time_service.set_current_time(now)

    # ---- snapshot / restore -----------------------------------------
    def snapshot(self) -> dict:
        return self.operator.snapshot_state()

    def initialize_state(self, snapshots) -> None:
        if isinstance(snapshots, dict):
            snapshots = [snapshots]
        self.operator.restore_state(snapshots)

    # ---- inspect ----------------------------------------------------
    def get_output(self) -> List[StreamRecord]:
        return self.output.records

    def extract_output_values(self) -> List[Any]:
        return [r.value for r in self.output.records]

    def get_side_output(self, tag) -> List[StreamRecord]:
        tag_id = tag.tag_id if hasattr(tag, "tag_id") else tag
        return self.output.side.get(tag_id, [])

    def get_watermarks(self) -> List[Watermark]:
        return self.output.watermarks

    def clear_output(self) -> None:
        self.output.records.clear()
        self.output.watermarks.clear()


KeyedOneInputStreamOperatorTestHarness = OneInputStreamOperatorTestHarness


class TwoInputStreamOperatorTestHarness(OneInputStreamOperatorTestHarness):
    def __init__(self, operator: TwoInputStreamOperator, key_selector1=None,
                 key_selector2=None, **kw):
        super().__init__(operator, key_selector=key_selector1, **kw)
        if key_selector2 is not None and hasattr(operator, "key_selector2"):
            operator.key_selector2 = as_key_selector(key_selector2)

    def process_element1(self, value, timestamp=None) -> None:
        record = value if isinstance(value, StreamRecord) else StreamRecord(value, timestamp)
        self.operator.set_key_context(record)
        self.operator.process_element1(record)

    def process_element2(self, value, timestamp=None) -> None:
        record = value if isinstance(value, StreamRecord) else StreamRecord(value, timestamp)
        if hasattr(self.operator, "set_key_context2"):
            self.operator.set_key_context2(record)
        self.operator.process_element2(record)

    def process_watermark1(self, timestamp) -> None:
        wm = timestamp if isinstance(timestamp, Watermark) else Watermark(timestamp)
        self.operator.process_watermark1(wm)

    def process_watermark2(self, timestamp) -> None:
        wm = timestamp if isinstance(timestamp, Watermark) else Watermark(timestamp)
        self.operator.process_watermark2(wm)
