"""Columnar (vectorized) execution tier: RecordBatch elements.

The reference executes one Java object per record through the operator
chain; its Table planner closes the per-record interpretation gap with
Janino codegen (codegen/CodeGenerator.scala).  A Python runtime cannot
codegen its way out of per-record overhead — the TPU-first equivalent
is COLUMNAR flow: a stream element may be a :class:`RecordBatch`
(numpy columns + a timestamp column), sources emit batches, and
eligible operators consume whole batches.  This is the same design
point as Flink's later Blink planner / Arrow-based vectorized
execution: per-element costs amortize over thousands of rows, and the
window engines receive ready numpy columns.

Used by the Table/SQL layer (flink_tpu/table/api.py lowers eligible
windowed GROUP BY plans onto :class:`ColumnarWindowOperator`) and
available directly via
``StreamExecutionEnvironment`` sources built from
:class:`ColumnarSource`.

Parallelism: RecordBatches cross forward edges whole; a keyBy edge at
parallelism > 1 goes through :class:`BatchKeyGroupSplitOperator` (one
hash pass + one mask per target subtask — the columnar keyBy
exchange).  Plans that don't fit the tier fall back to the
row-at-a-time path — same split the reference drew between codegen'd
and interpreted operators.  NOTE: re-lowering a columnar plan at a
DIFFERENT parallelism changes the topology shape, so checkpoints do
not carry across such a change (the runtime warns on restore).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from flink_tpu.ops.device_agg import DeviceAggregateFunction
from flink_tpu.streaming.elements import (  # noqa: F401 — RecordBatch
    RecordBatch,   # re-exported: the batch element moved to elements.py
    StreamRecord,  # when it became a first-class StreamElement
    Watermark,
)
from flink_tpu.streaming.operators import StreamOperator
from flink_tpu.streaming.sources import SinkFunction, SourceFunction

#: kill switch for the end-to-end batch pipeline (RecordBatch flowing
#: as stream elements through sources, operator chains, and the
#: netchannel consumer).  Off, vectorized sources emit per-row records
#: and remote bindings decode row-at-a-time — the boxed path the
#: differential tests and the bench A/B compare against.  The wire
#: CODEC has its own independent flag (netchannel.COLUMNAR_ENABLED).
PIPELINE_ENABLED = True


def columns_from_values(values: Sequence) -> Optional[Dict[str, np.ndarray]]:
    """Lower a list of row values onto the pipeline column convention
    ("v" for scalar rows, "f0".."fk" for tuple rows) — or None when the
    values don't fit a column shape (heterogeneous types, bools, ints
    beyond int64, nested tuples...).  Mirrors the netchannel codec's
    strict type tiers so a batch born here round-trips the wire
    columnar."""
    if not values:
        return None
    v0 = values[0]
    if type(v0) is tuple:
        arity = len(v0)
        if arity == 0 or any(type(v) is not tuple or len(v) != arity
                             for v in values):
            return None
        cols = {}
        for i in range(arity):
            col = _column_from_cells([v[i] for v in values])
            if col is None:
                return None
            cols[f"f{i}"] = col
        return cols
    col = _column_from_cells(values)
    if col is None:
        return None
    return {"v": col}


def _column_from_cells(cells: list) -> Optional[np.ndarray]:
    """One homogeneous cell list → ndarray, or None.  `bool` is a
    subclass of int and floats don't survive an int64 cast, hence the
    exact `type is` checks (same discipline as the wire codec)."""
    t = type(cells[0])
    if any(type(c) is not t for c in cells):
        return None
    if t is int:
        try:
            return np.array(cells, np.int64)
        except OverflowError:
            return None
    if t is float:
        return np.array(cells, np.float64)
    if t is str:
        arr = np.empty(len(cells), object)
        arr[:] = cells
        return arr
    return None


def batch_from_records(values: Sequence, timestamps: Optional[Sequence]
                       ) -> Optional[RecordBatch]:
    """Values + per-row Optional[int] timestamps → RecordBatch (with a
    validity mask when timestamps are mixed None/int), or None when the
    values don't columnarize."""
    cols = columns_from_values(values)
    if cols is None:
        return None
    if timestamps is None or all(t is None for t in timestamps):
        return RecordBatch(cols)
    if any(t is None for t in timestamps):
        mask = np.array([t is not None for t in timestamps], bool)
        stamps = np.array([t if t is not None else 0
                           for t in timestamps], np.int64)
        return RecordBatch(cols, stamps, mask)
    return RecordBatch(cols, np.array(list(timestamps), np.int64))


def batch_from_arrays(arrays, ts=None, ts_mask=None) -> RecordBatch:
    """Build a pipeline-convention batch from ready numpy columns: one
    array → scalar rows ("v"), a tuple/list of arrays → tuple rows
    ("f0".."fk")."""
    if isinstance(arrays, (tuple, list)):
        return RecordBatch(
            {f"f{i}": np.asarray(a) for i, a in enumerate(arrays)},
            ts, ts_mask)
    return RecordBatch({"v": np.asarray(arrays)}, ts, ts_mask)


class VectorizedCollectionSource(SourceFunction):
    """Bounded source over a Python collection that emits RecordBatch
    elements (columns built ONCE at construction) — the vectorized
    twin of FromCollectionSource, so a batch is *born* columnar
    instead of being re-derived per hop.  Values that don't fit the
    column convention raise at construction: callers fall back to
    FromCollectionSource (datastream.from_collection does this
    automatically when `vectorize=True` fails).

    With ``timestamped=True`` the input is (value, ts) pairs, same as
    FromCollectionSource.  Implements the cooperative emit_step +
    offset-checkpoint contract; one step emits ONE batch (the batch is
    the indivisible element)."""

    #: eligibility marker read by analysis.columnar_eligibility
    emits_batches = True

    def __init__(self, values: Sequence, timestamped: bool = False,
                 chunk: int = 16384):
        values = list(values)
        self.timestamped = timestamped
        self.chunk = chunk
        if timestamped:
            raw = [v for v, _ in values]
            ts = [t for _, t in values]
        else:
            raw, ts = values, None
        batch = batch_from_records(raw, ts)
        if batch is None and values:
            raise TypeError(
                "collection does not fit the columnar convention "
                "(heterogeneous / non-scalar rows) — use "
                "FromCollectionSource")
        #: the whole input as one master batch; emit_step slices it
        self._batch = batch
        self._n = len(values)
        self._running = True
        #: resume offset in ROWS (always a chunk boundary)
        self.offset = 0

    def run(self, ctx) -> None:
        while self.emit_step(ctx, self.chunk):
            pass

    def emit_step(self, ctx, max_records: int) -> bool:
        if self.offset < self._n and self._running:
            if not PIPELINE_ENABLED:
                # boxed A/B path: same rows, per-record records
                end = min(self.offset + self.chunk, self._n)
                sl = self._batch.take(slice(self.offset, end))
                self.offset = end
                if self.timestamped:
                    for v, t in zip(sl.row_values(), sl.timestamps()):
                        ctx.collect_with_timestamp(v, t)
                else:
                    for v in sl.row_values():
                        ctx.collect(v)
            else:
                end = min(self.offset + self.chunk, self._n)
                ctx.collect_batch(
                    self._batch.take(slice(self.offset, end)))
                self.offset = end
        return self.offset < self._n and self._running

    def cancel(self) -> None:
        self._running = False

    def __deepcopy__(self, memo):
        # batches are immutable — a clone only needs a fresh cursor
        clone = type(self).__new__(type(self))
        clone.__dict__.update(self.__dict__)
        clone._running = True
        return clone

    def snapshot_function_state(self, checkpoint_id=None) -> dict:
        return {"offset": self.offset}

    def restore_function_state(self, state: dict) -> None:
        self.offset = state["offset"]


class ColumnarSource(SourceFunction):
    """Bounded source over column arrays; emits RecordBatch chunks and
    a watermark after each chunk (input must be time-sorted on the
    rowtime column, the usual replayed-log shape).

    Implements the cooperative-stepping + offset-checkpoint contract
    (same as FromCollectionSource): snapshots at step boundaries see
    only fully-emitted batches, so recovery resumes exactly-once."""

    def __init__(self, cols: Dict[str, np.ndarray], rowtime: str,
                 chunk: int = 1 << 19, ooo_slack_ms: int = 0):
        self.cols = {k: np.asarray(v) for k, v in cols.items()}
        self.cols[rowtime] = np.asarray(self.cols[rowtime], np.int64)
        self.rowtime = rowtime
        self.chunk = chunk
        self.ooo_slack_ms = ooo_slack_ms
        self._running = True
        #: resume offset in ROWS (always a chunk boundary)
        self.offset = 0
        self._final_watermark = True

    def run(self, ctx) -> None:
        while self.emit_step(ctx, self.chunk):
            pass

    def emit_step(self, ctx, max_records: int) -> bool:
        """One cooperative step = ONE RecordBatch (`max_records` counts
        stream ELEMENTS, same per-element accounting as
        FromCollectionSource; a batch is the indivisible element here —
        slicing it to max_records rows would cap every batch at the
        executor's step size and destroy the columnar amortization)."""
        from flink_tpu.streaming.elements import MAX_WATERMARK
        ts_all = self.cols[self.rowtime]
        n = len(ts_all)
        if self.offset < n and self._running:
            sl = slice(self.offset, self.offset + self.chunk)
            batch = RecordBatch({k: v[sl] for k, v in self.cols.items()},
                                ts_all[sl])
            ctx.collect(batch)
            self.offset = min(self.offset + self.chunk, n)
            ctx.emit_watermark(Watermark(
                int(ts_all[self.offset - 1]) - self.ooo_slack_ms - 1))
        if self.offset < n and self._running:
            return True
        if self._final_watermark:
            ctx.emit_watermark(MAX_WATERMARK)
            self._final_watermark = False
        return False

    def cancel(self) -> None:
        self._running = False

    def __deepcopy__(self, memo):
        # per-attempt source cloning must not copy the input columns
        # (the source only ever slices them — views, no mutation); a
        # fresh cursor is all a clone needs.  type(self), not
        # ColumnarSource: a subclass (e.g. a test's gated source) must
        # survive the per-attempt clone
        clone = type(self).__new__(type(self))
        clone.__dict__.update(self.__dict__)
        clone._running = True
        return clone

    # checkpoint hooks (CheckpointedFunction-shaped source state)
    def snapshot_function_state(self, checkpoint_id=None) -> dict:
        return {"offset": self.offset,
                "final_watermark": self._final_watermark}

    def restore_function_state(self, state: dict) -> None:
        self.offset = state["offset"]
        self._final_watermark = state["final_watermark"]


class ColumnarCollectSink(SinkFunction):
    """Collects fired RecordBatches; row-style access for asserts."""

    def __init__(self):
        self.batches: List[RecordBatch] = []

    def invoke(self, value, context=None):
        self.batches.append(value)

    def total_rows(self) -> int:
        return sum(len(b) for b in self.batches)

    def rows(self):
        for b in self.batches:
            yield from b.rows()


class _ExplodeBatches(StreamOperator):
    """RecordBatch → per-row StreamRecords (field order = column
    order), each carrying its row's event timestamp.  The bridge from
    the columnar tier back to the row-at-a-time operators when a plan
    leaves the columnar shape."""

    def process_element(self, record: StreamRecord):
        batch: RecordBatch = record.value
        lists = [c.tolist() for c in batch.cols.values()]
        ts_list = (batch.ts.tolist() if batch.ts is not None
                   else [record.timestamp] * len(batch))
        out = self.output
        for ts, row in zip(ts_list, zip(*lists)):
            out.collect(StreamRecord(row, ts))


def explode_to_rows(stream):
    """Wrap a RecordBatch stream with the row-explode operator."""
    return stream._add_op("explode_batches", _ExplodeBatches)


class ColumnarWindowOperator(StreamOperator):
    """keyBy().window().aggregate(device_agg) over RecordBatch input.

    The columnar twin of DeviceWindowOperator: batches feed the engine
    directly (no per-record objects), fires leave as RecordBatches.
    Engine tier selection: the log-structured combiner engines
    (streaming/log_windows.py) when the aggregate has a cell
    decomposition and keys are integral; else the device-resident
    vectorized engines.

    out_fields maps each output column name to one of
    ("key", "agg", "wstart", "wend").
    """

    def __init__(self, assigner, agg: DeviceAggregateFunction,
                 key_col: str, input_col: Optional[str],
                 out_fields: Sequence[tuple],
                 initial_capacity: int = 1 << 14,
                 mesh=None, mesh_axis: str = "kg"):
        super().__init__()
        self.assigner = assigner
        self.agg = agg
        self.key_col = key_col
        self.input_col = input_col
        self.out_fields = list(out_fields)
        self.initial_capacity = initial_capacity
        #: with a mesh, the keyBy exchange is lax.all_to_all over the
        #: mesh axis and the aggregation shards over per-shard log
        #: engines (parallel/mesh_log.py) — the plan then stays at
        #: parallelism 1 and the mesh provides the scale axis
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        self.engine = None
        self.num_late_records_dropped = 0

    # ---- engine selection -------------------------------------------
    def _make_engine(self, key_dtype, require_log: bool = False) -> Any:
        """require_log: restoring a log-tier checkpoint — a silent
        fallback to the vectorized tier would feed it an incompatible
        snapshot format, so failures must surface."""
        from flink_tpu.streaming.device_window_operator import (
            engine_for_assigner,
            log_engine_for_assigner,
        )
        if require_log:
            from flink_tpu.streaming import log_windows as lw
            eng = log_engine_for_assigner(self.assigner, self.agg)
            if eng is None:
                raise RuntimeError(
                    "checkpoint was taken on the log engine tier, which "
                    "is unavailable here (native runtime / eligible "
                    "aggregate required)")
            return eng
        eng = None
        if self.mesh is not None and np.issubdtype(key_dtype, np.integer):
            from flink_tpu.parallel.mesh_log import (
                mesh_log_engine_for_assigner,
            )
            from flink_tpu.streaming.device_window_operator import (
                resolve_mesh,
            )
            # factory resolution stays INSIDE the integer-key branch:
            # non-mesh-eligible jobs must not pay a device/client init
            self.mesh = resolve_mesh(self.mesh)
            eng = mesh_log_engine_for_assigner(
                self.assigner, self.agg, self.mesh, axis=self.mesh_axis,
                max_parallelism=self.max_parallelism)
            if eng is not None:
                return eng
        if key_dtype.kind in "US":
            eng = self._string_engine()
            if eng is not None:
                return eng
        if np.issubdtype(key_dtype, np.integer):
            eng = log_engine_for_assigner(self.assigner, self.agg)
        if eng is None:
            eng = engine_for_assigner(self.assigner, self.agg,
                                      self.initial_capacity)
        if eng is None:
            raise ValueError(f"no engine for assigner {self.assigner!r}")
        return eng

    def _string_engine(self):
        """Fused wordcount engine for a STRING key column (tumbling
        float sum — the SQL wordcount shape); None when the shape or
        native runtime doesn't fit."""
        from flink_tpu.streaming.device_window_operator import (
            string_sum_engine_for_assigner,
        )
        return string_sum_engine_for_assigner(self.assigner, self.agg)

    def open(self):
        pass  # engine built on first batch (needs the key dtype)

    def set_key_context(self, record):
        pass

    # ---- input ------------------------------------------------------
    def process_element(self, record: StreamRecord):
        batch = record.value
        if isinstance(batch, tuple):
            # (target, sub_batch) carrier from the key-group split
            # exchange (parallelism > 1)
            batch = batch[1]
        if len(batch) == 0:
            return
        keys = batch.cols[self.key_col]
        if self.engine is None:
            self.engine = self._make_engine(np.asarray(keys).dtype)
            # engines without batch-fire support deliver via .emitted
            if hasattr(self.engine, "fired"):
                self.engine.emit_arrays = True
            # fast-forward to the operator watermark: rows behind it
            # must count as late, not fire into closed windows
            wm = getattr(self, "current_watermark", None)
            if wm is not None and wm > -(2 ** 63):
                self.engine.advance_watermark(wm)
        values = None
        value_hashes = None
        if self.input_col is not None:
            col = batch.cols[self.input_col]
            if self.agg.needs_value_hash:
                from flink_tpu.streaming.vectorized import hash_keys_np
                value_hashes = hash_keys_np(np.asarray(col))
            if self.agg.needs_value:
                values = np.asarray(col)
        self.engine.process_batch(keys, batch.ts, values,
                                  value_hashes=value_hashes)

    def process_watermark(self, watermark: Watermark):
        if self.engine is not None:
            getattr(self.engine, "flush", lambda: None)()
            self.engine.advance_watermark(watermark.timestamp)
            if getattr(self.engine, "emit_arrays", False):
                self._emit_fired()
            else:
                self._emit_rows()
            self.num_late_records_dropped = self.engine.num_late_dropped
        self.current_watermark = watermark.timestamp
        self.output.emit_watermark(watermark)

    def _emit_rows(self):
        """Row-delivering engines (e.g. VectorizedSessionWindows):
        batch their .emitted tuples into one output RecordBatch."""
        emitted = self.engine.emitted
        if not emitted:
            return
        keys_np = np.asarray([e[0] for e in emitted])
        results = np.asarray([e[1] for e in emitted])
        starts = np.asarray([e[2] for e in emitted], np.int64)
        ends = np.asarray([e[3] for e in emitted], np.int64)
        del emitted[:]
        cols = {}
        for name, kind in self.out_fields:
            cols[name] = {"key": keys_np, "agg": results,
                          "wstart": starts, "wend": ends}[kind]
        out = RecordBatch(cols, ends - 1)
        self.output.collect(StreamRecord(out, timestamp=int(ends.max()) - 1))

    def _emit_fired(self):
        fired = self.engine.fired
        for entry in fired:
            keys_np, results, start, end = entry
            if isinstance(start, np.ndarray):
                # session engines fire (keys, totals, starts, ends)
                starts, ends = start, end
                out_ts = int(ends.max()) - 1 if len(ends) else 0
            else:
                starts = np.full(len(keys_np), start, np.int64)
                ends = np.full(len(keys_np), end, np.int64)
                out_ts = end - 1
            cols = {}
            for name, kind in self.out_fields:
                if kind == "key":
                    cols[name] = keys_np
                elif kind == "agg":
                    cols[name] = results
                elif kind == "wstart":
                    cols[name] = starts
                else:
                    cols[name] = ends
            out = RecordBatch(cols, ends - 1)
            self.output.collect(StreamRecord(out, timestamp=out_ts))
        del fired[:]

    # ---- checkpoint -------------------------------------------------
    def snapshot_state(self, checkpoint_id: Optional[int] = None) -> dict:
        snap = super().snapshot_state(checkpoint_id)
        if self.engine is not None:
            snap["columnar_engine"] = self.engine.snapshot()
            from flink_tpu.parallel.mesh_log import _MeshShardedLogEngine
            from flink_tpu.streaming import log_windows as lw
            if isinstance(self.engine, lw.StringSumTumblingWindows):
                snap["columnar_tier"] = "string_sum"
            elif isinstance(self.engine, _MeshShardedLogEngine):
                snap["columnar_tier"] = "mesh_log"
            elif isinstance(self.engine, (lw.LogStructuredTumblingWindows,
                                          lw.LogStructuredSessionWindows)):
                snap["columnar_tier"] = "log"
            else:
                snap["columnar_tier"] = "vectorized"
        return snap

    def _kg_keep_fn(self):
        """Key-group-range filter for rescaled restores (the shared
        definition, so re-split state lands exactly where the split
        exchange routes live records)."""
        from flink_tpu.core.keygroups import make_key_group_keep_fn
        return make_key_group_keep_fn(self.max_parallelism,
                                      self.num_subtasks,
                                      self.subtask_index)

    def _build_engine_for_tier(self, tier):
        if tier == "string_sum":
            eng = self._string_engine()
            if eng is None:
                raise RuntimeError(
                    "checkpoint was taken on the fused string-sum "
                    "tier, unavailable here")
            return eng
        if tier == "mesh_log":
            from flink_tpu.parallel.mesh_log import (
                mesh_log_engine_for_assigner,
            )
            from flink_tpu.streaming.device_window_operator import (
                resolve_mesh,
            )
            self.mesh = resolve_mesh(self.mesh)
            if self.mesh is None:
                raise RuntimeError(
                    "checkpoint was taken on the mesh log tier; "
                    "restoring requires a mesh (env.set_mesh)")
            eng = mesh_log_engine_for_assigner(
                self.assigner, self.agg, self.mesh,
                axis=self.mesh_axis,
                max_parallelism=self.max_parallelism)
            if eng is None:
                raise RuntimeError(
                    "checkpoint was taken on the mesh log tier, which "
                    "is unavailable here (native runtime required)")
            return eng
        is_log = tier == "log"
        key_dtype = (np.dtype(np.uint64) if is_log
                     else np.dtype(object))
        return self._make_engine(key_dtype, require_log=is_log)

    def restore_state(self, snapshots) -> None:
        super().restore_state(snapshots)
        engine_snaps = [s for s in snapshots if "columnar_engine" in s]
        if not engine_snaps:
            return
        tiers = {s.get("columnar_tier") for s in engine_snaps}
        if len(tiers) > 1:
            raise ValueError(
                f"snapshots span engine tiers {sorted(tiers)}; cannot "
                "merge across tiers")
        tier = tiers.pop()
        rescaled = any(
            s.get("restore_old_parallelism", self.num_subtasks)
            != self.num_subtasks for s in engine_snaps)
        if self.engine is None:
            self.engine = self._build_engine_for_tier(tier)
            if hasattr(self.engine, "fired"):
                self.engine.emit_arrays = True
        if not rescaled and len(engine_snaps) == 1:
            self.engine.restore(engine_snaps[0]["columnar_engine"])
            return
        # parallelism changed: merge the old subtasks' engine states
        # and keep only this subtask's key groups (ref:
        # StateAssignmentOperation key-group re-split)
        if not hasattr(self.engine, "restore_many"):
            raise ValueError(
                f"the {tier!r} engine tier cannot re-split its state "
                "across a parallelism change; restore at the "
                "checkpointed parallelism")
        self.engine.restore_many(
            [s["columnar_engine"] for s in engine_snaps],
            keep_fn=self._kg_keep_fn())


class BatchKeyGroupSplitOperator(StreamOperator):
    """The keyBy exchange for RecordBatch flow at parallelism > 1:
    splits each batch by key-group-derived target subtask (the same
    range-partition arithmetic as KeyGroupRangeAssignment, computed
    vectorized in C++ — nat.key_groups), emitting (target, sub_batch)
    carriers the downstream custom partitioner routes by tag.  The
    columnar answer to the reference's per-record hash partitioner:
    one hash pass and one mask per target instead of a channel choice
    per record (round-2 verdict item 7)."""

    def __init__(self, key_col: str, max_parallelism: int, n_out: int):
        super().__init__()
        if n_out < 2:
            raise ValueError("the split exchange exists only for "
                             "parallelism > 1")
        self.key_col = key_col
        self.max_parallelism = max_parallelism
        self.n_out = n_out

    def set_key_context(self, record):
        pass

    def process_element(self, record: StreamRecord):
        batch: RecordBatch = record.value
        if len(batch) == 0:
            return
        from flink_tpu.streaming.vectorized import hash_keys_np
        kh = hash_keys_np(np.asarray(batch.cols[self.key_col]))
        try:
            import flink_tpu.native as nat
            targets = nat.key_groups(kh, self.max_parallelism,
                                     self.n_out)
        except Exception:  # noqa: BLE001 — numpy twin of ft_key_groups
            from flink_tpu.core.keygroups import (
                assign_operator_indexes_np,
            )
            targets = assign_operator_indexes_np(
                kh, self.max_parallelism, self.n_out)
        ts = np.asarray(batch.ts, np.int64) if batch.ts is not None \
            else None
        for t in range(self.n_out):
            m = targets == t
            if not m.any():
                continue
            sub = RecordBatch({k: np.asarray(v)[m]
                               for k, v in batch.cols.items()},
                              None if ts is None else ts[m])
            self.output.collect(StreamRecord((int(t), sub),
                                             record.timestamp))


class ColumnarIntervalJoinOperator(StreamOperator):
    """Vectorized stream-stream interval join over RecordBatch inputs
    (the columnar twin of the row-level interval join,
    flink_tpu/streaming/joining.py; ref role:
    DataStreamWindowJoin.scala's time-bounded join).

    Input elements are (tag, RecordBatch) carriers from the tagged
    union (0 = left, 1 = right).  Each side keeps a columnar buffer;
    an incoming batch probes the OTHER side's buffer with one
    vectorized hash-join pass:

      sort the buffer by 64-bit key hash (cached until the buffer
      changes) -> searchsorted the batch's hashes for candidate group
      ranges -> expand ranges with repeat/cumsum arithmetic -> filter
      by the time bound r.ts - l.ts in [lower, upper] AND exact key
      equality (hash-collision safe) -> gather the joined RecordBatch.

    Buffers prune by watermark (left rows die once wm >= ts + upper,
    right rows once wm >= ts - lower).  Single-parallelism, like the
    rest of the columnar tier."""

    def __init__(self, key_l: str, key_r: str, lower_ms: int,
                 upper_ms: int, out_fields_l, out_fields_r):
        super().__init__()
        self.key_l = key_l
        self.key_r = key_r
        self.lower = lower_ms
        self.upper = upper_ms
        #: [(out_name, src_col)] per side
        self.out_l = list(out_fields_l)
        self.out_r = list(out_fields_r)
        self._buf = [self._empty(), self._empty()]
        self.current_watermark = -(2 ** 63)
        # native fast path: the batched C++ join core probes per-key
        # time-sorted buffers with phase-split slot resolution; the
        # operator keeps append-only column storage per side and
        # gathers emitted pairs by global row id.  (Row-id addressed
        # storage is append-only; bounded inputs / replayed logs.)
        self._native = None
        self._store = None
        try:
            import flink_tpu.native as nat
            if nat.available():
                self._native = nat.NativeIntervalJoin(lower_ms, upper_ms)
                self._store = [self._new_store(), self._new_store()]
        except Exception:  # noqa: BLE001 — numpy path below
            self._native = None

    @staticmethod
    def _new_store():
        return {"cols": {}, "ts": None, "kh": None, "n": 0, "cap": 0}

    def _store_append(self, side: int, batch: RecordBatch,
                      kh: np.ndarray):
        st = self._store[side]
        n_new = len(batch)
        need = st["n"] + n_new
        if need > st["cap"]:
            cap = max(1 << 16, 1 << int(need - 1).bit_length())
            for name in batch.cols:
                old = st["cols"].get(name)
                arr = np.empty(cap, np.asarray(batch.cols[name]).dtype)
                if old is not None:
                    arr[:st["n"]] = old[:st["n"]]
                st["cols"][name] = arr
            for key in ("ts", "kh"):
                old = st[key]
                arr = np.empty(cap, np.int64 if key == "ts"
                               else np.uint64)
                if old is not None:
                    arr[:st["n"]] = old[:st["n"]]
                st[key] = arr
            st["cap"] = cap
        for name, col in batch.cols.items():
            st["cols"][name][st["n"]:need] = np.asarray(col)
        st["ts"][st["n"]:need] = np.asarray(batch.ts, np.int64)
        st["kh"][st["n"]:need] = kh
        st["n"] = need

    @staticmethod
    def _empty():
        return {"cols": None, "ts": None, "kh": None,
                "order": None, "sorted_kh": None}

    def set_key_context(self, record):
        pass

    def _hash(self, col: np.ndarray) -> np.ndarray:
        # hash_keys_np routes integral arrays through the native
        # splitmix64 itself
        from flink_tpu.streaming.vectorized import hash_keys_np
        return hash_keys_np(np.asarray(col))

    def _append(self, side: int, batch: RecordBatch, kh: np.ndarray):
        b = self._buf[side]
        if b["cols"] is None:
            b["cols"] = {k: np.asarray(v) for k, v in batch.cols.items()}
            b["ts"] = np.asarray(batch.ts, np.int64)
            b["kh"] = kh
        else:
            b["cols"] = {k: np.concatenate([b["cols"][k], batch.cols[k]])
                         for k in b["cols"]}
            b["ts"] = np.concatenate([b["ts"],
                                      np.asarray(batch.ts, np.int64)])
            b["kh"] = np.concatenate([b["kh"], kh])
        b["order"] = None  # sort cache dirtied

    def _sorted(self, side: int):
        # NOTE: correctness fallback only (no native runtime): every
        # append dirties the cache, so each probing batch re-argsorts
        # the (watermark-pruned) buffer — O(B log B) per batch.  The
        # native core is the performance path (counting-sorted batch,
        # monotone two-pointer probes).
        b = self._buf[side]
        if b["order"] is None and b["kh"] is not None:
            b["order"] = np.argsort(b["kh"], kind="stable")
            b["sorted_kh"] = b["kh"][b["order"]]
        return b

    def process_element(self, record: StreamRecord):
        tag, batch = record.value
        if len(batch) == 0:
            return
        key_col = self.key_l if tag == 0 else self.key_r
        kh = self._hash(batch.cols[key_col])
        if self._native is not None:
            self._store_append(tag, batch, kh)
            lrows, rrows = self._native.push(
                tag, kh, np.asarray(batch.ts, np.int64))
            if len(lrows):
                sl, sr = self._store[0], self._store[1]
                # exact key equality: the native core joins on 64-bit
                # hashes.  INTEGER keys hash via splitmix64 of their
                # 64-bit pattern — a BIJECTION, so collisions are
                # impossible and the recheck is skipped.  The two
                # sides must share signedness (a negative's bit
                # pattern aliases a huge unsigned); strings and
                # composites hash lossily and always verify.
                lkd = sl["cols"][self.key_l].dtype
                rkd = sr["cols"][self.key_r].dtype
                int_keys = lkd.kind == rkd.kind and lkd.kind in "iu"
                if not int_keys:
                    eq = (sl["cols"][self.key_l][lrows]
                          == sr["cols"][self.key_r][rrows])
                    if not eq.all():
                        lrows, rrows = lrows[eq], rrows[eq]
                        if not len(lrows):
                            return
                l_cols = {n: sl["cols"][c][lrows] for n, c in self.out_l}
                r_cols = {n: sr["cols"][c][rrows] for n, c in self.out_r}
                out_ts = np.maximum(sl["ts"][lrows], sr["ts"][rrows])
                out = RecordBatch({**l_cols, **r_cols}, out_ts)
                self.output.collect(
                    StreamRecord(out, timestamp=int(out_ts.max())))
            return
        self._append(tag, batch, kh)
        other = self._sorted(1 - tag)
        if other["cols"] is None or not len(other["kh"]):
            return
        starts = np.searchsorted(other["sorted_kh"], kh, "left")
        ends = np.searchsorted(other["sorted_kh"], kh, "right")
        counts = ends - starts
        total = int(counts.sum())
        if total == 0:
            return
        mine = np.repeat(np.arange(len(kh)), counts)
        cum0 = np.concatenate([[0], np.cumsum(counts)[:-1]])
        offs = np.arange(total) - np.repeat(cum0, counts)
        theirs = other["order"][np.repeat(starts, counts) + offs]
        ts_mine = np.asarray(batch.ts, np.int64)[mine]
        ts_other = other["ts"][theirs]
        if tag == 0:
            d = ts_other - ts_mine          # r.ts - l.ts
        else:
            d = ts_mine - ts_other
        ok = (d >= self.lower) & (d <= self.upper)
        # exact key equality (64-bit hash ties broken by content)
        okey = self.key_l if tag == 1 else self.key_r
        ok &= (np.asarray(batch.cols[key_col])[mine]
               == other["cols"][okey][theirs])
        if not ok.any():
            return
        mine, theirs = mine[ok], theirs[ok]
        if tag == 0:
            l_cols = {n: np.asarray(batch.cols[c])[mine]
                      for n, c in self.out_l}
            r_cols = {n: other["cols"][c][theirs] for n, c in self.out_r}
            out_ts = np.maximum(ts_mine[ok], ts_other[ok])
        else:
            l_cols = {n: other["cols"][c][theirs] for n, c in self.out_l}
            r_cols = {n: np.asarray(batch.cols[c])[mine]
                      for n, c in self.out_r}
            out_ts = np.maximum(ts_other[ok], ts_mine[ok])
        out = RecordBatch({**l_cols, **r_cols}, out_ts)
        self.output.collect(StreamRecord(out, timestamp=int(out_ts.max())))

    def process_watermark(self, watermark: Watermark):
        wm = watermark.timestamp
        self.current_watermark = wm
        if self._native is not None:
            self._native.prune(wm)
            self.output.emit_watermark(watermark)
            return
        for side, horizon in ((0, self.upper), (1, -self.lower)):
            b = self._buf[side]
            if b["cols"] is None:
                continue
            keep = b["ts"] + horizon > wm
            if not keep.all():
                b["cols"] = {k: v[keep] for k, v in b["cols"].items()}
                b["ts"] = b["ts"][keep]
                b["kh"] = b["kh"][keep]
                b["order"] = None
        self.output.emit_watermark(watermark)

    # checkpoint: the buffers ARE the operator state
    def snapshot_state(self, checkpoint_id=None) -> dict:
        snap = super().snapshot_state(checkpoint_id)
        if self._native is not None:
            snap["iv_join_store"] = [
                {"cols": {k: v[:s["n"]].copy()
                          for k, v in s["cols"].items()},
                 "ts": s["ts"][:s["n"]].copy() if s["ts"] is not None
                 else np.empty(0, np.int64),
                 "kh": s["kh"][:s["n"]].copy() if s["kh"] is not None
                 else np.empty(0, np.uint64)}
                for s in self._store]
            snap["iv_join_watermark"] = self.current_watermark
            return snap
        snap["iv_join_buffers"] = [
            None if b["cols"] is None else
            {"cols": {k: v.copy() for k, v in b["cols"].items()},
             "ts": b["ts"].copy(), "kh": b["kh"].copy()}
            for b in self._buf]
        return snap

    def restore_state(self, snapshots) -> None:
        super().restore_state(snapshots)
        for s in snapshots:
            if "iv_join_store" in s:
                import flink_tpu.native as nat
                if not nat.available():
                    # native-format snapshot on a host without the
                    # library: rebuild the numpy buffers instead
                    self._native = None
                    self._buf = []
                    for st in s["iv_join_store"]:
                        nb = self._empty()
                        if len(st["ts"]):
                            nb["cols"] = {k: np.asarray(v) for k, v
                                          in st["cols"].items()}
                            nb["ts"] = np.asarray(st["ts"], np.int64)
                            nb["kh"] = np.asarray(st["kh"], np.uint64)
                        self._buf.append(nb)
                    continue
                self._native = nat.NativeIntervalJoin(self.lower,
                                                      self.upper)
                self._store = [self._new_store(), self._new_store()]
                # replay each side into the core — pairs produced by
                # the replay were all emitted before the checkpoint
                # barrier, so they are DROPPED (push drains them;
                # left replays first, probing an empty right buffer)
                for side, st in enumerate(s["iv_join_store"]):
                    ts = np.asarray(st["ts"], np.int64)
                    kh = np.asarray(st["kh"], np.uint64)
                    if len(ts):
                        self._store_append(
                            side,
                            RecordBatch(dict(st["cols"]), ts), kh)
                        self._native.push(side, kh, ts)
                wm = s.get("iv_join_watermark")
                if wm is not None and wm > -(2 ** 63):
                    self.current_watermark = wm
                    self._native.prune(wm)
                continue
            if "iv_join_buffers" in s:
                # numpy-format snapshot: the restored rows live in the
                # numpy buffers, so the numpy path must serve them
                # even when this host could build the native core
                self._native = None
                self._buf = []
                for b in s["iv_join_buffers"]:
                    nb = self._empty()
                    if b is not None:
                        nb["cols"] = {k: np.asarray(v)
                                      for k, v in b["cols"].items()}
                        nb["ts"] = np.asarray(b["ts"], np.int64)
                        nb["kh"] = np.asarray(b["kh"], np.uint64)
                    self._buf.append(nb)
