"""Two-phase-commit sink: exactly-once output tied to checkpoints.

Re-designs flink-streaming-java/.../api/functions/sink/
TwoPhaseCommitSinkFunction.java:73.  Protocol (doc comment there):

- every incoming value is written into the CURRENT transaction;
- on snapshot (the barrier reaching the sink) the current transaction
  is PRE-COMMITTED (flushed, made durable but not visible), parked on
  the pending list tagged with the checkpoint id, and a fresh
  transaction begins — all atomically with the operator snapshot;
- when the checkpoint COMPLETES (notifyCheckpointComplete), pending
  transactions for that checkpoint (and older) are COMMITTED;
- on restore, pending transactions from the restored checkpoint are
  recover-and-committed (the checkpoint completed — we are restoring
  from it), and the transaction that was open at snapshot time is
  recover-and-aborted (its data lies after the barrier and will be
  replayed).

Commits MUST be idempotent: a failure after commit but before the next
checkpoint replays the commit on recovery (same contract as the
reference — Kafka transactional ids, file renames, etc.).
"""

from __future__ import annotations

import abc
from typing import Any, List, Optional, Tuple

from flink_tpu.core.functions import RichFunction
from flink_tpu.streaming.sources import SinkFunction


class TwoPhaseCommitSinkFunction(SinkFunction, RichFunction, abc.ABC):
    """(ref: TwoPhaseCommitSinkFunction.java:73)"""

    def __init__(self):
        RichFunction.__init__(self)
        self._current_txn: Any = None
        #: (checkpoint_id, transaction) awaiting notifyCheckpointComplete
        self._pending_commit: List[Tuple[Optional[int], Any]] = []

    # ---- user SPI ---------------------------------------------------
    @abc.abstractmethod
    def begin_transaction(self) -> Any: ...

    @abc.abstractmethod
    def invoke_in_transaction(self, transaction, value, context) -> None: ...

    @abc.abstractmethod
    def pre_commit(self, transaction) -> None: ...

    @abc.abstractmethod
    def commit(self, transaction) -> None: ...

    @abc.abstractmethod
    def abort(self, transaction) -> None: ...

    def recover_and_commit(self, transaction) -> None:
        """Commit a pre-committed transaction found in restored state
        (default: plain commit — override if recovery needs e.g.
        resuming an external transaction by id)."""
        self.commit(transaction)

    def recover_and_abort(self, transaction) -> None:
        self.abort(transaction)

    # ---- lifecycle --------------------------------------------------
    def open(self, configuration):
        """Abort any leftover transactions from a previous attempt —
        the function instance is shared across restarts, and without a
        restore (no completed checkpoint yet) attempt N+1 would
        otherwise replay into attempt N's buffers and double-commit.
        Pre-committed-but-uncheckpointed transactions roll back on
        recovery, same as the reference."""
        if self._current_txn is not None:
            self.abort(self._current_txn)
        for _cid, txn in self._pending_commit:
            self.abort(txn)
        self._pending_commit = []
        self._current_txn = self.begin_transaction()

    def invoke(self, value, context=None):
        self.invoke_in_transaction(self._current_txn, value, context)

    # ---- checkpoint integration (operator function-state hooks) -----
    def snapshot_function_state(self, checkpoint_id: Optional[int]) -> dict:
        """Runs at the barrier, atomically with the operator snapshot
        (ref: snapshotState :313 — preCommit + beginTransaction)."""
        import copy
        self.pre_commit(self._current_txn)
        self._pending_commit.append((checkpoint_id, self._current_txn))
        self._current_txn = self.begin_transaction()
        # `current` is the NEW post-barrier transaction: on restore its
        # (replayed) data is aborted, while `pending` commits.  Deep-
        # copied: with in-memory checkpoint storage the snapshot would
        # otherwise ALIAS the live transactions, and a later abort()
        # (e.g. open() on restart) would clear the very objects the
        # restored checkpoint recover-and-commits.
        return copy.deepcopy({
            "pending": list(self._pending_commit),
            "current": self._current_txn,
        })

    def restore_function_state(self, state: dict) -> None:
        """(ref: initializeState :353 — recoverAndCommit pending,
        recoverAndAbort the formerly-current transaction)."""
        for _cid, txn in state["pending"]:
            self.recover_and_commit(txn)
        self._pending_commit = []
        self.recover_and_abort(state["current"])
        self._current_txn = self.begin_transaction()

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        """(ref: notifyCheckpointComplete :268)"""
        remaining = []
        for cid, txn in self._pending_commit:
            if cid is None or cid <= checkpoint_id:
                self.commit(txn)
            else:
                remaining.append((cid, txn))
        self._pending_commit = remaining

    def finish(self) -> None:
        """End of input: commit everything still in flight — pending
        transactions plus the current one.  The final-checkpoint
        behavior for finite jobs (no barrier will ever arrive again to
        commit them)."""
        for _cid, txn in self._pending_commit:
            self.commit(txn)
        self._pending_commit = []
        self.pre_commit(self._current_txn)
        self.commit(self._current_txn)
        self._current_txn = self.begin_transaction()


class _BufferingTransaction:
    """Transaction for buffering sinks: values parked until commit.
    Transaction ids are globally unique (uuid), not a process-local
    counter: a restarted process writing to a durable target must not
    collide with ids committed by a previous run, or idempotence
    dedupe silently drops the new data."""

    __slots__ = ("txn_id", "values", "prepared")

    def __init__(self):
        import uuid
        self.txn_id = uuid.uuid4().hex
        self.values: List[Any] = []
        self.prepared = False

    def __getstate__(self):
        return (self.txn_id, self.values, self.prepared)

    def __setstate__(self, state):
        self.txn_id, self.values, self.prepared = state


class TransactionalCollectSink(TwoPhaseCommitSinkFunction):
    """In-memory exactly-once sink: values become visible in
    `committed` only when their checkpoint completes.  Commits are
    idempotent by transaction id, as the contract requires."""

    def __init__(self, target: Optional[list] = None):
        super().__init__()
        self.committed: List[Any] = target if target is not None else []
        self._committed_txn_ids = set()

    def begin_transaction(self):
        return _BufferingTransaction()

    def invoke_in_transaction(self, txn, value, context):
        txn.values.append(value)

    def pre_commit(self, txn):
        txn.prepared = True

    def commit(self, txn):
        if txn.txn_id in self._committed_txn_ids:
            return  # idempotent replay
        self._committed_txn_ids.add(txn.txn_id)
        self.committed.extend(txn.values)

    def abort(self, txn):
        txn.values.clear()
