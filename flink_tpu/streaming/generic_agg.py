"""Vectorized execution for ARBITRARY Python ``AggregateFunction``s.

The reference's one WindowOperator serves *every* windowed workload by
calling the user's aggregate per record against heap keyed state
(ref: flink-streaming-java/.../runtime/operators/windowing/
WindowOperator.java:291-421, HeapAggregatingState.java:80-89).  Here the
engine tiers (log/scatter/mesh) only cover aggregates with a known cell
decomposition; everything else used to fall to the per-record Python
``window_operator.py``.  This module closes that gap with a
log-structured tier that works for ANY Python aggregate:

- **ingest** appends (key, value-columns) rows to a per-window log —
  pure array appends, no hash probes, no per-record Python;
- **fire** sorts the log by key (stable, so per-key arrival order is
  preserved) and folds each key's run with the user's ``add``;
- the fold runs in **diagonal rounds**: round *r* gathers the *r*-th
  row of every key's run and calls the user's ``add`` ONCE with numpy
  column vectors — the user's Python arithmetic executes elementwise
  over all keys at once.  Python-level ``add`` calls per fire =
  max per-key multiplicity, not the number of records.

Whether a given aggregate's ``add``/``get_result``/``merge`` tolerate
array arguments is decided by a runtime **probe** on the first batch:
the lifted fold is run against the scalar reference on a sample and
must agree.  Aggregates that fail the probe (data-dependent control
flow, exotic accumulators) run the same sorted-segment fold with scalar
``add`` calls — still no per-record state probes, and identical
semantics.

Windows are fired by watermark exactly like the other engine tiers
(window [start, start+size) fires when ``start+size-1 <= watermark``);
logs past a size threshold are compacted into per-key accumulator rows
(folded with ``merge`` at fire), so steady-state memory is O(keys), not
O(records), matching the reference's accumulator-per-key state.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.streaming.operators import (
    StreamOperator,
    TimestampedCollector,
)

log = logging.getLogger("flink_tpu.generic_agg")

#: (aggregate class name, reason head) pairs already warned about —
#: the probe fallback warning fires once per aggregate/cause, not once
#: per subtask
_FALLBACK_WARNED: set = set()

__all__ = [
    "LiftedAggregate",
    "GenericLogTumblingWindows",
    "GenericLogSlidingWindows",
    "GenericLogSessionWindows",
    "GenericWindowOperator",
    "generic_engine_for_assigner",
    "is_generic_eligible",
]

_NUMERIC = (int, float, bool, np.integer, np.floating, np.bool_)


class _ProbeDisagreement(Exception):
    """Lifted fold and scalar reference disagreed on the probe sample
    (message carries the failing field/dtype for the fallback log)."""


def _stable_argsort(keys: np.ndarray) -> np.ndarray:
    """Stable argsort; 64-bit integer keys ride the C++ radix sort
    (numpy's stable 64-bit sort is a comparison sort, ~5x slower at
    fire-path sizes).  Signed keys map through a sign-bit flip, which
    is order-preserving into unsigned space."""
    if keys.dtype == np.uint64 or keys.dtype == np.int64:
        import flink_tpu.native as nat
        if nat.available():
            u = (keys if keys.dtype == np.uint64
                 else keys.view(np.uint64) ^ np.uint64(1 << 63))
            return nat.argsort_u64(u)
    return np.argsort(keys, kind="stable")


def _grouped(keys: np.ndarray):
    """Fused C++ grouping (argsort + segments + length-descending
    layout) for 64-bit integer keys: (order, seg_starts, seg_lens,
    ukeys) or None when the dtype / native runtime doesn't apply.
    ukeys come back in the original dtype."""
    if keys.dtype not in (np.dtype(np.uint64), np.dtype(np.int64)) \
            or len(keys) == 0:
        return None
    import flink_tpu.native as nat
    if not nat.available():
        return None
    signed = keys.dtype == np.int64
    u = (keys.view(np.uint64) ^ np.uint64(1 << 63)) if signed else keys
    order, starts, lens, ukeys = nat.fold_prep(u)
    if signed:
        ukeys = (ukeys ^ np.uint64(1 << 63)).view(np.int64)
    return order, starts, lens, ukeys


def columnify(rows: Sequence[Any]):
    """rows of scalars / uniform tuples → (cols, spec).

    spec: "scalar" | ("tuple", k) | ("list", k); None when the rows
    are not column-representable (ragged / nested / non-scalar
    fields) — callers then keep the rows as an object column.
    """
    first = rows[0]
    if isinstance(first, _NUMERIC + (str, np.str_, bytes)):
        try:
            col = np.asarray(rows)
        except Exception:
            return None, None   # mixed scalar/sequence rows
        if col.dtype.kind == "O" or col.ndim != 1:
            return None, None
        return [col], "scalar"
    if isinstance(first, (tuple, list)):
        k = len(first)
        if k == 0 or any(
                not isinstance(f, _NUMERIC + (str, np.str_, bytes))
                for f in first):
            return None, None
        try:
            cols = [np.asarray([r[i] for r in rows]) for i in range(k)]
        except Exception:
            return None, None
        if any(c.dtype.kind == "O" or c.ndim != 1 for c in cols):
            return None, None
        return cols, ("tuple" if isinstance(first, tuple) else "list", k)
    return None, None


def _value_struct(cols, spec):
    if spec == "scalar":
        return cols[0]
    kind, _ = spec
    return tuple(cols) if kind == "tuple" else list(cols)


class LiftedAggregate:
    """A Python ``AggregateFunction`` with (probed) array semantics.

    Accumulators are represented as a tuple of parallel numpy arrays
    ("fields"); the user's functions are called with the SAME Python
    structure they declared (scalar / tuple / list), just holding
    arrays instead of scalars.

    An aggregate that would pass the probe but must not be lifted
    (see ``AggregateFunction.force_scalar``) pins ``mode`` to
    "scalar" here, before any probe runs.
    """

    def __init__(self, agg):
        self.agg = agg
        self.acc0 = agg.create_accumulator()
        self.acc_spec = self._spec_of(self.acc0)
        pinned = bool(getattr(agg, "force_scalar", False))
        #: "lifted" | "scalar" | None (undecided — probe on first use)
        self.mode: Optional[str] = "scalar" if pinned else None
        self.field_dtypes: Optional[List[np.dtype]] = None
        #: whether get_result lifts too (it can fail independently of
        #: add — e.g. a result built via data-dependent branching)
        self.result_lifted = False
        #: who decided the mode: "static" (AOT analysis), "probe"
        #: (runtime sample), "pin" (force_scalar), "restore"
        self.decided_by: Optional[str] = "pin" if pinned else None
        #: why the scalar path was chosen (None while undecided/lifted)
        self.fallback_reason: Optional[str] = (
            "force_scalar" if pinned else None)
        #: operator uid/name for log + trace context (set by the owner)
        self.owner: str = ""
        self._static_lift = False
        self._static_result_lift = False

    # ---- ahead-of-time verdict --------------------------------------
    def apply_static(self, report) -> None:
        """Feed a conclusive AOT verdict (analysis.liftability).

        SCALAR_ONLY / IMPURE locks the scalar fold immediately; a
        LIFTABLE proof arms a probe-skip fast path — the probe still
        dry-runs one ``add`` to learn field dtypes, but skips the
        scalar-reference replay and comparison (one less warm-up
        batch).  Inconclusive (or None) leaves the runtime probe in
        charge."""
        if report is None or self.mode is not None:
            return
        verdict = getattr(report, "verdict", None)
        if verdict == "LIFTABLE":
            self._static_lift = True
            self._static_result_lift = bool(
                getattr(report, "result_liftable", False))
        elif verdict in ("SCALAR_ONLY", "IMPURE"):
            reasons = "; ".join(getattr(report, "reasons", [])) \
                or verdict.lower()
            self._lock("scalar", "static", reasons, warn=False)

    def _lock(self, mode: str, decided_by: str,
              reason: Optional[str] = None, warn: bool = True) -> None:
        self.mode = mode
        self.decided_by = decided_by
        if mode == "scalar" and reason:
            self.fallback_reason = reason
            if warn:
                self._warn_fallback(reason)
        try:
            from flink_tpu.runtime.tracing import get_tracer
            get_tracer().record_instant(
                "lift.decision", mode=mode, decided_by=decided_by,
                reason=reason or "", operator=self.owner,
                aggregate=type(self.agg).__name__)
        except Exception:
            pass

    def _warn_fallback(self, reason: str) -> None:
        key = (type(self.agg).__name__, reason.split(":")[0])
        if key in _FALLBACK_WARNED:
            return
        _FALLBACK_WARNED.add(key)
        where = f" (operator {self.owner})" if self.owner else ""
        log.warning(
            "aggregate %s%s falls back to the per-record scalar "
            "fold: %s", type(self.agg).__name__, where, reason)

    # ---- accumulator structure --------------------------------------
    @staticmethod
    def _spec_of(acc0):
        if isinstance(acc0, _NUMERIC):
            return "scalar"
        if isinstance(acc0, (tuple, list)) and len(acc0) and all(
                isinstance(f, _NUMERIC) for f in acc0):
            return ("tuple" if isinstance(acc0, tuple) else "list",
                    len(acc0))
        return None

    def _n_fields(self) -> int:
        return 1 if self.acc_spec == "scalar" else self.acc_spec[1]

    def _acc_struct(self, fields):
        if self.acc_spec == "scalar":
            return fields[0]
        kind, _ = self.acc_spec
        return tuple(fields) if kind == "tuple" else list(fields)

    def _fields_of(self, acc_struct, n: int):
        """Validate + normalize a lifted call's return into field
        arrays of length n (scalars broadcast)."""
        if self.acc_spec == "scalar":
            parts = [acc_struct]
        else:
            kind, k = self.acc_spec
            if not isinstance(acc_struct, (tuple, list)) \
                    or len(acc_struct) != k:
                raise TypeError("accumulator structure changed")
            parts = list(acc_struct)
        out = []
        for p in parts:
            a = np.asarray(p)
            if a.ndim == 0:
                a = np.full(n, a[()])
            elif a.shape != (n,):
                raise TypeError("accumulator field is not a column")
            out.append(a)
        return out

    def init_fields(self, n: int) -> List[np.ndarray]:
        inits = ([self.acc0] if self.acc_spec == "scalar"
                 else list(self.acc0))
        return [np.full(n, v, dt)
                for v, dt in zip(inits, self.field_dtypes)]

    # ---- probe ------------------------------------------------------
    def probe(self, cols, vspec, obj_rows=None) -> str:
        """Decide lifted vs scalar on a data sample; locks the mode."""
        if self.mode is not None:
            return self.mode
        agg = self.agg
        if self.acc_spec is None or vspec is None:
            self._lock("scalar", "probe",
                       "accumulator or value rows are not "
                       "column-representable", warn=False)
            return self.mode
        if self._static_lift:
            # AOT-proven liftable: skip the scalar-reference replay.
            # One dry-run add still runs to learn the field dtypes.
            try:
                probe_fields = self._fields_of(
                    agg.add(_value_struct([c[:1] for c in cols], vspec),
                            self._acc_struct([np.asarray([v]) for v in (
                                [self.acc0] if self.acc_spec == "scalar"
                                else list(self.acc0))])), 1)
                self.field_dtypes = [f.dtype for f in probe_fields]
                self.result_lifted = self._static_result_lift
                self._lock("lifted", "static")
                return self.mode
            except Exception:
                # the proof did not survive contact with real data —
                # fall back to the full runtime probe
                self._static_lift = False
        m = min(64, len(cols[0]))
        sample = [c[:m] for c in cols]
        rows = list(zip(*[c.tolist() for c in sample])) \
            if vspec != "scalar" else sample[0].tolist()
        if vspec is not None and vspec != "scalar" and vspec[0] == "list":
            rows = [list(r) for r in rows]
        # scalar reference: up to two interleaved groups (a 1-record
        # first batch probes with one group — an empty group's
        # get_result may legitimately raise, e.g. mean's 0/0)
        n_groups = 2 if m >= 2 else 1
        try:
            ref = []
            for g in range(n_groups):
                acc = agg.create_accumulator()
                for r in rows[g::2]:
                    acc = agg.add(r, acc)
                ref.append(acc)
            ref_res = [agg.get_result(a) for a in ref]
        except Exception as e:
            self._lock("scalar", "probe",
                       f"scalar reference replay raised {e!r}")
            return self.mode
        # lifted: the same groups as slot columns, diagonal rounds
        try:
            # dry-run one add to learn the field dtypes
            probe_fields = self._fields_of(
                agg.add(_value_struct([c[:1] for c in sample], vspec),
                        self._acc_struct([np.asarray([v]) for v in (
                            [self.acc0] if self.acc_spec == "scalar"
                            else list(self.acc0))])), 1)
            self.field_dtypes = [f.dtype for f in probe_fields]
            fields = self.init_fields(n_groups)
            max_len = (m + 1) // 2 if n_groups == 2 else m
            for r in range(max_len):
                idx = [g + 2 * r for g in range(n_groups)
                       if g + 2 * r < m]
                if not idx:
                    break
                slots = np.asarray([i % 2 for i in idx])
                vs = _value_struct([c[idx] for c in sample], vspec)
                acc = self._acc_struct([f[slots] for f in fields])
                new = self._fields_of(agg.add(vs, acc), len(idx))
                for f, nf in zip(fields, new):
                    f[slots] = nf.astype(f.dtype, copy=False)
            lift = [self._acc_struct([np.asarray([f[g]]) for f in fields])
                    for g in range(n_groups)]
            mismatch = None
            for g in range(n_groups):
                detail = self._acc_mismatch(lift[g], ref[g])
                if detail is not None:
                    mismatch = f"group {g}: {detail}"
                    break
            if mismatch is None and n_groups == 2:
                merged = agg.merge(lift[0], lift[1])
                mf = self._fields_of(merged, 1)
                detail = self._acc_mismatch(self._acc_struct(
                    [np.asarray([f[0]]) for f in mf]),
                    agg.merge(ref[0], ref[1]))
                if detail is not None:
                    mismatch = f"merge: {detail}"
            if mismatch is not None:
                raise _ProbeDisagreement(mismatch)
            # result lifting probed separately (failure only demotes
            # get_result, not the fold)
            try:
                res = agg.get_result(self._acc_struct(
                    [np.asarray([float(f[g]) for g in range(n_groups)])
                     .astype(f.dtype) for f in fields]))
                self.result_lifted = self._res_close(
                    res, ref_res[:n_groups])
            except Exception:
                self.result_lifted = False
            self._lock("lifted", "probe")
        except _ProbeDisagreement as e:
            self._lock("scalar", "probe",
                       f"lifted fold disagrees with the scalar "
                       f"reference — {e}")
        except Exception as e:
            self._lock("scalar", "probe",
                       f"lifted replay raised {e!r}")
        return self.mode

    def _acc_mismatch(self, lifted_struct, scalar_acc) -> Optional[str]:
        """First disagreeing accumulator field between a 1-slot lifted
        struct and a scalar reference, or None when they agree.  The
        detail (field index, dtype, both values) feeds the structured
        fallback warning."""
        lf = self._fields_of(lifted_struct, 1)
        sf = ([scalar_acc] if self.acc_spec == "scalar"
              else list(scalar_acc))
        for i, (a, b) in enumerate(zip(lf, sf)):
            if not np.allclose(np.asarray(a, np.float64),
                               np.float64(b), rtol=1e-9, atol=1e-12,
                               equal_nan=True):
                return (f"field {i} (dtype {np.asarray(a).dtype}): "
                        f"lifted={np.asarray(a)[0]!r} "
                        f"scalar={b!r}")
        return None

    @staticmethod
    def _res_close(lifted_res, scalar_results):
        n = len(scalar_results)
        try:
            if isinstance(scalar_results[0], _NUMERIC):
                arr = np.asarray(lifted_res)
                if arr.shape != (n,):
                    return False
                return np.allclose(arr.astype(np.float64),
                                   np.asarray(scalar_results, np.float64),
                                   rtol=1e-9, atol=1e-12, equal_nan=True)
            if isinstance(scalar_results[0], (tuple, list)):
                k = len(scalar_results[0])
                if not isinstance(lifted_res, (tuple, list)) \
                        or len(lifted_res) != k:
                    return False
                for i in range(k):
                    arr = np.asarray(lifted_res[i])
                    if arr.shape != (n,):
                        return False
                    want = np.asarray([r[i] for r in scalar_results],
                                      np.float64)
                    if not np.allclose(arr.astype(np.float64), want,
                                       rtol=1e-9, atol=1e-12,
                                       equal_nan=True):
                        return False
                return True
        except Exception:
            return False
        return False

    # ---- folds ------------------------------------------------------
    def fold_rows(self, order, seg_starts, seg_lens, cols, vspec,
                  seg_perm=None, presorted=False,
                  cols_presorted=False):
        """Fold sorted segments of value rows into per-segment
        accumulator fields.  order: stable sort permutation over the
        rows; seg_starts/lens: segment layout in sorted space.

        Lifted path: segments are processed in LENGTH-DESCENDING order
        (either already laid out that way — ``presorted`` from the C++
        ``ft_fold_prep`` — or permuted here; the returned fields follow
        that order) so each diagonal round's live set is a prefix —
        accumulator reads/writes are slice views, not gather/scatter."""
        n_seg = len(seg_starts)
        if self.mode == "lifted":
            if presorted:
                starts_d, lens_d = seg_starts, seg_lens
            else:
                if seg_perm is None:
                    # length-descending permutation via the radix
                    # argsort (lens are small ints: one counting pass)
                    mx = int(seg_lens.max()) if n_seg else 0
                    seg_perm = _stable_argsort(
                        (mx - seg_lens).astype(np.uint64))
                starts_d = seg_starts[seg_perm]
                lens_d = seg_lens[seg_perm]
            fields = self.init_fields(n_seg)
            max_len = int(lens_d[0]) if n_seg else 0
            # survivors per round from the length histogram: k(r) =
            # #segments with len > r (lens_d is descending, so those
            # are exactly the first k(r) segments)
            hist = np.bincount(lens_d, minlength=max_len + 1)
            alive = n_seg - np.cumsum(hist)
            # pre-permute the value columns once: per-round gathers
            # then index near-sorted positions instead of random rows
            # (skipped when the C++ group kernel already co-scattered)
            cols_s = cols if cols_presorted else [c[order] for c in cols]
            for r in range(max_len):
                k = int(alive[r])
                if k <= 0:
                    break
                rows = starts_d[:k] + r
                vs = _value_struct([c[rows] for c in cols_s], vspec)
                acc = self._acc_struct([f[:k] for f in fields])
                new = self._fields_of(self.agg.add(vs, acc), k)
                for f, nf in zip(fields, new):
                    f[:k] = nf
            return fields, seg_perm
        # scalar fallback: per-segment Python fold (no per-record
        # state probes — the sort already grouped the keys)
        agg = self.agg
        accs = np.empty(n_seg, object)
        if vspec is None:
            obj = cols  # cols IS the object row list here
            for i in range(n_seg):
                s = seg_starts[i]
                acc = agg.create_accumulator()
                for j in range(int(seg_lens[i])):
                    acc = agg.add(obj[order[s + j]], acc)
                accs[i] = acc
        else:
            pycols = [c.tolist() for c in cols]
            mk = (
                (lambda j: pycols[0][j]) if vspec == "scalar" else
                (lambda j: tuple(c[j] for c in pycols))
                if vspec[0] == "tuple" else
                (lambda j: [c[j] for c in pycols]))
            for i in range(n_seg):
                s = seg_starts[i]
                acc = agg.create_accumulator()
                for j in range(int(seg_lens[i])):
                    acc = agg.add(mk(int(order[s + j])), acc)
                accs[i] = acc
        return accs, None

    def merge_sorted(self, order, seg_starts, seg_lens, accs,
                     presorted=False):
        """Fold sorted segments of accumulator rows with ``merge``.
        accs: field-array list (lifted) or object array (scalar).
        Returns (merged, seg_perm) like fold_rows — merged follows
        the length-descending segment order in lifted mode."""
        n_seg = len(seg_starts)
        if self.mode == "lifted":
            seg_perm = None
            if presorted:
                starts_d, lens_d = seg_starts, seg_lens
            else:
                mx = int(seg_lens.max()) if n_seg else 0
                seg_perm = _stable_argsort(
                    (mx - seg_lens).astype(np.uint64))
                starts_d = seg_starts[seg_perm]
                lens_d = seg_lens[seg_perm]
            accs_s = accs if order is None else [f[order] for f in accs]
            fields = [f[starts_d].copy() for f in accs_s]
            max_len = int(lens_d[0]) if n_seg else 0
            hist = np.bincount(lens_d, minlength=max_len + 1)
            alive = n_seg - np.cumsum(hist)
            for r in range(1, max_len):
                k = int(alive[r])
                if k <= 0:
                    break
                rows = starts_d[:k] + r
                a = self._acc_struct([f[:k] for f in fields])
                b = self._acc_struct([f[rows] for f in accs_s])
                new = self._fields_of(self.agg.merge(a, b), k)
                for f, nf in zip(fields, new):
                    f[:k] = nf
            return fields, seg_perm
        agg = self.agg
        out = np.empty(n_seg, object)
        for i in range(n_seg):
            s = seg_starts[i]
            acc = accs[order[s]]
            for j in range(1, int(seg_lens[i])):
                acc = agg.merge(acc, accs[order[s + j]])
            out[i] = acc
        return out, None

    def merge_chunks(self, keys: np.ndarray, accs):
        """Concatenated acc rows (possibly several chunks' worth) →
        per-key merged accs: group by key (co-scattering the acc
        fields through the C++ kernel when eligible) and fold with
        ``merge``.  Returns (ukeys, merged)."""
        if self.mode == "lifted" \
                and keys.dtype in (np.dtype(np.uint64),
                                   np.dtype(np.int64)):
            import flink_tpu.native as nat
            if nat.available():
                g = nat.group_cols(keys.view(np.uint64), accs,
                                   want_order=False)
                if g is not None:
                    _, saccs, starts, lens, ukeys = g
                    if keys.dtype == np.dtype(np.int64):
                        ukeys = ukeys.view(np.int64)
                    merged, _ = self.merge_sorted(
                        None, starts, lens, saccs, presorted=True)
                    return ukeys, merged
        prep = _grouped(keys)
        if prep is not None:
            order, starts, lens, ukeys = prep
            merged, _ = self.merge_sorted(order, starts, lens, accs,
                                          presorted=True)
            return ukeys, merged
        order = _stable_argsort(keys)
        skeys = keys[order]
        starts, lens = _segments(skeys)
        merged, seg_perm = self.merge_sorted(order, starts, lens, accs)
        return (skeys[starts] if seg_perm is None
                else skeys[starts[seg_perm]]), merged

    def results_of(self, accs, n: int):
        """Accumulators → list of per-key Python results."""
        agg = self.agg
        if self.mode == "lifted":
            if self.result_lifted:
                res = agg.get_result(self._acc_struct(list(accs)))
                if isinstance(res, (tuple, list)):
                    parts = [np.asarray(p).tolist() for p in res]
                    mk = tuple if isinstance(res, tuple) else list
                    return [mk(p[i] for p in parts) for i in range(n)]
                return np.asarray(res).tolist()
            structs = (accs[0].tolist() if self.acc_spec == "scalar"
                       else None)
            if structs is not None:
                return [agg.get_result(a) for a in structs]
            kind, _ = self.acc_spec
            mk = tuple if kind == "tuple" else list
            pyfields = [f.tolist() for f in accs]
            return [agg.get_result(mk(f[i] for f in pyfields))
                    for i in range(n)]
        return [agg.get_result(a) for a in accs]


class _WindowLog:
    """Append-only row log for one window: chunks of raw value rows
    plus compacted accumulator chunks (per-key, key-sorted)."""

    __slots__ = ("key_chunks", "col_chunks", "acc_key_chunks",
                 "acc_chunks", "count")

    def __init__(self):
        self.key_chunks: List[np.ndarray] = []
        self.col_chunks: List[Any] = []   # per chunk: cols list | obj rows
        self.acc_key_chunks: List[np.ndarray] = []
        self.acc_chunks: List[Any] = []   # fields list | object array
        self.count = 0

    def append(self, keys, cols):
        self.key_chunks.append(keys)
        self.col_chunks.append(cols)
        self.count += len(keys)


def _segments(sorted_keys: np.ndarray):
    """Boundaries of equal-key runs in an already-sorted key column."""
    n = len(sorted_keys)
    if n == 0:
        return (np.zeros(0, np.int64),) * 2
    change = np.empty(n, bool)
    change[0] = True
    np.not_equal(sorted_keys[1:], sorted_keys[:-1], out=change[1:])
    starts = np.flatnonzero(change)
    lens = np.diff(np.append(starts, n))
    return starts, lens


class _GenericLogEngine:
    """Shared machinery: value columnification, the probe, the
    sort+fold fire path, snapshot/restore.  Subclasses define window
    assignment and the fire schedule."""

    def __init__(self, aggregate, compact_threshold: int = 1 << 21):
        self.agg = aggregate
        self.lift = LiftedAggregate(aggregate)
        self.compact_threshold = compact_threshold
        self.windows: Dict[int, _WindowLog] = {}
        self.watermark = -(2 ** 63)
        self.emitted: List[Tuple[Any, Any, int, int]] = []
        self.emit_arrays = False
        self.fired: List[Tuple[np.ndarray, Any, int, int]] = []
        self.num_late_dropped = 0
        self.vspec = None
        self._vspec_locked = False

    # -- interface parity with the other engine tiers ---------------
    def flush(self, grow_to=None):
        pass

    def block_until_ready(self):
        pass

    @property
    def mode(self) -> Optional[str]:
        return self.lift.mode

    # -- ingest ------------------------------------------------------
    def _prep_values(self, values, n: int):
        """values (array | list of rows | None) → (cols, obj_rows).

        The value spec locks on the first batch; a later batch with a
        DIFFERENT shape (heterogeneous stream) demotes the whole
        engine to object-row mode — semantics match the per-record
        WindowOperator, only the vectorization is lost."""
        if values is None:
            raise ValueError(
                "generic aggregates need the record values "
                "(process_batch(values=...))")
        rows = None
        if isinstance(values, np.ndarray) and values.dtype.kind != "O":
            if values.ndim == 1:
                cols, vspec = [values], "scalar"
            else:
                cols = [values[:, i] for i in range(values.shape[1])]
                vspec = ("tuple", values.shape[1])
        else:
            rows = (values.tolist()
                    if isinstance(values, np.ndarray) else list(values))
            cols, vspec = columnify(rows)
        if not self._vspec_locked:
            self.vspec, self._vspec_locked = vspec, True
            if vspec is None:
                self.lift.mode = "scalar"
            else:
                self.lift.probe(cols, vspec)
        elif vspec != self.vspec:
            # shape change mid-stream: demote everything to object rows
            if self.vspec is not None:
                self._demote_to_object()
            vspec = None
        if vspec is None:
            if rows is None:
                rows = (values.tolist()
                        if isinstance(values, np.ndarray)
                        else list(values))
            obj = np.empty(n, object)
            obj[:] = rows
            return None, obj
        return cols, None

    def _demote_to_object(self):
        """Convert buffered column chunks (and the locked spec) to
        object-row mode — the correctness path for value streams whose
        shape changes after the first batch.  Compacted acc chunks
        stay: merge/get_result consume accumulators, not values."""
        if self.lift.mode == "lifted":
            # re-materialize lifted acc chunks as scalar accumulators
            for log in self.windows.values():
                for i, fields in enumerate(log.acc_chunks):
                    m = len(log.acc_key_chunks[i])
                    accs = np.empty(m, object)
                    if self.lift.acc_spec == "scalar":
                        vals = fields[0].tolist()
                        accs[:] = vals
                    else:
                        kind, _ = self.lift.acc_spec
                        mk = tuple if kind == "tuple" else list
                        pyf = [f.tolist() for f in fields]
                        accs[:] = [mk(f[j] for f in pyf)
                                   for j in range(m)]
                    log.acc_chunks[i] = accs
        self.lift.mode = "scalar"
        spec = self.vspec
        self.vspec = None
        for log in self.windows.values():
            for i, cc in enumerate(log.col_chunks):
                if not isinstance(cc, list):
                    continue  # already object rows
                m = len(log.key_chunks[i])
                obj = np.empty(m, object)
                if spec == "scalar":
                    obj[:] = cc[0].tolist()
                else:
                    kind, _ = spec
                    mk = tuple if kind == "tuple" else list
                    pyc = [col.tolist() for col in cc]
                    obj[:] = [mk(col[j] for col in pyc)
                              for j in range(m)]
                log.col_chunks[i] = obj

    def _append(self, start: int, keys, cols, obj):
        log = self.windows.get(start)
        if log is None:
            log = self.windows[start] = _WindowLog()
        log.append(keys, cols if obj is None else obj)
        if log.count >= self.compact_threshold:
            self._compact(log)

    # -- fold machinery ----------------------------------------------
    def _fold_sorted_rows(self, keys, cols, payload):
        """Group a row chunk by key and fold → (ukeys, accs).  Three
        grouping tiers: fused C++ count+co-scatter (small key domains,
        numeric value columns), C++ radix fold_prep (64-bit integer
        keys), numpy stable argsort (everything else)."""
        if cols is not None \
                and keys.dtype in (np.dtype(np.uint64),
                                   np.dtype(np.int64)):
            import flink_tpu.native as nat
            if nat.available():
                lifted = self.lift.mode == "lifted"
                g = nat.group_cols(keys.view(np.uint64),
                                   cols if lifted else (),
                                   want_order=not lifted)
                if g is not None:
                    order, scols, starts, lens, ukeys = g
                    if keys.dtype == np.dtype(np.int64):
                        ukeys = ukeys.view(np.int64)
                    if lifted:
                        # columns came back co-scattered: rounds index
                        # them directly, no numpy re-permute
                        accs, _ = self.lift.fold_rows(
                            order, starts, lens, scols, self.vspec,
                            presorted=True, cols_presorted=True)
                    else:
                        accs, _ = self.lift.fold_rows(
                            order, starts, lens, cols, self.vspec,
                            presorted=True)
                    return ukeys, accs
        prep = _grouped(keys)
        if prep is not None:
            order, starts, lens, ukeys = prep
            accs, _ = self.lift.fold_rows(
                order, starts, lens,
                payload if self.vspec is None else cols,
                self.vspec, presorted=True)
            return ukeys, accs
        order = _stable_argsort(keys)
        skeys = keys[order]
        starts, lens = _segments(skeys)
        accs, seg_perm = self.lift.fold_rows(
            order, starts, lens,
            payload if self.vspec is None else cols, self.vspec)
        return (skeys[starts] if seg_perm is None
                else skeys[starts[seg_perm]]), accs

    def _fold_log(self, log: _WindowLog):
        """→ (keys_sorted_unique, accs) folding raw rows with add and
        compacted chunks with merge."""
        acc_keys: List[np.ndarray] = list(log.acc_key_chunks)
        acc_chunks: List[Any] = list(log.acc_chunks)
        if log.key_chunks:
            keys = (log.key_chunks[0] if len(log.key_chunks) == 1
                    else np.concatenate(log.key_chunks))
            if self.vspec is None:
                obj = (log.col_chunks[0] if len(log.col_chunks) == 1
                       else np.concatenate(log.col_chunks))
                cols, payload = None, obj
            else:
                k = len(log.col_chunks[0])
                cols = [np.concatenate([c[i] for c in log.col_chunks])
                        if len(log.col_chunks) > 1 else
                        log.col_chunks[0][i] for i in range(k)]
                payload = cols
            ukeys, accs = self._fold_sorted_rows(keys, cols, payload)
            acc_keys.append(ukeys)
            acc_chunks.append(accs)
        if not acc_keys:
            return np.zeros(0, np.int64), None
        if len(acc_keys) == 1:
            return acc_keys[0], acc_chunks[0]
        keys = np.concatenate(acc_keys)
        if self.lift.mode == "lifted":
            nf = self.lift._n_fields()
            accs = [np.concatenate([c[i] for c in acc_chunks])
                    for i in range(nf)]
        else:
            accs = np.concatenate(acc_chunks)
        return self.lift.merge_chunks(keys, accs)

    def _compact(self, log: _WindowLog):
        """Fold the raw rows into an acc chunk.  Acc chunks are NOT
        merged here — re-merging the carry on every compaction is the
        quadratic-retained-state trap; fire merges all chunks once.
        Only when the acc chunks alone outgrow the threshold (heavy
        key churn) are they deduped into one."""
        raw_only = _WindowLog()
        raw_only.key_chunks = log.key_chunks
        raw_only.col_chunks = log.col_chunks
        ukeys, accs = self._fold_log(raw_only)
        log.key_chunks, log.col_chunks = [], []
        if len(ukeys):
            log.acc_key_chunks.append(ukeys)
            log.acc_chunks.append(accs)
        acc_rows = sum(len(c) for c in log.acc_key_chunks)
        if len(log.acc_key_chunks) > 1 \
                and acc_rows >= self.compact_threshold:
            merged = _WindowLog()
            merged.acc_key_chunks = log.acc_key_chunks
            merged.acc_chunks = log.acc_chunks
            ukeys, accs = self._fold_log(merged)
            log.acc_key_chunks = [ukeys]
            log.acc_chunks = [accs]
            acc_rows = len(ukeys)
        log.count = acc_rows

    def _emit(self, ukeys, accs, start: int, end: int):
        n = len(ukeys)
        if n == 0:
            return 0
        if self.emit_arrays:
            if self.lift.mode == "lifted" and self.lift.result_lifted:
                res = self.agg.get_result(self.lift._acc_struct(
                    list(accs)))
            else:
                res = np.asarray(self.lift.results_of(accs, n),
                                 dtype=object)
            self.fired.append((ukeys, res, start, end))
        else:
            results = self.lift.results_of(accs, n)
            pykeys = ukeys.tolist()
            self.emitted.extend(
                (pykeys[i], results[i], start, end) for i in range(n))
        return n

    # -- checkpoint ---------------------------------------------------
    def snapshot(self) -> dict:
        for log in self.windows.values():
            # compacted acc rows are only portable when the fold ran;
            # raw rows always are — compact so restarts resume from
            # bounded state
            if log.key_chunks and self.lift.mode is not None:
                self._compact(log)
        wins = {}
        for start, log in self.windows.items():
            if log.key_chunks:   # mode never probed: raw rows
                wins[start] = {
                    "raw_keys": [np.asarray(c) for c in log.key_chunks],
                    "raw_cols": log.col_chunks,
                    "vspec": self.vspec,
                }
            else:
                wins[start] = {
                    "acc_keys": log.acc_key_chunks,
                    "accs": log.acc_chunks,
                }
        return {
            "generic_log": True,
            "watermark": self.watermark,
            "num_late_dropped": self.num_late_dropped,
            "vspec": self.vspec,
            "vspec_locked": self._vspec_locked,
            "mode": self.lift.mode,
            "decided_by": self.lift.decided_by,
            "result_lifted": self.lift.result_lifted,
            "field_dtypes": ([str(d) for d in self.lift.field_dtypes]
                             if self.lift.field_dtypes else None),
            "windows": wins,
        }

    def restore(self, snap: dict) -> None:
        self.watermark = snap["watermark"]
        self.num_late_dropped = snap["num_late_dropped"]
        self.vspec = snap["vspec"]
        if isinstance(self.vspec, list):   # JSON round-trip safety
            self.vspec = tuple(self.vspec)
        self._vspec_locked = snap["vspec_locked"]
        self.lift.mode = snap["mode"]
        if self.lift.mode is not None:
            self.lift.decided_by = snap.get("decided_by") or "restore"
        self.lift.result_lifted = snap["result_lifted"]
        if snap["field_dtypes"]:
            self.lift.field_dtypes = [np.dtype(d)
                                      for d in snap["field_dtypes"]]
        self.windows = {}
        for start, w in snap["windows"].items():
            log = _WindowLog()
            if "raw_keys" in w:
                log.key_chunks = list(w["raw_keys"])
                log.col_chunks = list(w["raw_cols"])
                log.count = sum(len(c) for c in log.key_chunks)
            else:
                log.acc_key_chunks = list(w["acc_keys"])
                log.acc_chunks = list(w["accs"])
                log.count = sum(len(c) for c in log.acc_key_chunks)
            self.windows[int(start)] = log

    def restore_many(self, snaps, keep_fn=None) -> None:
        """Union-restore (rescale): accumulate every snapshot's chunks,
        filtering keys by the key-group keep_fn.  Subtasks probe
        independently, so snapshots may disagree on lifted-vs-scalar
        mode or the value spec (one subtask alone may have seen a
        demoting shape change) — a mixed set restores on the common
        denominator: every restored engine demotes to object-row /
        scalar mode before its chunks are adopted."""
        mixed = (len({(s.get("mode"), repr(s.get("vspec")))
                      for s in snaps if s.get("mode") is not None}) > 1)
        for snap in snaps:
            other = type(self)(self.agg, **self._ctor_extra())
            other.restore(snap)
            if mixed and other.lift.mode is not None:
                other._demote_to_object()
                self.vspec = None
                self._vspec_locked = True
                self.lift.mode = "scalar"
                self.lift.decided_by = "restore"
                self.lift.fallback_reason = \
                    "mixed-mode snapshot set restored on the common " \
                    "denominator"
            self.watermark = max(self.watermark, other.watermark)
            self.num_late_dropped += other.num_late_dropped
            if self.lift.mode is None and other.lift.mode is not None:
                self.vspec = other.vspec
                self._vspec_locked = other._vspec_locked
                self.lift.mode = other.lift.mode
                self.lift.decided_by = other.lift.decided_by \
                    or "restore"
                self.lift.result_lifted = other.lift.result_lifted
                self.lift.field_dtypes = other.lift.field_dtypes
            for start, log in other.windows.items():
                mine = self.windows.get(start)
                if mine is None:
                    mine = self.windows[start] = _WindowLog()
                for kc, cc in zip(log.key_chunks, log.col_chunks):
                    keep = keep_fn(kc) if keep_fn is not None else None
                    if keep is None:
                        mine.key_chunks.append(kc)
                        mine.col_chunks.append(cc)
                        mine.count += len(kc)
                    else:
                        mine.key_chunks.append(kc[keep])
                        mine.col_chunks.append(
                            cc[keep] if self.vspec is None
                            else [c[keep] for c in cc])
                        mine.count += int(keep.sum())
                for kc, ac in zip(log.acc_key_chunks, log.acc_chunks):
                    keep = keep_fn(kc) if keep_fn is not None else None
                    if keep is None:
                        mine.acc_key_chunks.append(kc)
                        mine.acc_chunks.append(ac)
                        mine.count += len(kc)
                    else:
                        mine.acc_key_chunks.append(kc[keep])
                        mine.acc_chunks.append(
                            [f[keep] for f in ac]
                            if self.lift.mode == "lifted" else ac[keep])
                        mine.count += int(keep.sum())

    def _ctor_extra(self) -> dict:
        return {"compact_threshold": self.compact_threshold}


class GenericLogTumblingWindows(_GenericLogEngine):
    """keyBy().window(Tumbling).aggregate(<any AggregateFunction>)."""

    def __init__(self, aggregate, window_size_ms: int,
                 compact_threshold: int = 1 << 21):
        super().__init__(aggregate, compact_threshold)
        self.size = window_size_ms
        self.lateness_horizon = window_size_ms

    def _ctor_extra(self) -> dict:
        return {"window_size_ms": self.size,
                "compact_threshold": self.compact_threshold}

    def process_batch(self, keys, timestamps, values=None,
                      key_hashes=None, value_hashes=None) -> None:
        ts = np.asarray(timestamps, np.int64)
        keys = np.asarray(keys)
        if len(keys) == 0:
            return
        starts = ts - np.mod(ts, self.size)
        lo = int(starts.min())
        hi = int(starts.max())
        # fast path: the oldest record in the batch is still live →
        # no late mask, no per-record bool work
        if lo + self.lateness_horizon - 1 <= self.watermark:
            live = starts + self.lateness_horizon - 1 > self.watermark
            self.num_late_dropped += int((~live).sum())
            if not live.any():
                return
            keys, ts, starts = keys[live], ts[live], starts[live]
            if values is not None:
                values = (values[live]
                          if isinstance(values, np.ndarray)
                          else [v for v, ok in zip(values, live) if ok])
            lo = int(starts.min())
            hi = int(starts.max())
        cols, obj = self._prep_values(values, len(keys))
        if lo == hi:
            self._append(lo, keys, cols, obj)
            return
        for start in np.unique(starts):
            m = starts == start
            self._append(int(start), keys[m],
                         None if cols is None else [c[m] for c in cols],
                         None if obj is None else obj[m])

    def advance_watermark(self, watermark: int) -> int:
        self.watermark = watermark
        fired = 0
        for start in sorted(self.windows):
            if start + self.size - 1 > watermark:
                continue
            log = self.windows.pop(start)
            if log.count == 0:
                continue
            ukeys, accs = self._fold_log(log)
            fired += self._emit(ukeys, accs, start, start + self.size)
        return fired


class GenericLogSlidingWindows(_GenericLogEngine):
    """Sliding windows via pane decomposition: ingest into panes of
    the slide, fire merges size/slide folded panes per key (the panes
    optimization the reference applies to aligned sliding windows)."""

    def __init__(self, aggregate, window_size_ms: int, slide_ms: int,
                 compact_threshold: int = 1 << 21):
        if window_size_ms % slide_ms:
            raise ValueError("size must be a multiple of slide")
        super().__init__(aggregate, compact_threshold)
        self.size = window_size_ms
        self.slide = slide_ms
        self.n_panes = window_size_ms // slide_ms
        self.lateness_horizon = window_size_ms
        #: pane start -> folded (ukeys, accs), computed on first use
        self._pane_folds: Dict[int, Tuple[np.ndarray, Any]] = {}
        #: end of the last fired window (panes outlive their windows,
        #: so fired windows must never re-fire on the next advance)
        self._fired_until = -(2 ** 63)

    def _ctor_extra(self) -> dict:
        return {"window_size_ms": self.size, "slide_ms": self.slide,
                "compact_threshold": self.compact_threshold}

    def process_batch(self, keys, timestamps, values=None,
                      key_hashes=None, value_hashes=None) -> None:
        ts = np.asarray(timestamps, np.int64)
        keys = np.asarray(keys)
        if len(keys) == 0:
            return
        pane = ts - np.mod(ts, self.slide)
        lo = int(pane.min())
        hi = int(pane.max())
        if lo + self.lateness_horizon - 1 <= self.watermark:
            live = pane + self.lateness_horizon - 1 > self.watermark
            self.num_late_dropped += int((~live).sum())
            if not live.any():
                return
            keys, ts, pane = keys[live], ts[live], pane[live]
            if values is not None:
                values = (values[live]
                          if isinstance(values, np.ndarray)
                          else [v for v, ok in zip(values, live) if ok])
            lo = int(pane.min())
            hi = int(pane.max())
        cols, obj = self._prep_values(values, len(keys))
        if lo == hi:
            self._pane_folds.pop(lo, None)  # pane grew: refold
            self._append(lo, keys, cols, obj)
            return
        for start in np.unique(pane):
            self._pane_folds.pop(int(start), None)
            m = pane == start
            self._append(int(start), keys[m],
                         None if cols is None else [c[m] for c in cols],
                         None if obj is None else obj[m])

    def _pane_fold(self, start: int):
        cached = self._pane_folds.get(start)
        if cached is not None:
            return cached
        log = self.windows.get(start)
        if log is None or log.count == 0:
            out = (np.zeros(0, np.int64), None)
        else:
            out = self._fold_log(log)
        self._pane_folds[start] = out
        return out

    def advance_watermark(self, watermark: int) -> int:
        self.watermark = watermark
        fired = 0
        if not self.windows and not self._pane_folds:
            return 0
        # candidate window ends come from the panes that EXIST — never
        # walk the raw event-time range one slide at a time (a week's
        # idle gap at a 10 ms slide would be ~60M iterations)
        pane_starts = sorted(set(self.windows) | set(self._pane_folds))
        fireable = ((watermark + 1) // self.slide) * self.slide
        ends: set = set()
        for p in pane_starts:
            e_lo = max(p + self.slide, self._fired_until + self.slide)
            e_hi = min(p + self.size, fireable)
            ends.update(range(e_lo, e_hi + 1, self.slide))
        for e in sorted(ends):
            ps = [p for p in range(e - self.size, e, self.slide)
                  if p in self.windows or p in self._pane_folds]
            if ps:
                folds = [self._pane_fold(p) for p in ps]
                folds = [(k, a) for k, a in folds if len(k)]
                if folds:
                    fired += self._fire_merged(folds, e - self.size, e)
            self._fired_until = e
            # retire panes that no future window can contain
            for p in [p for p in list(self.windows)
                      if p + self.size <= e]:
                self.windows.pop(p, None)
                self._pane_folds.pop(p, None)
            for p in [p for p in self._pane_folds
                      if p + self.size <= e]:
                self._pane_folds.pop(p, None)
        # panes fully behind an empty stretch the loop never visited
        # still retire once every window containing them is fireable
        for p in [p for p in list(self.windows)
                  if p + self.size <= max(self._fired_until, fireable)
                  and p + self.size - 1 <= watermark]:
            self.windows.pop(p, None)
            self._pane_folds.pop(p, None)
        return fired

    def _demote_to_object(self):
        super()._demote_to_object()
        self._pane_folds.clear()  # cached folds hold lifted fields

    def snapshot(self) -> dict:
        snap = super().snapshot()
        snap["fired_until"] = self._fired_until
        return snap

    def restore(self, snap: dict) -> None:
        super().restore(snap)
        self._fired_until = snap.get("fired_until", -(2 ** 63))
        self._pane_folds = {}

    def restore_many(self, snaps, keep_fn=None) -> None:
        super().restore_many(snaps, keep_fn)
        for snap in snaps:
            self._fired_until = max(
                self._fired_until, snap.get("fired_until", -(2 ** 63)))
        self._pane_folds = {}

    def _fire_merged(self, folds, start: int, end: int) -> int:
        if len(folds) == 1:
            ukeys, accs = folds[0]
            return self._emit(ukeys, accs, start, end)
        keys = np.concatenate([k for k, _ in folds])
        if self.lift.mode == "lifted":
            nf = self.lift._n_fields()
            accs = [np.concatenate([a[i] for _, a in folds])
                    for i in range(nf)]
        else:
            accs = np.concatenate([a for _, a in folds])
        ukeys, merged = self.lift.merge_chunks(keys, accs)
        return self._emit(ukeys, merged, start, end)


class GenericLogSessionWindows(_GenericLogEngine):
    """Event-time session windows for arbitrary aggregates: retained
    open-session rows are carried in (key, ts) sorted order (the
    contract that keeps long-gap sessions linear — see session_cm);
    each watermark sorts only the NEW rows and merges two key-major
    streams, then folds closed sessions with the lifted add."""

    def __init__(self, aggregate, gap_ms: int,
                 compact_threshold: int = 1 << 21):
        super().__init__(aggregate, compact_threshold)
        self.gap = gap_ms
        # retained open-session rows, (key, ts)-sorted
        self._r_keys = np.zeros(0, np.int64)
        self._r_ts = np.zeros(0, np.int64)
        self._r_cols: Optional[List[np.ndarray]] = None
        self._r_obj: Optional[np.ndarray] = None
        # new rows since the last advance (unsorted chunks)
        self._n_keys: List[np.ndarray] = []
        self._n_ts: List[np.ndarray] = []
        self._n_cols: List[Any] = []

    def _ctor_extra(self) -> dict:
        return {"compact_threshold": self.compact_threshold}

    def _demote_to_object(self):
        spec = self.vspec
        super()._demote_to_object()

        def to_obj(cc):
            if not isinstance(cc, list):
                return cc
            m = len(cc[0])
            obj = np.empty(m, object)
            if spec == "scalar":
                obj[:] = cc[0].tolist()
            else:
                kind, _ = spec
                mk = tuple if kind == "tuple" else list
                pyc = [col.tolist() for col in cc]
                obj[:] = [mk(col[j] for col in pyc) for j in range(m)]
            return obj

        if self._r_cols is not None:
            self._r_obj = to_obj(self._r_cols)
            self._r_cols = None
        self._n_cols = [to_obj(c) for c in self._n_cols]

    def process_batch(self, keys, timestamps, values=None,
                      key_hashes=None, value_hashes=None) -> None:
        ts = np.asarray(timestamps, np.int64)
        keys = np.asarray(keys)
        if len(keys) == 0:
            return
        live = ts + self.gap - 1 > self.watermark
        if not live.all():
            # merge-before-drop: the reference merges a late record
            # with existing sessions FIRST and only drops when the
            # MERGED window is late (WindowOperator.java:308-343,
            # isWindowLate after mergeWindows).  A record whose own
            # window [ts, ts+gap) is behind the watermark therefore
            # survives when it chains — directly or through other
            # rows within the gap — to a session that is still open.
            # (log_windows.py:884 cannot offer this refinement: its
            # kernel keeps no host-visible open-session rows.)
            live |= self._revive_late(keys, ts, live)
            self.num_late_dropped += int((~live).sum())
            if not live.any():
                return
            if not live.all():
                keys, ts = keys[live], ts[live]
                if values is not None:
                    values = (values[live]
                              if isinstance(values, np.ndarray)
                              else [v for v, ok in zip(values, live)
                                    if ok])
        cols, obj = self._prep_values(values, len(keys))
        self._n_keys.append(keys)
        self._n_ts.append(ts)
        self._n_cols.append(cols if obj is None else obj)

    def _revive_late(self, keys, ts, live) -> np.ndarray:
        """Mask of initially-late rows that still belong to an OPEN
        session.  Anchors are every accepted open row: the retained
        set, pending new rows, and this batch's live rows.  Rows and
        anchors of one key are chained into components with the same
        inclusive-touch rule the fire path uses (Δts <= gap); a late
        row in a component that contains any anchor is revived —
        including rows that only reach an anchor through OTHER late
        rows (the transitive merge the reference performs session by
        session)."""
        out = np.zeros(len(keys), bool)
        late_idx = np.flatnonzero(~live)
        ak = ([self._r_keys] + list(self._n_keys) + [keys[live]])
        at = ([self._r_ts] + list(self._n_ts) + [ts[live]])
        ak = np.concatenate(ak)
        if len(ak) == 0:
            return out
        at = np.concatenate(at)
        allk = np.concatenate([ak, keys[late_idx]])
        allt = np.concatenate([at, ts[late_idx]])
        anchor = np.zeros(len(allk), bool)
        anchor[:len(ak)] = True
        src = np.full(len(allk), -1, np.int64)
        src[len(ak):] = late_idx
        order = np.lexsort((allt, allk))
        k2, t2 = allk[order], allt[order]
        a2, s2 = anchor[order], src[order]
        newc = np.empty(len(k2), bool)
        newc[0] = True
        np.not_equal(k2[1:], k2[:-1], out=newc[1:])
        np.logical_or(newc[1:], t2[1:] - t2[:-1] > self.gap,
                      out=newc[1:])
        comp = np.cumsum(newc) - 1
        has_anchor = np.zeros(int(comp[-1]) + 1, bool)
        np.logical_or.at(has_anchor, comp, a2)
        revived = s2[has_anchor[comp] & (s2 >= 0)]
        out[revived] = True
        return out

    def _merge_sorted_streams(self, keys, ts, payload):
        """Merge (key,ts)-sorted retained rows with the (key,ts)-sorted
        new rows WITHOUT re-sorting the retained set."""
        rk, rt = self._r_keys, self._r_ts
        if len(rk) == 0:
            return keys, ts, payload
        # position of each new row in the merged stream: count of
        # retained rows strictly before it (lexicographic (key, ts));
        # encode as complex? no — two-level searchsorted via stable
        # keys then ts is subtle; use np.lexsort on the CONCATENATED
        # pair but with a precomputed "already sorted" hint: merging
        # two sorted streams with lexsort is O(n log n) on the merged
        # length but touches each element once — acceptable because
        # the expensive case (quadratic re-sort of a LARGE retained
        # set per advance) is avoided by timsort's run detection:
        # argsort(kind="stable") on two concatenated sorted runs is
        # a single merge pass (numpy uses timsort for stable).
        mk = np.concatenate([rk, keys])
        mt = np.concatenate([rt, ts])
        order = np.lexsort((mt, mk))
        if self.vspec is None:
            obj = np.concatenate([self._r_obj, payload])
            return mk[order], mt[order], obj[order]
        cols = [np.concatenate([rc, nc])[order]
                for rc, nc in zip(self._r_cols, payload)]
        return mk[order], mt[order], cols

    def advance_watermark(self, watermark: int) -> int:
        self.watermark = watermark
        if self._n_keys:
            nk = np.concatenate(self._n_keys)
            nt = np.concatenate(self._n_ts)
            if self.vspec is None:
                payload = np.concatenate(self._n_cols)
            else:
                k = len(self._n_cols[0])
                payload = [np.concatenate([c[i] for c in self._n_cols])
                           for i in range(k)]
            order = np.lexsort((nt, nk))
            nk, nt = nk[order], nt[order]
            payload = (payload[order] if self.vspec is None
                       else [c[order] for c in payload])
            self._n_keys, self._n_ts, self._n_cols = [], [], []
            keys, ts, payload = self._merge_sorted_streams(nk, nt, payload)
        else:
            keys, ts, payload = self._r_keys, self._r_ts, (
                self._r_obj if self.vspec is None else self._r_cols)
        n = len(keys)
        if n == 0:
            return 0
        # session boundaries: new key OR ts gap STRICTLY over the gap
        # (touching windows merge: TimeWindow.intersects is inclusive,
        # windowing.py:81-82 / reference TimeWindow.java)
        new_sess = np.empty(n, bool)
        new_sess[0] = True
        np.not_equal(keys[1:], keys[:-1], out=new_sess[1:])
        np.logical_or(new_sess[1:], ts[1:] - ts[:-1] > self.gap,
                      out=new_sess[1:])
        sess_id = np.cumsum(new_sess) - 1
        starts = np.flatnonzero(new_sess)
        lens = np.diff(np.append(starts, n))
        last_ts = ts[starts + lens - 1]
        closed = last_ts + self.gap - 1 <= watermark
        fired = 0
        if closed.any():
            cs, cl = starts[closed], lens[closed]
            # vectorized ragged-range build (no per-session Python):
            # order = [cs_i, cs_i+1, ..., cs_i+cl_i) for every closed
            # session, via repeat + a running-offset correction
            total = int(cl.sum())
            seg_starts = np.zeros(len(cl), np.int64)
            np.cumsum(cl[:-1], out=seg_starts[1:])
            order = (np.repeat(cs - seg_starts, cl)
                     + np.arange(total, dtype=np.int64)) \
                if total else np.zeros(0, np.int64)
            accs, seg_perm = self.lift.fold_rows(
                order, seg_starts.astype(np.int64), cl, payload,
                self.vspec)
            if seg_perm is not None:
                cs, cl = cs[seg_perm], cl[seg_perm]
            first_ts = ts[cs]
            end_ts = ts[cs + cl - 1] + self.gap
            ukeys = keys[cs]
            if self.emit_arrays:
                res = (self.agg.get_result(self.lift._acc_struct(
                    list(accs)))
                    if self.lift.mode == "lifted"
                    and self.lift.result_lifted
                    else np.asarray(
                        self.lift.results_of(accs, len(cs)),
                        dtype=object))
                self.fired.append((ukeys, res, first_ts, end_ts))
            else:
                results = self.lift.results_of(accs, len(cs))
                pykeys = ukeys.tolist()
                self.emitted.extend(
                    (pykeys[i], results[i], int(first_ts[i]),
                     int(end_ts[i])) for i in range(len(cs)))
            fired += len(cs)
        keep_rows = ~closed[sess_id]
        self._r_keys = keys[keep_rows]
        self._r_ts = ts[keep_rows]
        if self.vspec is None:
            self._r_obj = payload[keep_rows]
            self._r_cols = None
        else:
            self._r_cols = [c[keep_rows] for c in payload]
            self._r_obj = None
        return fired

    # session state rides the retained rows, not window logs
    def snapshot(self) -> dict:
        snap = super().snapshot()
        snap["session"] = {
            "r_keys": self._r_keys, "r_ts": self._r_ts,
            "r_cols": self._r_cols, "r_obj": self._r_obj,
            "n_keys": list(self._n_keys), "n_ts": list(self._n_ts),
            "n_cols": list(self._n_cols),
        }
        return snap

    def restore(self, snap: dict) -> None:
        super().restore(snap)
        s = snap["session"]
        self._r_keys, self._r_ts = s["r_keys"], s["r_ts"]
        self._r_cols, self._r_obj = s["r_cols"], s["r_obj"]
        self._n_keys = list(s["n_keys"])
        self._n_ts = list(s["n_ts"])
        self._n_cols = list(s["n_cols"])

    def restore_many(self, snaps, keep_fn=None) -> None:
        # as in the base class: a mode/spec-mixed snapshot set restores
        # on the common denominator (object rows, scalar folds)
        mixed = (len({(s.get("mode"), repr(s.get("vspec")))
                      for s in snaps if s.get("mode") is not None}) > 1)
        for snap in snaps:
            other = GenericLogSessionWindows(self.agg, self.gap)
            other.restore(snap)
            if mixed and other.lift.mode is not None:
                other._demote_to_object()
                self.vspec = None
                self._vspec_locked = True
                self.lift.mode = "scalar"
                self.lift.decided_by = "restore"
                self.lift.fallback_reason = \
                    "mixed-mode snapshot set restored on the common " \
                    "denominator"
            self.watermark = max(self.watermark, other.watermark)
            self.num_late_dropped += other.num_late_dropped
            if self.lift.mode is None and other.lift.mode is not None:
                self.vspec = other.vspec
                self._vspec_locked = other._vspec_locked
                self.lift.mode = other.lift.mode
                self.lift.decided_by = other.lift.decided_by \
                    or "restore"
                self.lift.result_lifted = other.lift.result_lifted
                self.lift.field_dtypes = other.lift.field_dtypes
            keep = (keep_fn(other._r_keys) if keep_fn is not None
                    else np.ones(len(other._r_keys), bool))
            # re-queue as new rows; the next advance merge-sorts them
            if keep.any():
                self._n_keys.append(other._r_keys[keep])
                self._n_ts.append(other._r_ts[keep])
                self._n_cols.append(
                    other._r_obj[keep] if other._r_obj is not None
                    else [c[keep] for c in other._r_cols])
            for nk, nt, nc in zip(other._n_keys, other._n_ts,
                                  other._n_cols):
                k2 = keep_fn(nk) if keep_fn is not None else None
                if k2 is None:
                    self._n_keys.append(nk)
                    self._n_ts.append(nt)
                    self._n_cols.append(nc)
                elif k2.any():
                    self._n_keys.append(nk[k2])
                    self._n_ts.append(nt[k2])
                    self._n_cols.append(
                        nc[k2] if not isinstance(nc, list)
                        else [c[k2] for c in nc])


def is_generic_eligible(assigner, aggregate_function, trigger, evictor,
                        allowed_lateness, late_tag,
                        window_function) -> bool:
    """Graph-builder gate for the generic vectorized tier: same shape
    constraints as the device gate (event-time aligned assigners,
    default trigger, no evictor, zero lateness) but for ANY Python
    AggregateFunction (ref: the one-operator-serves-all contract of
    WindowOperator.java:291-421)."""
    from flink_tpu.streaming.windowing import (
        EventTimeSessionWindows,
        SlidingEventTimeWindows,
        TumblingEventTimeWindows,
    )
    if trigger is not None or evictor is not None:
        return False
    if allowed_lateness != 0 or late_tag is not None:
        return False
    if window_function is not None and not callable(window_function):
        return False
    if isinstance(assigner, SlidingEventTimeWindows):
        return assigner.size % assigner.slide == 0 and assigner.offset == 0
    if isinstance(assigner, TumblingEventTimeWindows):
        return assigner.offset == 0
    return isinstance(assigner, EventTimeSessionWindows)


class GenericWindowOperator(StreamOperator):
    """Batched window operator for ARBITRARY Python AggregateFunctions
    — the DataStream-facing face of the generic log engines.  Buffers
    records, flushes them as columns into the engine, fires on
    watermarks; same lifecycle contract as DeviceWindowOperator
    (which serves DeviceAggregateFunction; this serves the rest)."""

    def __init__(self, assigner, aggregate_function,
                 window_function=None, flush_batch: int = 8192,
                 compact_threshold: int = 1 << 21,
                 force_scalar: bool = False):
        super().__init__()
        self.assigner = assigner
        self.agg = aggregate_function
        self.window_function = window_function
        self.flush_batch = flush_batch
        self.compact_threshold = compact_threshold
        #: pin the engine's per-record scalar fold even when the
        #: lift probe would accept the aggregate (see
        #: AggregateFunction.force_scalar for when that matters)
        self.force_scalar = force_scalar
        self.engine = None
        #: AOT liftability report (computed lazily, sentinel = unset)
        self._lift_report = False
        self._keys: List[Any] = []
        self._ts: List[int] = []
        self._values: List[Any] = []
        self._last_fireable = None
        self.num_late_records_dropped = 0

    # ---- lifecycle --------------------------------------------------
    def open(self):
        if generic_engine_for_assigner(self.assigner, self.agg) is None:
            raise ValueError(
                f"no generic engine for assigner {self.assigner!r}")
        self.collector = TimestampedCollector(self.output)
        if self.metrics is not None:
            ctr = self.metrics.counter("numLateRecordsDropped")
            ctr.count = 0
            g = self.metrics.add_group("lift")
            g.gauge("decision", lambda: (
                (self.engine.lift.mode if self.engine is not None
                 else None) or "undecided"))
            g.gauge("decided_by", lambda: (
                (self.engine.lift.decided_by if self.engine is not None
                 else None) or "undecided"))
            g.gauge("fallback_reason", lambda: (
                (self.engine.lift.fallback_reason
                 if self.engine is not None else None) or ""))

    def _static_verdict(self):
        """AOT liftability analysis of the aggregate (pass 2), cached;
        None when opted out (force_probe) or the analyzer errored."""
        if self._lift_report is False:
            self._lift_report = None
            if not self.force_scalar \
                    and not getattr(self.agg, "force_probe", False):
                try:
                    from flink_tpu.analysis.liftability import (
                        analyze_aggregate,
                    )
                    self._lift_report = analyze_aggregate(self.agg)
                except Exception:
                    self._lift_report = None
        return self._lift_report

    def set_key_context(self, record):
        pass  # keys resolve vectorized at flush

    def process_element(self, record):
        if record.timestamp is None:
            raise ValueError(
                "generic window operator requires event-time records "
                "(assign timestamps upstream)")
        self._keys.append(self.key_selector.get_key(record.value)
                          if self.key_selector is not None
                          else record.value)
        self._ts.append(record.timestamp)
        self._values.append(record.value)
        if len(self._keys) >= self.flush_batch:
            self._flush_buffer()

    def process_batch(self, batch):
        """Columnar ingest: a RecordBatch feeds the engine as ready
        columns — no StreamRecord boxing, no per-row buffer appends.
        Buffered scalar rows flush first (they predate the batch, and
        the engine must see rows in arrival order)."""
        n = len(batch)
        if n == 0:
            return
        if batch.ts is None or (batch.ts_mask is not None
                                and not batch.ts_mask.all()):
            # same contract as the scalar path: every row needs an
            # event timestamp
            raise ValueError(
                "generic window operator requires event-time records "
                "(assign timestamps upstream)")
        self._flush_buffer()
        self._ensure_engine()
        values = batch.row_values()
        keys_arr = self._batch_keys(batch, values)
        self.engine.process_batch(
            keys_arr, np.asarray(batch.ts, np.int64), values)
        self._note_columnar(n)

    def _batch_keys(self, batch, values):
        """Key column for a batch: a ready column when the selector is
        positional (or absent on scalar rows), else per-row get_key —
        always the exact keys the scalar path would have buffered."""
        from flink_tpu.core.functions import _FieldKeySelector
        sel = self.key_selector
        if sel is None and batch.is_scalar:
            return np.asarray(next(iter(batch.cols.values())))
        if isinstance(sel, _FieldKeySelector) \
                and type(sel._field) is int and not batch.is_scalar:
            col = batch.cols.get(f"f{sel._field}")
            if col is not None:
                return np.asarray(col)
        keys = ([sel.get_key(v) for v in values] if sel is not None
                else values)
        keys_arr = np.asarray(keys)
        if keys_arr.ndim != 1:
            karr = np.empty(len(keys), object)
            karr[:] = keys
            keys_arr = karr
        return keys_arr

    def _ensure_engine(self):
        if self.engine is None:
            self.engine = generic_engine_for_assigner(
                self.assigner, self.agg, self.compact_threshold)
            self.engine.lift.owner = self.operator_id or ""
            if self.force_scalar:
                self.engine.lift.mode = "scalar"
                self.engine.lift.decided_by = "pin"
                self.engine.lift.fallback_reason = "force_scalar"
            else:
                self.engine.lift.apply_static(self._static_verdict())

    def _flush_buffer(self):
        if not self._keys:
            return
        self._ensure_engine()
        keys_arr = np.asarray(self._keys)
        if keys_arr.ndim != 1:
            # composite keys stay object rows (sortable tuples)
            karr = np.empty(len(self._keys), object)
            karr[:] = self._keys
            keys_arr = karr
        self.engine.process_batch(
            keys_arr, np.asarray(self._ts, np.int64), self._values)
        self._keys.clear()
        self._ts.clear()
        self._values.clear()

    def process_watermark(self, watermark):
        from flink_tpu.streaming.elements import MAX_TIMESTAMP
        from flink_tpu.streaming.windowing import (
            SlidingEventTimeWindows,
            TumblingEventTimeWindows,
        )
        wm = watermark.timestamp
        grid = None
        if isinstance(self.assigner, SlidingEventTimeWindows):
            grid = self.assigner.slide
        elif isinstance(self.assigner, TumblingEventTimeWindows):
            grid = self.assigner.size
        if grid is not None and wm != MAX_TIMESTAMP:
            fireable = ((wm + 1) // grid) * grid if wm >= 0 else None
            if fireable is not None and fireable == self._last_fireable:
                self.current_watermark = wm
                self.output.emit_watermark(watermark)
                return
            self._last_fireable = fireable
        self._flush_buffer()
        if self.engine is not None:
            before = len(self.engine.emitted)
            self.engine.advance_watermark(wm)
            self._emit_from(before)
            self.num_late_records_dropped = self.engine.num_late_dropped
            if self.metrics is not None:
                self.metrics.counter(
                    "numLateRecordsDropped").count = \
                    self.engine.num_late_dropped
        self.current_watermark = wm
        self.output.emit_watermark(watermark)

    def _emit_from(self, start_idx: int):
        from flink_tpu.streaming.windowing import TimeWindow
        emitted = self.engine.emitted
        fn = self.window_function
        for key, result, w_start, w_end in emitted[start_idx:]:
            self.collector.set_absolute_timestamp(w_end - 1)
            if fn is None:
                self.collector.collect(result)
            else:
                out = fn(key, TimeWindow(w_start, w_end), [result])
                if out is not None:
                    for v in out:
                        self.collector.collect(v)
        del emitted[start_idx:]

    # ---- checkpoint -------------------------------------------------
    def snapshot_state(self, checkpoint_id=None) -> dict:
        self._flush_buffer()
        snap = StreamOperator.snapshot_state(self, checkpoint_id)
        if self.engine is not None:
            snap["generic_engine"] = self.engine.snapshot()
        return snap

    def restore_state(self, snapshots) -> None:
        StreamOperator.restore_state(self, snapshots)
        engine_snaps = [s["generic_engine"] for s in snapshots
                        if s.get("generic_engine") is not None]
        if not engine_snaps:
            return
        self._ensure_engine()
        rescaled = any(
            s.get("restore_old_parallelism", self.num_subtasks)
            != self.num_subtasks for s in snapshots)
        if rescaled or len(engine_snaps) > 1 or self.num_subtasks > 1:
            from flink_tpu.core.keygroups import make_key_group_keep_fn
            keep_fn = make_key_group_keep_fn(
                self.max_parallelism, self.num_subtasks,
                self.subtask_index)
            self.engine.restore_many(engine_snaps, keep_fn)
        else:
            self.engine.restore(engine_snaps[0])
        if self.force_scalar:
            # the pin outranks a checkpoint taken without it
            self.engine.lift.mode = "scalar"
            self.engine.lift.decided_by = "pin"
            self.engine.lift.fallback_reason = "force_scalar"


def generic_engine_for_assigner(assigner, aggregate,
                                compact_threshold: int = 1 << 21):
    """Assigner → generic log engine, or None when the assigner shape
    has no generic tier (custom assigners stay on the scalar path)."""
    from flink_tpu.streaming.windowing import (
        EventTimeSessionWindows,
        SlidingEventTimeWindows,
        TumblingEventTimeWindows,
    )
    if isinstance(assigner, TumblingEventTimeWindows) \
            and assigner.offset == 0:
        return GenericLogTumblingWindows(
            aggregate, assigner.size, compact_threshold)
    if isinstance(assigner, SlidingEventTimeWindows) \
            and assigner.offset == 0 \
            and assigner.size % assigner.slide == 0:
        return GenericLogSlidingWindows(
            aggregate, assigner.size, assigner.slide, compact_threshold)
    if isinstance(assigner, EventTimeSessionWindows):
        return GenericLogSessionWindows(
            aggregate, assigner.gap, compact_threshold)
    return None
