"""Windowed heavy hitters: Count-Min estimates + candidate tracking.

The consumer of CountMinSketchAggregate.point_query promised by
flink_tpu/ops/sketches.py: per (key, window) the device keeps a
Count-Min sketch (frequencies of items within the key's stream), the
host keeps the bounded set of DISTINCT (key, item) candidates seen in
the window (a sketch can estimate but not enumerate), and at fire time
one batched device point_query estimates every candidate's frequency;
items with est >= phi * total (or the top-k by estimate) emit as the
window's heavy hitters.

This is the batched re-design of what the reference would express as a
ProcessWindowFunction iterating buffered elements (there is no sketch
library in Flink 1.5; the per-element buffering path is
EvictingWindowOperator's ListState).  Here ingestion stays O(1) device
work per record (CM scatter, flink_tpu.ops.sketches) and the candidate
set costs one vectorized slot-index pass per batch — no per-record
host loops (BASELINE.md config #4 shape).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from flink_tpu.ops.sketches import CountMinSketchAggregate
from flink_tpu.streaming.vectorized import (
    VectorizedTumblingWindows,
    hash_keys_np,
    make_slot_index,
)


class _Candidates:
    """Distinct (key, item) pairs of one window, vectorized dedupe."""

    __slots__ = ("index", "key_hashes", "item_his", "item_los",
                 "keys", "items", "count")

    def __init__(self):
        self.index = make_slot_index(1 << 10)
        self.key_hashes: List[np.ndarray] = []
        self.item_his: List[np.ndarray] = []
        self.item_los: List[np.ndarray] = []
        self.keys: List[Any] = []
        self.items: List[Any] = []
        self.count = 0

    def add_batch(self, pair_hashes, key_hashes, item_hashes, keys, items):
        next_slot = [self.count]

        def alloc(n):
            out = np.arange(next_slot[0], next_slot[0] + n)
            next_slot[0] += n
            return out

        _, _, first_idx = self.index.lookup_or_insert(pair_hashes, alloc)
        self.count = next_slot[0]
        if len(first_idx):
            self.key_hashes.append(key_hashes[first_idx])
            ih = item_hashes[first_idx]
            self.item_his.append((ih >> np.uint64(32)).astype(np.uint32))
            self.item_los.append((ih & np.uint64(0xFFFFFFFF)).astype(np.uint32))
            self.keys.extend(np.asarray(keys, dtype=object)[first_idx].tolist())
            self.items.extend(np.asarray(items, dtype=object)[first_idx].tolist())


class WindowedHeavyHitters(VectorizedTumblingWindows):
    """keyBy(key).window(Tumbling).heavy_hitters(item, phi | k).

    emitted entries are (key, hitters, window_start, window_end) where
    hitters is a list of (item, estimated_count) sorted descending.
    """

    def __init__(self, window_size_ms: int, phi: Optional[float] = None,
                 k: Optional[int] = None, depth: int = 4, width: int = 2048,
                 initial_capacity: int = 1 << 14,
                 max_candidates_per_window: int = 1 << 22,
                 microbatch: int = 1 << 17):
        if phi is None and k is None:
            raise ValueError("need a phi threshold or a top-k bound")
        agg = CountMinSketchAggregate(depth=depth, width=width)
        super().__init__(agg, window_size_ms,
                         initial_capacity=initial_capacity,
                         microbatch=microbatch)
        self.phi = phi
        self.k = k
        self.max_candidates = max_candidates_per_window
        self._candidates: Dict[int, _Candidates] = {}
        self._jit_point_query = jax.jit(agg.point_query)
        #: (key, [(item, est), ...], start, end)
        self.hh_emitted: List[Tuple[Any, list, int, int]] = []

    # ---- ingestion ---------------------------------------------------
    def process_items(self, keys, timestamps, items,
                      weights: Optional[np.ndarray] = None) -> None:
        """One batch of (key, item[, weight]) records."""
        ts = np.asarray(timestamps, np.int64)
        kh = hash_keys_np(keys)
        ih = hash_keys_np(items)
        if weights is None:
            weights = np.ones(len(ts), np.float32)
        starts = ts - np.mod(ts, self.size)
        live = starts + self.lateness_horizon - 1 > self.watermark
        pair = kh * np.uint64(0x9E3779B97F4A7C15) ^ ih
        for start in np.unique(starts[live]).tolist():
            m = (starts == start) & live
            cand = self._candidates.get(start)
            if cand is None:
                cand = _Candidates()
                self._candidates[start] = cand
            cand.add_batch(pair[m], kh[m], ih[m],
                           np.asarray(keys, dtype=object)[m],
                           np.asarray(items, dtype=object)[m])
            if cand.count > self.max_candidates:
                raise RuntimeError(
                    f"window {start}: > {self.max_candidates} distinct "
                    f"(key, item) candidates; raise "
                    f"max_candidates_per_window or pre-aggregate")
        self.process_batch(keys, ts, values=weights, key_hashes=kh,
                           value_hashes=ih)

    # ---- firing ------------------------------------------------------
    def advance_watermark(self, watermark: int) -> int:
        # query candidates of every due window BEFORE the engine fires
        # (fire clears the device state)
        self.flush()
        for start in sorted(self._candidates):
            if start + self.size - 1 > watermark:
                continue
            self._query_window(start, self._candidates.pop(start))
        return super().advance_watermark(watermark)

    def _query_window(self, start: int, cand: _Candidates) -> None:
        shard = self.windows.get(start)
        if shard is None or cand.count == 0:
            return
        key_hashes = (np.concatenate(cand.key_hashes)
                      if len(cand.key_hashes) > 1 else cand.key_hashes[0])
        ihi = (np.concatenate(cand.item_his)
               if len(cand.item_his) > 1 else cand.item_his[0])
        ilo = (np.concatenate(cand.item_los)
               if len(cand.item_los) > 1 else cand.item_los[0])
        # keys are already present in the shard index: lookup only
        slots, _, first_idx = shard.index.lookup_or_insert(
            key_hashes, self.arena.alloc)
        assert len(first_idx) == 0, "candidate key missing from window index"
        ests = np.asarray(self._jit_point_query(
            self.state, slots.astype(np.int32), ihi, ilo))
        totals = np.asarray(self._jit_result(
            self.state, slots.astype(np.int32)))
        # group candidates per key and select
        per_key: Dict[Any, list] = {}
        for i in range(cand.count):
            est = float(ests[i])
            if self.phi is not None and est < self.phi * float(totals[i]):
                continue
            per_key.setdefault(cand.keys[i], []).append((cand.items[i], est))
        end = start + self.size
        for key, hitters in per_key.items():
            hitters.sort(key=lambda kv: -kv[1])
            if self.k is not None:
                hitters = hitters[:self.k]
            self.hh_emitted.append((key, hitters, start, end))
