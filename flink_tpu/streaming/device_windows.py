"""Fully device-resident tumbling-window aggregation.

The fastest path in the framework: key→slot resolution happens ON the
TPU in an HBM hash table (flink_tpu.ops.device_table), fused into the
same XLA program as the aggregation scatter — per micro-batch the host
ships only raw key/value hash lanes and gets back an overflow counter.
Compare the reference's per-record paths (hashmap probe per record in
HeapAggregatingState.java:80-89; two JNI hops per record in
RocksDBAggregatingState.java:108-131) and the host-indexed engine in
flink_tpu/streaming/vectorized.py whose searchsorted/np.unique work
this removes.

Keys must be 64-bit integers (or anything the caller pre-hashes
injectively): the table stores the ORIGINAL key lanes, so window fires
reconstruct exact keys from the table — no host-side key dictionary.
Non-integer keys use the host-indexed engine instead.

Per live window: one DeviceHashTable + one state arena (table position
= state slot).  Tumbling windows keep 1-2 windows live, so per-window
arenas cost little and firing frees the whole window at once.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.ops.device_agg import DeviceAggregateFunction
from flink_tpu.ops.device_table import (
    DeviceHashTable,
    insert_or_lookup_impl,
    make_table,
)


class _DeviceWindow:
    __slots__ = ("start", "table", "state")

    def __init__(self, start: int, table: DeviceHashTable, state: dict):
        self.start = start
        self.table = table
        self.state = state


class DeviceTumblingWindows:
    """keyBy().window(Tumbling).aggregate(agg) with on-device key index.

    API: `process_batch(key_lanes..., value_hashes..., values, ts)` then
    `advance_watermark(wm)`; results come back as numpy arrays
    (keys reconstructed from the device table)."""

    def __init__(self, agg: DeviceAggregateFunction, window_size_ms: int,
                 capacity: int = 1 << 20, max_probes: int = 128,
                 fire_tile: int = 1 << 18):
        self.agg = agg
        self.size = window_size_ms
        self.capacity = capacity
        self.max_probes = max_probes
        self.fire_tile = fire_tile
        self.watermark = -(2**63)
        self.windows: Dict[int, _DeviceWindow] = {}
        self.num_late_dropped = 0
        self.overflowed = 0
        #: (keys[np.uint64], results[np], start, end) per fired window
        self.fired: List[Tuple[np.ndarray, np.ndarray, int, int]] = []

        def fused_step(table, state, k_hi, k_lo, values, vh_hi, vh_lo, mask):
            table, slots, ok = insert_or_lookup_impl(
                table, k_hi, k_lo, mask, max_probes=self.max_probes)
            eff = mask & ok & (slots >= 0)
            safe = jnp.where(slots >= 0, slots, 0)
            state = self.agg.update(state, safe, values, vh_hi, vh_lo, eff)
            overflow = (mask & ~ok).sum()
            return table, state, overflow

        self._jit_step = jax.jit(fused_step, donate_argnums=(0, 1))

        def fire_tile_fn(state, slots):
            return self.agg.result(state, slots)

        self._jit_fire = jax.jit(fire_tile_fn)

    def _new_window(self, start: int) -> _DeviceWindow:
        return _DeviceWindow(
            int(start), make_table(self.capacity),
            self.agg.init_state(self.capacity))

    # ---- ingestion --------------------------------------------------
    def process_batch(self, key_hi: np.ndarray, key_lo: np.ndarray,
                      timestamps: np.ndarray,
                      values: Optional[np.ndarray] = None,
                      vh_hi: Optional[np.ndarray] = None,
                      vh_lo: Optional[np.ndarray] = None) -> None:
        ts = np.asarray(timestamps, np.int64)
        starts = ts - np.mod(ts, self.size)
        live = starts + self.size - 1 > self.watermark
        if not live.all():
            self.num_late_dropped += int((~live).sum())
        dummy = np.zeros(1, np.uint32)
        for start in np.unique(starts[live]):
            w = self.windows.get(start)
            if w is None:
                w = self._new_window(int(start))
                self.windows[int(start)] = w
            mask = (starts == start) & live
            # pad the selection to the next power of two — stable shapes,
            # one compile per size bucket instead of one per distinct
            # batch/straddle length (full batches included: a raw-length
            # fast path would recompile for every new batch size)
            n_sel = int(mask.sum())
            padded = 1 << max(0, (n_sel - 1)).bit_length()

            def pad(a, dtype):
                out = np.zeros(padded, dtype)
                out[:n_sel] = a[mask]
                return out

            k_hi = pad(key_hi, np.uint32)
            k_lo = pad(key_lo, np.uint32)
            m = np.zeros(padded, bool)
            m[:n_sel] = True
            vals = (pad(np.asarray(values, self.agg.value_dtype),
                        self.agg.value_dtype)
                    if self.agg.needs_value else
                    np.zeros(1, self.agg.value_dtype))
            hh = pad(vh_hi, np.uint32) if self.agg.needs_value_hash else dummy
            hl = pad(vh_lo, np.uint32) if self.agg.needs_value_hash else dummy
            w.table, w.state, overflow = self._jit_step(
                w.table, w.state, k_hi, k_lo, vals, hh, hl, m)
            # overflow is a device scalar; defer the sync to fire time
            self._pending_overflow = getattr(self, "_pending_overflow", [])
            self._pending_overflow.append(overflow)

    # ---- firing -----------------------------------------------------
    def advance_watermark(self, watermark: int) -> int:
        self.watermark = watermark
        for ov in getattr(self, "_pending_overflow", []):
            self.overflowed += int(np.asarray(ov))
        self._pending_overflow = []
        fired_total = 0
        for start in sorted(self.windows):
            if start + self.size - 1 > watermark:
                continue
            w = self.windows.pop(start)
            # gather every table position's result, tiled
            futures = []
            for i in range(0, self.capacity, self.fire_tile):
                slots = jnp.arange(i, min(i + self.fire_tile, self.capacity),
                                   dtype=jnp.int32)
                futures.append(self._jit_fire(w.state, slots))
            results = np.concatenate([np.asarray(f) for f in futures])
            occ = np.asarray(w.table.occupied)
            hi = np.asarray(w.table.key_hi)[occ].astype(np.uint64)
            lo = np.asarray(w.table.key_lo)[occ].astype(np.uint64)
            keys = (hi << np.uint64(32)) | lo
            self.fired.append((keys, results[occ], start, start + self.size))
            fired_total += int(occ.sum())
        return fired_total

    def block_until_ready(self) -> None:
        for w in self.windows.values():
            jax.tree_util.tree_map(lambda a: a.block_until_ready(), w.state)


def lanes_from_int_keys(keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Original int64/uint64 keys → (hi, lo) uint32 lanes (identity
    encoding — fires reconstruct the exact keys)."""
    k = np.asarray(keys).astype(np.uint64)
    return ((k >> np.uint64(32)).astype(np.uint32),
            (k & np.uint64(0xFFFFFFFF)).astype(np.uint32))
