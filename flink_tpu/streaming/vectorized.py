"""Vectorized tumbling-window aggregation engine — the TPU hot path.

This is the performance centerpiece (SURVEY.md §7 stage 1, BASELINE.md
north star): where the reference walks one record at a time through
WindowOperator.processElement → HeapAggregatingState.add (hashmap
probe) or RocksDBAggregatingState.add (two JNI hops + serde,
RocksDBAggregatingState.java:108-131), this engine consumes whole
record batches:

  host:   vectorized key hashing (numpy), vectorized window
          assignment (ts - ts % size), slot resolution via
          searchsorted over sorted hash arrays (no Python dict on the
          hot path),
  device: ONE jit-compiled scatter per micro-batch updating the whole
          key-group range's accumulators in HBM
          (add/max/min combiner per DeviceAggregateFunction), and ONE
          gather per window fire.

Semantics match WindowOperator + EventTimeTrigger for tumbling
event-time windows with allowed_lateness=0 (the batched counterpart of
the scalar operator — differentially tested against it).  Sliding
windows reduce to this engine by pane replication; session windows
stay on the scalar operator (they merge, SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.core.keygroups import splitmix64_np, stable_hash64
from flink_tpu.ops.device_agg import DeviceAggregateFunction
from flink_tpu.ops.hashing import split_hash64_np
from flink_tpu.runtime.device_stats import TELEMETRY
from flink_tpu.runtime.tracing import traced_jit

_perf_ns = time.perf_counter_ns


def hash_keys_np(keys) -> np.ndarray:
    """Vectorized stable 64-bit key hashing: integer arrays go through
    splitmix64 in one numpy pass; object arrays fall back to per-key
    stable_hash64 (paid once per record batch, not per state access).
    Uniform numeric TUPLES (composite keys / distinct-count over
    composites) arrive as a 2-D array — per-column hashes combine
    order-sensitively into one 64-bit hash per row."""
    arr = np.asarray(keys)
    if arr.dtype.kind == "f" and arr.size \
            and np.all(arr == arr.astype(np.int64)):
        arr = arr.astype(np.int64)
    if arr.dtype.kind in "iu":
        if arr.ndim == 1:
            try:
                import flink_tpu.native as nat
                if nat.available():
                    return nat.splitmix64(arr.astype(np.uint64,
                                                     copy=False))
            except Exception:  # noqa: BLE001 — numpy twin below
                pass
            return splitmix64_np(arr.astype(np.uint64))
        h = np.zeros(len(arr), np.uint64)
        for j in range(arr.shape[1]):
            h = splitmix64_np(
                h ^ splitmix64_np(arr[:, j].astype(np.uint64))
                ^ np.uint64(0x9E3779B97F4A7C15 * (j + 1) & (2**64 - 1)))
        return h
    if arr.ndim > 1:
        return np.fromiter((stable_hash64(tuple(r)) for r in arr),
                           dtype=np.uint64, count=len(arr))
    return np.fromiter((stable_hash64(k) for k in arr),
                       dtype=np.uint64, count=len(arr))


_EMPTY = np.uint64(0)
_ZERO_REMAP = np.uint64(0x9E3779B97F4A7C15)


class VectorizedSlotIndex:
    """hash64 → dense slot via a vectorized open-addressing table.

    The replacement for the per-record dict probe: a whole batch
    resolves in a handful of numpy gather/compare rounds over a
    linear-probing table (load kept < 0.6).  A steady-state batch (all
    keys known, few collisions) costs ~2 vector passes — far cheaper
    per record than the reference heap backend's per-record hashmap
    probe, and ~4x cheaper than binary search over a sorted array
    (random binary searches are cache-miss bound).

    Intra-batch insert races resolve exactly like the device table
    (flink_tpu.ops.device_table): unresolved records write their hash
    at their probe position, re-read to find winners, losers advance.
    Slots are handed out by an external allocator callback so multiple
    windows share one device-state arena."""

    __slots__ = ("table_hash", "table_slot", "cap", "n")

    def __init__(self, capacity: int = 1 << 12):
        cap = 1 << max(4, (capacity - 1).bit_length())
        self.table_hash = np.zeros(cap, np.uint64)   # 0 = empty
        self.table_slot = np.zeros(cap, np.int64)
        self.cap = cap
        self.n = 0

    def _pos0(self, h: np.ndarray) -> np.ndarray:
        return ((h ^ (h >> np.uint64(32)))
                & np.uint64(self.cap - 1)).astype(np.int64)

    def _grow(self, need: int) -> None:
        new_cap = self.cap
        while (self.n + need) * 5 > new_cap * 3:   # load < 0.6
            new_cap *= 2
        if new_cap == self.cap:
            return
        old_hash, old_slot = self.table_hash, self.table_slot
        occ = old_hash != _EMPTY
        self.table_hash = np.zeros(new_cap, np.uint64)
        self.table_slot = np.zeros(new_cap, np.int64)
        self.cap = new_cap
        self.n = 0
        if occ.any():
            self._insert_existing(old_hash[occ], old_slot[occ])

    def _insert_existing(self, hashes: np.ndarray, slots: np.ndarray) -> None:
        """Rehash unique entries into the (empty, larger) table."""
        pos = self._pos0(hashes)
        pending = np.arange(len(hashes))
        mask_c = np.int64(self.cap - 1)
        while len(pending):
            pi = pos[pending]
            empty = self.table_hash[pi] == _EMPTY
            idx = pending[empty]
            if len(idx):
                self.table_hash[pos[idx]] = hashes[idx]
                won = self.table_hash[pos[idx]] == hashes[idx]
                w = idx[won]
                self.table_slot[pos[w]] = slots[w]
                self.n += len(w)
                done = np.zeros(len(hashes), bool)
                done[w] = True
                pending = pending[~done[pending]]
            if len(pending):
                pos[pending] = (pos[pending] + 1) & mask_c

    def lookup_or_insert(
        self, batch_hashes: np.ndarray,
        alloc: Callable[[int], np.ndarray],
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Resolve a batch to slots; new keys get slots from `alloc`.
        Returns (slots[N] int64, new_mask_over_new_uniques (all True),
        first_idx) where first_idx gives, for each inserted unique
        hash, one position in the batch holding that key (for
        first-seen key capture)."""
        h = np.where(batch_hashes == _EMPTY, _ZERO_REMAP, batch_hashes)
        self._grow(len(h))
        n = len(h)
        out = np.full(n, -1, np.int64)
        pos = self._pos0(h)
        pending = np.arange(n)
        mask_c = np.int64(self.cap - 1)
        new_first: List[np.ndarray] = []
        while len(pending):
            hp = h[pending]
            p = pos[pending]
            cur = self.table_hash[p]
            match = cur == hp
            if match.any():
                m = pending[match]
                out[m] = self.table_slot[pos[m]]
            empty = cur == _EMPTY
            if empty.any():
                idx = pending[empty]
                pi = pos[idx]
                # last-write-wins per position; re-read to find winners
                self.table_hash[pi] = h[idx]
                won = self.table_hash[pi] == h[idx]
                w = idx[won]
                if len(w):
                    # dedupe winners sharing a position AND hash (batch
                    # duplicates): keep the first per position
                    pw, first_per_pos = np.unique(pos[w], return_index=True)
                    w = w[first_per_pos]
                    new_slots = alloc(len(w))
                    self.table_slot[pos[w]] = new_slots
                    out[w] = new_slots
                    self.n += len(w)
                    new_first.append(w)
            resolved = out[pending] >= 0
            pending = pending[~resolved]
            if len(pending):
                # duplicates of a just-inserted key re-check their
                # current position (it now matches); others advance
                cur2 = self.table_hash[pos[pending]]
                advance = pending[cur2 != h[pending]]
                pos[advance] = (pos[advance] + 1) & mask_c
        if new_first:
            first_idx = np.concatenate(new_first)
        else:
            first_idx = np.zeros(0, np.int64)
        return out, np.ones(len(first_idx), bool), first_idx


def make_slot_index(capacity: int = 1 << 12):
    """Fastest available slot index: the C++ open-addressing table
    (flink_tpu.native.NativeSlotIndex, ~10-30x the numpy passes) when
    the native runtime built, else the numpy VectorizedSlotIndex."""
    try:
        import flink_tpu.native as nat
        if nat.available():
            return nat.NativeSlotIndex(capacity)
    except Exception:  # noqa: BLE001
        pass
    return VectorizedSlotIndex(capacity)


class _SlotArena:
    """Dense slot allocator over the device-state arrays."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self.next = 0
        self.free: List[np.ndarray] = []  # freed slot arrays

    def alloc(self, n: int) -> np.ndarray:
        out = np.empty(n, np.int64)
        filled = 0
        while self.free and filled < n:
            chunk = self.free[-1]
            take = min(len(chunk), n - filled)
            out[filled:filled + take] = chunk[:take]
            if take == len(chunk):
                self.free.pop()
            else:
                self.free[-1] = chunk[take:]
            filled += take
        fresh = n - filled
        if fresh:
            out[filled:] = np.arange(self.next, self.next + fresh)
            self.next += fresh
        return out

    def release(self, slots: np.ndarray) -> None:
        if len(slots):
            self.free.append(np.asarray(slots, np.int64))

    @property
    def high_water(self) -> int:
        return self.next

    @property
    def live_count(self) -> int:
        """Slots currently allocated (handed out and not released)."""
        return self.next - sum(len(c) for c in self.free)


def pad_pow2(a: np.ndarray, fill) -> np.ndarray:
    """Pad to the next power of two (stable jit shapes)."""
    n = len(a)
    padded = 1 << max(0, (n - 1)).bit_length()
    out = np.full(padded, fill, a.dtype)
    out[:n] = a
    return out


def make_masked_update(agg: DeviceAggregateFunction):
    """Jitted scatter-update where the mask derives on device from the
    live count — one scalar over the wire instead of a bool array."""

    def update_fn(state, slots, values, hi, lo, n):
        mask = jnp.arange(slots.shape[0], dtype=jnp.int32) < n
        return agg.update(state, slots, values, hi, lo, mask)

    return traced_jit(update_fn, name="window.masked_update",
                      donate_argnums=0)


class _ScratchMergeMixin:
    """Device-side slot merging shared by the sliding and session
    engines: state[dst] ⊕= state[src] in one jit call, padded to
    power-of-two shapes with a sacrificial scratch slot (allocated from
    the arena, never gathered).  Requires self.agg / self.arena /
    self.state / self._jit_merge and a _ensure_state_capacity hook."""

    _scratch_slot_id: Optional[int] = None

    def _scratch(self) -> int:
        if self._scratch_slot_id is None:
            self._scratch_slot_id = int(self.arena.alloc(1)[0])
        return self._scratch_slot_id

    def _ensure_state_capacity(self) -> None:
        """Grow the device arrays if the arena outran them — fire-time
        union allocations bypass the ingest-path growth check, and an
        out-of-bounds scatter under jit drops writes SILENTLY."""
        if self.arena.high_water > self.capacity:
            new_cap = max(self.capacity * 2,
                          1 << (self.arena.high_water - 1).bit_length())
            self.state = self.agg.grow_state(self.state, new_cap)
            self.capacity = new_cap

    def _merge_tiled(self, dst, src) -> None:
        n = len(dst)
        if n == 0:
            return
        self._ensure_state_capacity()
        scratch = self._scratch()
        d = pad_pow2(np.asarray(dst, np.int32), scratch)
        s = pad_pow2(np.asarray(src, np.int32), scratch)
        self.state = self._jit_merge(self.state, jnp.asarray(d),
                                     jnp.asarray(s))


class _WindowShard:
    """Per-live-window bookkeeping: its own slot index + first-seen
    keys (and their hashes, for cross-window merging), all slots drawn
    from the shared arena.  Keys stay as numpy arrays end to end —
    per-record Python boxing (.tolist) measurably dominates the host
    side of the ingest loop at 1M+ keys/window."""

    __slots__ = ("start", "index", "key_list", "slot_list", "hash_list")

    def __init__(self, start: int):
        self.start = start
        self.index = make_slot_index()
        self.key_list: List[np.ndarray] = []
        self.slot_list: List[np.ndarray] = []
        self.hash_list: List[np.ndarray] = []

    @property
    def n_keys(self) -> int:
        return sum(len(a) for a in self.key_list)

    def all_keys(self) -> np.ndarray:
        if not self.key_list:
            return np.empty(0, object)
        if len(self.key_list) > 1:
            self.key_list = [np.concatenate(self.key_list)]
        return self.key_list[0]

    def all_slots(self) -> np.ndarray:
        if not self.slot_list:
            return np.empty(0, np.int64)
        if len(self.slot_list) > 1:
            self.slot_list = [np.concatenate(self.slot_list)]
        return self.slot_list[0]

    def all_hashes(self) -> np.ndarray:
        if not self.hash_list:
            return np.empty(0, np.uint64)
        if len(self.hash_list) > 1:
            self.hash_list = [np.concatenate(self.hash_list)]
        return self.hash_list[0]


class VectorizedTumblingWindows:
    """Batched keyBy().window(Tumbling...).aggregate(device_agg)."""

    def __init__(self, aggregate: DeviceAggregateFunction, window_size_ms: int,
                 initial_capacity: int = 1 << 16,
                 microbatch: int = 1 << 17,
                 emit: Optional[Callable[[Any, Any, int, int], None]] = None):
        self.agg = aggregate
        self.size = window_size_ms
        #: how far past a (pane) start a record stays live — subclasses
        #: with multi-pane windows widen this
        self.lateness_horizon = window_size_ms
        self.capacity = initial_capacity
        self.state = aggregate.init_state(initial_capacity)
        self.arena = _SlotArena(initial_capacity)
        self.windows: Dict[int, _WindowShard] = {}
        self.watermark = -(2**63)
        self.microbatch = microbatch
        #: emit(key, result, window_start, window_end); None → collect
        self.emit = emit
        self.emitted: List[Tuple[Any, Any, int, int]] = []
        #: True → skip per-key tuples; fires land in `fired` as
        #: (keys_np, results_np, start, end) batches, both in
        #: slot-sorted fire order
        self.emit_arrays = False
        self.fired: List[Tuple[np.ndarray, np.ndarray, int, int]] = []
        self.num_late_dropped = 0
        # pending micro-batch (pre-allocated growing buffers)
        self._p_slots: List[np.ndarray] = []
        self._p_values: List[np.ndarray] = []
        self._p_hi: List[np.ndarray] = []
        self._p_lo: List[np.ndarray] = []
        self._p_count = 0
        self._jit_update = make_masked_update(self.agg)
        self._jit_result = traced_jit(self.agg.result,
                                      name="window.result")
        self._jit_clear = traced_jit(self.agg.clear_slots,
                                     name="window.clear", donate_argnums=0)
        # contiguous fire fast path: slots handed out by the arena are
        # dense, so a full tile of consecutive slots fires as ONE
        # dynamic_slice + dense reduction instead of a row gather
        # (XLA gathers ~2.5M rows/s vs memory-bandwidth slicing)
        agg = self.agg

        def _result_contig(state, start, tile):
            sub = {k: jax.lax.dynamic_slice_in_dim(v, start, tile, 0)
                   for k, v in state.items()}
            return agg.result_dense(sub)

        self._jit_result_contig = traced_jit(_result_contig,
                                             name="window.result_contig",
                                             static_argnums=(2,))

        specs = agg.state_specs()

        def _clear_contig(state, start, tile):
            out = dict(state)
            for name, spec in specs.items():
                fill = jnp.full((tile, *spec.shape), spec.fill,
                                dtype=spec.dtype)
                out[name] = jax.lax.dynamic_update_slice_in_dim(
                    out[name], fill, start, 0)
            return out

        self._jit_clear_contig = traced_jit(_clear_contig,
                                            name="window.clear_contig",
                                            static_argnums=(2,),
                                            donate_argnums=0)
        # full-arena fire: when the fired window owns EVERY live slot
        # (the steady tumbling cadence — one window live at a time) and
        # covers enough of the arena, one fused full-array reduce beats
        # tiled dynamic-slice gathers (a [tile, m] dynamic_slice out of
        # a multi-GB array materializes unfused, ~4x the bandwidth cost
        # — measured, BENCH_NOTES.md), and the clear becomes one
        # donated full fill at write bandwidth
        self._jit_result_all = traced_jit(agg.result_dense,
                                          name="window.result_all")
        # fire/clear tile bounded by BYTES not slot count: a gather or
        # clear materializes [tile, *slot_shape] intermediates, so wide
        # per-slot state (Count-Min: depth*width ints) must shrink the
        # tile (16GB HBM budget, ~256MB per intermediate)
        bytes_per_slot = max(
            sum(int(np.prod(spec.shape, dtype=np.int64)) * spec.dtype.itemsize
                for spec in aggregate.state_specs().values()), 1)
        budget = 256 << 20
        tile = 1 << max(9, (budget // bytes_per_slot).bit_length() - 1)
        self.FIRE_TILE = min(tile, type(self).FIRE_TILE)

    # ---- ingestion --------------------------------------------------
    def process_batch(
        self,
        keys,
        timestamps: np.ndarray,
        values: Optional[np.ndarray] = None,
        key_hashes: Optional[np.ndarray] = None,
        value_hashes: Optional[np.ndarray] = None,
    ) -> None:
        """One batch of records: assign windows, resolve slots, buffer
        the scatter. `keys` may be any sequence; pass `key_hashes` to
        skip hashing (e.g. when the exchange already hashed them)."""
        ts = np.asarray(timestamps, np.int64)
        kh = key_hashes if key_hashes is not None else hash_keys_np(keys)
        starts = ts - np.mod(ts, self.size)
        # drop late records (latest containing window's end <= watermark,
        # lateness 0); for tumbling the horizon is the window size, for
        # pane-based sliding it is the full window size over pane starts
        live = starts + self.lateness_horizon - 1 > self.watermark
        if not live.all():
            self.num_late_dropped += int((~live).sum())
            if not live.any():
                return
            ts, kh, starts = ts[live], kh[live], starts[live]
            # keep numeric dtype — boxing to object arrays is only for
            # non-array key sequences
            keys = (keys[live] if isinstance(keys, np.ndarray)
                    else np.asarray(keys, dtype=object)[live])
            if values is not None:
                values = np.asarray(values)[live]
            if value_hashes is not None:
                value_hashes = np.asarray(value_hashes)[live]

        if self.agg.needs_value_hash and value_hashes is None:
            value_hashes = hash_keys_np(values)

        keys_arr = keys if isinstance(keys, np.ndarray) else np.asarray(
            keys, dtype=object)
        uniq_starts = np.unique(starts)
        single_window = len(uniq_starts) == 1
        for start in uniq_starts:
            shard = self.windows.get(start)
            if shard is None:
                shard = _WindowShard(int(start))
                self.windows[int(start)] = shard
            if single_window:
                bh, masked_keys = kh, keys_arr
                m_values = values
                m_vhashes = value_hashes
            else:
                mask = starts == start
                bh = kh[mask]
                masked_keys = keys_arr[mask]
                m_values = None if values is None else np.asarray(values)[mask]
                m_vhashes = None if value_hashes is None else value_hashes[mask]
            slots, new_uniq, first_idx = shard.index.lookup_or_insert(
                bh, self.arena.alloc)
            if len(first_idx):
                shard.key_list.append(masked_keys[first_idx])
                shard.slot_list.append(np.asarray(slots[first_idx], np.int64))
                shard.hash_list.append(np.asarray(bh[first_idx], np.uint64))
            self._buffer(slots, m_values, m_vhashes)
        if self._p_count >= self.microbatch:
            self.flush()

    def _buffer(self, slots, values, value_hashes) -> None:
        self._p_slots.append(slots.astype(np.int32))
        if self.agg.needs_value:
            self._p_values.append(np.asarray(values, self.agg.value_dtype))
        if self.agg.needs_value_hash:
            hi, lo = split_hash64_np(value_hashes)
            self._p_hi.append(hi)
            self._p_lo.append(lo)
        self._p_count += len(slots)
        # grow device arrays before slots overflow capacity
        if self.arena.high_water > self.capacity:
            self.flush(grow_to=max(self.capacity * 2,
                                   1 << (self.arena.high_water - 1).bit_length()))

    def flush(self, grow_to: Optional[int] = None) -> None:
        if grow_to is not None and grow_to > self.capacity:
            # growing reallocates; flush pending first at old capacity
            # only if slots fit — otherwise grow first
            self.state = self.agg.grow_state(self.state, grow_to)
            self.capacity = grow_to
        if self._p_count == 0:
            return
        n = self._p_count
        padded = 1 << max(0, (n - 1)).bit_length()
        slots = np.zeros(padded, np.int32)
        np.concatenate(self._p_slots, out=slots[:n])
        # unused operands ship as broadcastable dummies — no transfer
        if self.agg.needs_value:
            values = np.zeros(padded, self.agg.value_dtype)
            np.concatenate(self._p_values, out=values[:n])
        else:
            values = np.zeros(1, self.agg.value_dtype)
        if self.agg.needs_value_hash:
            hi0 = np.concatenate(self._p_hi) if len(self._p_hi) > 1 else self._p_hi[0]
            lo0 = np.concatenate(self._p_lo) if len(self._p_lo) > 1 else self._p_lo[0]
            hi0, lo0 = self.agg.compress_value_hash(hi0, lo0)
            hi = np.zeros(padded, hi0.dtype)
            lo = np.zeros(padded, lo0.dtype)
            hi[:n] = hi0
            lo[:n] = lo0
        else:
            hi = np.zeros(1, np.uint32)
            lo = np.zeros(1, np.uint32)
        if TELEMETRY.enabled:
            t0 = _perf_ns()
            self.state = self._jit_update(self.state, slots, values, hi,
                                          lo, np.int32(n))
            TELEMETRY.record_transfer(
                "h2d", slots.nbytes + values.nbytes + hi.nbytes + lo.nbytes,
                t0, _perf_ns(), "window.flush")
            TELEMETRY.note_flush(n)
        else:
            self.state = self._jit_update(self.state, slots, values, hi,
                                          lo, np.int32(n))
        self._p_slots.clear()
        self._p_values.clear()
        self._p_hi.clear()
        self._p_lo.clear()
        self._p_count = 0

    # ---- firing -----------------------------------------------------
    #: gather/clear tile: fixed shape → one compile, bounded
    #: intermediates (HLL result materializes [TILE, m] floats)
    FIRE_TILE = 1 << 18

    def advance_watermark(self, watermark: int) -> int:
        """Fire every window whose end-1 <= watermark; returns the
        number of (key, window) results emitted.  Tiled device gathers
        (the TPU twin of onEventTime → emitWindowContents)."""
        self.watermark = watermark
        fired = 0
        for start in sorted(self.windows):
            if start + self.size - 1 > watermark:
                continue
            shard = self.windows.pop(start)
            self.flush()
            slots = shard.all_slots()
            if len(slots):
                end = start + self.size
                full = (len(slots) == self.arena.live_count
                        and 4 * len(slots) >= self.capacity)
                slots = self._emit_fire(shard.all_keys(), slots, start, end,
                                        full=full)
                fired += len(slots)
                if full:
                    # the fired results are already materialized on the
                    # host; DROP the register file before rebuilding it
                    # (a donated pure fill cannot alias its input —
                    # measured OOM at 2x arena — so peak must stay at
                    # one arena), then refill fresh at write bandwidth
                    self.state = None
                    self.state = self.agg.init_state(self.capacity)
                else:
                    self._clear_tiled(slots)
                self.arena.release(slots)
        if TELEMETRY.enabled:
            TELEMETRY.note_windows_fired(fired)
        return fired

    def _emit_fire(self, keys, slots: np.ndarray, start: int, end: int,
                   full: bool = False):
        """Fire (keys, slots) in slot-sorted order; returns the slots
        in fire order so callers clear/release the same layout.

        Slot order matters: a window's slots are a dense arena range
        (up to free-list fragmentation), so the sorted gather/clear
        collapses to dynamic-slice tiles (memory bandwidth) instead of
        row gathers (~2.5M rows/s); sorted release also keeps future
        allocations ascending, so the property is self-sustaining."""
        if len(slots) == 0:
            return slots
        keys = keys if isinstance(keys, np.ndarray) else np.asarray(
            keys, dtype=object)
        order = np.argsort(slots, kind="stable")
        slots = slots[order]
        keys = keys[order]
        if full:
            # one fused reduce over the whole state (no slice
            # materialization), one D2H of the per-slot results,
            # host-side fancy index into fire order
            if TELEMETRY.enabled:
                t0 = _perf_ns()
                res_all = np.asarray(self._jit_result_all(self.state))
                TELEMETRY.record_transfer("d2h", res_all.nbytes,
                                          t0, _perf_ns(), "window.fire")
                TELEMETRY.note_fire_read()
                results = res_all[slots]
            else:
                results = np.asarray(self._jit_result_all(self.state))[slots]
        if self.emit_arrays:
            self.fired.append((keys,
                               results if full
                               else self._gather_tiled_np(slots),
                               start, end))
        elif self.emit is not None:
            res_list = (results.tolist() if full
                        else self._gather_tiled(slots))
            for key, res in zip(keys, res_list):
                self.emit(key, res, start, end)
        else:
            res_list = (results.tolist() if full
                        else self._gather_tiled(slots))
            self.emitted.extend(zip(keys, res_list,
                                    [start] * len(slots), [end] * len(slots)))
        return slots

    def _is_contiguous_tile(self, chunk: np.ndarray, tile: int) -> bool:
        """Full tile of strictly consecutive slots, fully inside the
        current capacity — eligible for dynamic_slice fire/clear."""
        return (len(chunk) == tile
                and int(chunk[0]) + tile <= self.capacity
                and int(chunk[-1]) - int(chunk[0]) == tile - 1
                and np.array_equal(
                    chunk, np.arange(chunk[0], chunk[0] + tile,
                                     dtype=chunk.dtype)))

    def _fire_tile_future(self, chunk: np.ndarray, tile: int):
        """One tile's result future: contiguous full tiles take the
        dynamic-slice path; ragged/unordered tiles gather."""
        if self._is_contiguous_tile(chunk, tile):
            return self._jit_result_contig(self.state,
                                           np.int32(chunk[0]), tile)
        if len(chunk) < tile:
            padded = np.full(tile, chunk[0], np.int32)
            padded[:len(chunk)] = chunk
        else:
            padded = chunk.astype(np.int32)
        return self._jit_result(self.state, jnp.asarray(padded))

    def _gather_tiled(self, slots: np.ndarray) -> list:
        n = len(slots)
        tile = self.FIRE_TILE
        futures = []
        for i in range(0, n, tile):
            chunk = slots[i:i + tile]
            # dispatch all tiles before materializing any — transfers
            # overlap device compute on the async dispatch queue
            futures.append((self._fire_tile_future(chunk, tile),
                            len(chunk)))
        if TELEMETRY.enabled and futures:
            t0 = _perf_ns()
            outs = [np.asarray(f)[:ln] for f, ln in futures]
            TELEMETRY.record_transfer(
                "d2h", sum(o.nbytes for o in outs), t0, _perf_ns(),
                "window.fire")
            TELEMETRY.note_fire_read(len(futures))
        else:
            outs = [np.asarray(f)[:ln] for f, ln in futures]
        return np.concatenate(outs).tolist() if outs else []

    def _gather_tiled_np(self, slots: np.ndarray) -> np.ndarray:
        n = len(slots)
        tile = self.FIRE_TILE
        futures = []
        for i in range(0, n, tile):
            chunk = slots[i:i + tile]
            futures.append((self._fire_tile_future(chunk, tile),
                            len(chunk)))
        if TELEMETRY.enabled and futures:
            t0 = _perf_ns()
            outs = [np.asarray(f)[:ln] for f, ln in futures]
            TELEMETRY.record_transfer(
                "d2h", sum(o.nbytes for o in outs), t0, _perf_ns(),
                "window.fire")
            TELEMETRY.note_fire_read(len(futures))
            return np.concatenate(outs)
        return np.concatenate([np.asarray(f)[:ln] for f, ln in futures])

    def _clear_tiled(self, slots: np.ndarray) -> None:
        n = len(slots)
        tile = self.FIRE_TILE
        for i in range(0, n, tile):
            chunk = slots[i:i + tile]
            if self._is_contiguous_tile(chunk, tile):
                # contiguous: one dynamic_update_slice of the fill
                # block instead of a 4KB-per-row scatter
                self.state = self._jit_clear_contig(
                    self.state, np.int32(chunk[0]), tile)
                continue
            padded = np.full(tile, chunk[0], np.int32)
            padded[:len(chunk)] = chunk
            self.state = self._jit_clear(self.state, jnp.asarray(padded))

    def block_until_ready(self) -> None:
        jax.tree_util.tree_map(lambda a: a.block_until_ready(), self.state)


class ScalarHeapTumblingWindows:
    """The per-record heap baseline the north star measures against:
    dict-of-dicts accumulator tables updated one record at a time with
    the scalar AggregateFunction contract — the same work
    HeapAggregatingState.add does (HeapAggregatingState.java:80-89)."""

    def __init__(self, aggregate, window_size_ms: int,
                 emit: Optional[Callable] = None):
        self.agg = aggregate
        self.size = window_size_ms
        self.windows: Dict[int, Dict[Any, Any]] = {}
        self.watermark = -(2**63)
        self.emit = emit
        self.emitted: List[Tuple[Any, Any, int, int]] = []
        self.num_late_dropped = 0

    def process(self, key, timestamp: int, value=None) -> None:
        start = timestamp - timestamp % self.size
        if start + self.size - 1 <= self.watermark:
            self.num_late_dropped += 1
            return
        table = self.windows.get(start)
        if table is None:
            table = {}
            self.windows[start] = table
        acc = table.get(key)
        if acc is None:
            acc = self.agg.create_accumulator()
        table[key] = self.agg.add(value, acc)

    def advance_watermark(self, watermark: int) -> int:
        self.watermark = watermark
        fired = 0
        for start in sorted(self.windows):
            if start + self.size - 1 > watermark:
                continue
            table = self.windows.pop(start)
            end = start + self.size
            for key, acc in table.items():
                res = self.agg.get_result(acc)
                if self.emit is not None:
                    self.emit(key, res, start, end)
                else:
                    self.emitted.append((key, res, start, end))
            fired += len(table)
        return fired


class VectorizedSlidingWindows(_ScratchMergeMixin, VectorizedTumblingWindows):
    """Batched keyBy().window(SlidingEventTimeWindows).aggregate(agg) —
    pane-composed (config #3: 10s/1s t-digest at 10M keys).

    Where the reference writes each record into size/slide separate
    window states (WindowOperator.processElement loops the assigned
    windows, multiplying state and writes by the overlap factor —
    SlidingEventTimeWindows.assignWindows), this engine aggregates each
    record ONCE into its slide-sized pane and composes a window's
    result at fire time by merging its size/slide panes on device
    (agg.merge_slots — mergeability is what the sketch kernels are
    built around).  Ingest cost is tumbling-at-slide-granularity
    regardless of overlap; the overlap factor is paid only on the
    per-key fire path, as device merges.

    Semantics match WindowOperator + SlidingEventTimeWindows with
    lateness 0, differentially tested against the scalar operator."""

    def __init__(self, aggregate: DeviceAggregateFunction,
                 window_size_ms: int, slide_ms: int,
                 initial_capacity: int = 1 << 16,
                 microbatch: int = 1 << 17,
                 emit: Optional[Callable[[Any, Any, int, int], None]] = None):
        if window_size_ms % slide_ms != 0:
            raise ValueError("window size must be a multiple of the slide "
                             "(pane composition; ref: the aligned-window "
                             "precondition)")
        super().__init__(aggregate, slide_ms, initial_capacity, microbatch,
                         emit)
        self.window_size = window_size_ms
        self.slide = slide_ms
        self.n_panes = window_size_ms // slide_ms
        self.lateness_horizon = window_size_ms
        self._fired_horizon = -(2**63)  # last watermark fires ran at
        self._jit_merge = traced_jit(self.agg.merge_slots,
                                     name="window.merge", donate_argnums=0)

    def advance_watermark(self, watermark: int) -> int:
        """Fire every sliding window with end-1 in
        (previous watermark, watermark]; prune panes no window needs."""
        prev = self._fired_horizon
        self._fired_horizon = watermark
        self.watermark = watermark
        self.flush()
        fired = 0
        if not self.windows:
            return 0
        # candidate window starts W on the slide grid with
        #   W + size - 1 <= wm      (due now)
        #   W + size - 1 > prev     (not fired on an earlier call)
        #   W >= min_pane - size + slide  (contains at least one pane)
        min_pane = min(self.windows)
        max_pane = max(self.windows)
        # no window starting after the last data-bearing pane holds data
        hi = min(watermark - self.window_size + 1, max_pane)
        start_from = max(min_pane - self.window_size + self.slide,
                         prev - self.window_size + 2)
        first = -(-start_from // self.slide) * self.slide  # ceil to grid
        if first > hi:
            self._prune_panes(watermark)
            return 0
        for W in range(first, hi + 1, self.slide):
            panes = [self.windows[p]
                     for p in range(W, W + self.window_size, self.slide)
                     if p in self.windows and self.windows[p].slot_list]
            if not panes:
                continue
            end = W + self.window_size
            if len(panes) == 1:
                # single-pane window: gather straight from pane slots
                shard = panes[0]
                slots = shard.all_slots()
                keys = shard.all_keys()
                self._emit_fire(keys, slots, W, end)
                fired += len(slots)
                continue
            # union the panes' keys into fresh fire slots, merging on
            # device pane by pane
            union_index = make_slot_index(
                sum(p.n_keys for p in panes))
            union_key_list: List[np.ndarray] = []
            union_slot_list: List[np.ndarray] = []
            for shard in panes:
                ph = shard.all_hashes()
                pslots = shard.all_slots()
                uslots, _, first_idx = union_index.lookup_or_insert(
                    ph, self.arena.alloc)
                if len(first_idx):
                    union_key_list.append(shard.all_keys()[first_idx])
                    union_slot_list.append(uslots[first_idx])
                self._merge_tiled(uslots, pslots)
            union_slots = (np.concatenate(union_slot_list)
                           if union_slot_list else np.empty(0, np.int64))
            union_keys = (np.concatenate(union_key_list)
                          if union_key_list else np.empty(0, object))
            union_slots = self._emit_fire(union_keys, union_slots, W, end)
            fired += len(union_slots)
            self._clear_tiled(union_slots)
            self.arena.release(union_slots)
        self._prune_panes(watermark)
        if TELEMETRY.enabled:
            TELEMETRY.note_windows_fired(fired)
        return fired

    def _prune_panes(self, watermark: int) -> None:
        """Pane [P, P+slide) is dead once its last containing window
        [P, P+size) fired, i.e. watermark >= P+size-1."""
        for P in sorted(self.windows):
            if P + self.window_size - 1 > watermark:
                break
            shard = self.windows.pop(P)
            slots = shard.all_slots()
            if len(slots):
                slots = np.sort(slots)
                self._clear_tiled(slots)
                self.arena.release(slots)


# ---------------------------------------------------------------------
# engine snapshots (checkpoint integration for DeviceWindowOperator)
# ---------------------------------------------------------------------

def _snapshot_arena(arena: _SlotArena) -> dict:
    return {"capacity": arena.capacity, "next": arena.next,
            "free": [np.array(a, np.int64) for a in arena.free]}


def _restore_arena(snap: dict) -> _SlotArena:
    arena = _SlotArena(snap["capacity"])
    arena.next = snap["next"]
    arena.free = [np.array(a, np.int64) for a in snap["free"]]
    return arena


def _snapshot_shard(sh: _WindowShard) -> dict:
    # index state snapshots as occupied (hash, slot) pairs — a format
    # both index implementations (numpy / native C++) restore from
    if hasattr(sh.index, "export"):
        ih, isl = sh.index.export()
    else:
        occ = sh.index.table_hash != _EMPTY
        ih = sh.index.table_hash[occ].copy()
        isl = sh.index.table_slot[occ].copy()
    return {"start": sh.start, "keys": sh.all_keys().copy(),
            "slots": sh.all_slots().copy(), "hashes": sh.all_hashes().copy(),
            "index_hashes": ih, "index_slots": isl}


def _restore_shard(snap: dict) -> _WindowShard:
    sh = _WindowShard(snap["start"])
    ks = snap["keys"]
    if not isinstance(ks, np.ndarray):  # legacy list-format snapshot
        arr = np.empty(len(ks), object)
        arr[:] = ks
        ks = arr
    sh.key_list = [ks] if len(ks) else []
    sh.slot_list = [np.array(snap["slots"], np.int64)]
    sh.hash_list = [np.array(snap["hashes"], np.uint64)]
    if "index_hash" in snap:  # legacy full-table snapshot format
        ih_t = np.array(snap["index_hash"], np.uint64)
        occ = ih_t != _EMPTY
        ih = ih_t[occ]
        isl = np.array(snap["index_slot"], np.int64)[occ]
    else:
        ih = np.array(snap["index_hashes"], np.uint64)
        isl = np.array(snap["index_slots"], np.int64)
    sh.index = make_slot_index(2 * max(len(ih), 8))
    if hasattr(sh.index, "set_bulk"):
        sh.index.set_bulk(ih, isl)
    else:
        sh.index._grow(len(ih))
        sh.index._insert_existing(ih, isl)
    return sh


def _tumbling_snapshot(self) -> dict:
    """Device state lands as host numpy (the device→host DMA half of
    the checkpoint, SURVEY §5 checkpoint row); host-side indexes ride
    along as plain arrays."""
    self.flush()
    if TELEMETRY.enabled:
        t0 = _perf_ns()
        host_state = {k: np.asarray(v) for k, v in self.state.items()}
        TELEMETRY.record_transfer(
            "d2h", sum(a.nbytes for a in host_state.values()),
            t0, _perf_ns(), "window.snapshot")
    else:
        host_state = {k: np.asarray(v) for k, v in self.state.items()}
    return {
        "state": host_state,
        "capacity": self.capacity,
        "arena": _snapshot_arena(self.arena),
        "watermark": self.watermark,
        "num_late_dropped": self.num_late_dropped,
        "windows": {int(s): _snapshot_shard(sh)
                    for s, sh in self.windows.items()},
        "fired_horizon": getattr(self, "_fired_horizon", None),
        "scratch": getattr(self, "_scratch_slot_id", None),
    }


def _tumbling_restore(self, snap: dict) -> None:
    self.capacity = snap["capacity"]
    self.state = {k: jnp.asarray(v) for k, v in snap["state"].items()}
    self.arena = _restore_arena(snap["arena"])
    self.watermark = snap["watermark"]
    self.num_late_dropped = snap["num_late_dropped"]
    self.windows = {int(s): _restore_shard(sh)
                    for s, sh in snap["windows"].items()}
    if snap.get("fired_horizon") is not None:
        self._fired_horizon = snap["fired_horizon"]
    if snap.get("scratch") is not None:
        self._scratch_slot_id = snap["scratch"]
    self._p_slots.clear()
    self._p_values.clear()
    self._p_hi.clear()
    self._p_lo.clear()
    self._p_count = 0


VectorizedTumblingWindows.snapshot = _tumbling_snapshot
VectorizedTumblingWindows.restore = _tumbling_restore
