"""DataStream API: fluent stream-graph building.

Re-designs flink-streaming-java/.../api/datastream/ (DataStream.java,
KeyedStream.java, WindowedStream.java:305-850, AllWindowedStream,
ConnectedStreams) and api/environment/StreamExecutionEnvironment.java
(execute :1508, getStreamGraph :1532).  SURVEY.md §2.9 lists the
surface this mirrors.

Naming is pythonic snake_case; the call shapes match the reference:
env.from_collection(...).key_by(...).time_window(Time.seconds(5))
   .aggregate(agg).add_sink(sink); env.execute().
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional, Union

from flink_tpu.core.config import Configuration
from flink_tpu.core.functions import (
    AggregateFunction,
    as_filter_function,
    as_flat_map_function,
    as_key_selector,
    as_map_function,
    as_reduce_function,
)
from flink_tpu.core.state import (
    AggregatingStateDescriptor,
    FoldingStateDescriptor,
    ListStateDescriptor,
    ReducingStateDescriptor,
)
from flink_tpu.streaming.graph import (
    StreamEdge,
    StreamGraph,
    StreamNode,
    create_job_graph,
)
from flink_tpu.streaming.operators import (
    CoProcessOperator,
    CoStreamFlatMap,
    CoStreamMap,
    KeyedProcessOperator,
    ProcessOperator,
    StreamFilter,
    StreamFlatMap,
    StreamGroupedReduce,
    StreamMap,
    StreamSink,
)
from flink_tpu.streaming.partitioners import (
    BroadcastPartitioner,
    CustomPartitionerWrapper,
    ForwardPartitioner,
    GlobalPartitioner,
    KeyGroupStreamPartitioner,
    RebalancePartitioner,
    RescalePartitioner,
    ShufflePartitioner,
    StreamPartitioner,
)
from flink_tpu.streaming.sources import (
    CollectSink,
    FileTextSource,
    FromCollectionSource,
    PrintSink,
    SocketTextStreamSource,
    SourceFunction,
    StreamSource,
    TimestampsAndWatermarksOperator,
    WriteAsTextSink,
)
from flink_tpu.streaming.window_operator import (
    EvictingWindowOperator,
    WindowOperator,
)
from flink_tpu.streaming.windowing import (
    GlobalWindows,
    SlidingEventTimeWindows,
    SlidingProcessingTimeWindows,
    Time,
    TumblingEventTimeWindows,
    TumblingProcessingTimeWindows,
    CountTrigger,
    PurgingTrigger,
    WindowAssigner,
)


class StreamExecutionEnvironment:
    """(ref: StreamExecutionEnvironment.java)"""

    def __init__(self, configuration: Optional[Configuration] = None):
        self.config = configuration or Configuration()
        self.graph = StreamGraph()
        self.parallelism = 1
        self.max_parallelism = 128
        self.time_characteristic = "event"  # event | processing | ingestion
        self.checkpoint_interval: Optional[int] = None
        self.checkpoint_mode = "exactly_once"
        self.checkpoint_storage: dict = {"storage": "memory", "retain": 1}
        self.processing_time_service = None  # executor default if None
        self.state_backend: str = self.config.get_string("state.backend", "heap")
        self.restart_strategy: Optional[dict] = {"strategy": "none"}
        self.latency_tracking_interval: Optional[int] = None
        #: device mesh for sharded window aggregation (None = 1 chip)
        self.mesh = None
        self.mesh_axis = "kg"
        #: None → LocalExecutor; int n → MiniCluster with n workers
        self.num_task_managers: Optional[int] = None
        #: "host:port" of a running Dispatcher → RemoteExecutor
        self.remote_address: Optional[str] = None
        self.remote_secret: Optional[str] = None
        self.remote_tls = None
        self._last_executor = None
        self._executed = False
        #: most recent pre-flight Diagnostics (validate()/execute())
        self._last_validation = None

    # ---- factory ----------------------------------------------------
    @staticmethod
    def get_execution_environment(configuration=None) -> "StreamExecutionEnvironment":
        return StreamExecutionEnvironment(configuration)

    # ---- configuration ----------------------------------------------
    def set_parallelism(self, parallelism: int) -> "StreamExecutionEnvironment":
        self.parallelism = parallelism
        return self

    def set_max_parallelism(self, max_parallelism: int) -> "StreamExecutionEnvironment":
        self.max_parallelism = max_parallelism
        return self

    def set_stream_time_characteristic(self, tc: str) -> "StreamExecutionEnvironment":
        assert tc in ("event", "processing", "ingestion")
        self.time_characteristic = tc
        return self

    def set_state_backend(self, backend: str) -> "StreamExecutionEnvironment":
        self.state_backend = backend
        return self

    def enable_checkpointing(self, interval_ms: int,
                             mode: str = "exactly_once",
                             async_persist: bool = False,
                             timeout_ms: Optional[int] = None,
                             tolerable_failures: Optional[int] = None
                             ) -> "StreamExecutionEnvironment":
        """``async_persist=True`` materializes completed checkpoints on
        a writer thread (processing continues during the storage
        write; operators are notified only after durability — the 2PC
        ordering).  Opt-in, like the reference's incremental/async
        snapshot flags: a non-transactional sink observing replay
        after a failure sees a wider post-barrier gap.

        ``timeout_ms`` aborts a pending checkpoint that has not fully
        acked within the window, releasing its concurrency slot so the
        coordinator can re-trigger (ref: checkpointing timeout).
        ``tolerable_failures`` = N tolerates N CONSECUTIVE
        failed/aborted checkpoints before escalating to a task failure
        (ref: execution.checkpointing.tolerable-failed-checkpoints);
        None keeps the legacy behavior (aborts never escalate, a
        failed persist fails the job)."""
        self.checkpoint_interval = interval_ms
        self.checkpoint_mode = mode
        self.checkpoint_async_persist = async_persist
        self.checkpoint_timeout_ms = timeout_ms
        self.checkpoint_tolerable_failures = tolerable_failures
        return self

    _UNSET = object()

    def set_alignment_limits(self, spill_threshold=_UNSET,
                             abort_limit=_UNSET
                             ) -> "StreamExecutionEnvironment":
        """Exactly-once alignment buffering policy: elements queued
        on alignment-blocked channels past ``spill_threshold`` spill
        to disk (ref BufferSpiller.java:67; default: the channel
        capacity); an alignment that buffers more than ``abort_limit``
        elements in total ABORTS its checkpoint instead of buffering
        on (ref the alignment cap of TaskManagerOptions.java:342;
        default: unbounded)."""
        if spill_threshold is not self._UNSET:
            self.alignment_spill_threshold = spill_threshold
        if abort_limit is not self._UNSET:
            self.alignment_abort_limit = abort_limit
        return self

    def set_checkpoint_storage(self, storage: str, directory: Optional[str] = None,
                               retain: int = 1) -> "StreamExecutionEnvironment":
        """`memory` | `filesystem` (with directory) — the checkpoint-
        storage half of the state.backend switch (ref:
        MemoryStateBackend vs FsStateBackend checkpoint streams)."""
        self.checkpoint_storage = {"storage": storage, "retain": retain}
        if directory is not None:
            self.checkpoint_storage["dir"] = directory
        return self

    def set_mesh(self, mesh, axis: str = "kg") -> "StreamExecutionEnvironment":
        """Shard device window aggregation over `mesh[axis]` — the
        keyBy exchange runs as lax.all_to_all over ICI inside the
        jitted step (flink_tpu.parallel.mesh_windows), the TPU-native
        replacement for the reference's Netty key-group shuffle.

        `mesh` may be a CALLABLE returning a Mesh: the pod topology,
        where each TaskExecutor process builds a mesh over its OWN
        device subset at operator open (a Mesh holds live device
        handles and cannot ship inside the pickled job graph).  With a
        factory the operator runs at the env parallelism — the keyed
        exchange shards keys across processes over the DCN data plane,
        and each subtask's mesh shards its key range over ICI."""
        self.mesh = mesh
        self.mesh_axis = axis
        return self

    def use_mini_cluster(self, num_task_managers: int = 2
                         ) -> "StreamExecutionEnvironment":
        """Execute on the in-process multi-worker MiniCluster
        (flink_tpu.runtime.minicluster) instead of the single-loop
        LocalExecutor (ref: MiniCluster.java — multi-TM in one JVM)."""
        self.num_task_managers = num_task_managers
        return self

    def use_remote_cluster(self, jm_address: str, secret=None, tls=None
                           ) -> "StreamExecutionEnvironment":
        """Submit to a running cluster's Dispatcher at
        "host:port" (ref: RemoteStreamEnvironment /
        ClusterClient.run — flink_tpu.runtime.cluster).  The job graph
        is cloudpickled and shipped via the blob server; results come
        back through accumulators.  `secret` authenticates against a
        --secret cluster; `tls` (a runtime.tls.TlsConfig) speaks
        mutual TLS to a --tls-dir cluster."""
        self.remote_address = jm_address
        self.remote_secret = secret
        self.remote_tls = tls
        return self

    def set_restart_strategy(self, strategy: str, **kw) -> "StreamExecutionEnvironment":
        """fixed_delay(restart_attempts, delay_ms) | failure_rate | none
        (ref: RestartStrategies)"""
        self.restart_strategy = {"strategy": strategy, **kw}
        return self

    def set_failover_strategy(self, strategy: str
                              ) -> "StreamExecutionEnvironment":
        """"full" (default) | "region" — scope of a restart on task
        failure (ref: jobmanager.execution.failover-strategy,
        RestartPipelinedRegionStrategy).  "region" restarts only the
        failed task's pipelined region on the local executor; healthy
        regions carry their live state across the restart."""
        assert strategy in ("full", "region")
        self.failover_strategy = strategy
        return self

    def set_savepoint_restore(self, path: str,
                              allow_non_restored_state: bool = False
                              ) -> "StreamExecutionEnvironment":
        """Start the next execution from a savepoint — the
        `flink run -s <path>` contract.  Restoring at a different
        parallelism re-splits keyed state by key-group range and
        operator list state round-robin (ref: SavepointRestoreSettings
        + StateAssignmentOperation).  Snapshot state whose operator
        uids match nothing in the new topology FAILS the restore
        unless allow_non_restored_state (the reference's
        --allowNonRestoredState)."""
        self.savepoint_restore_path = path
        self.allow_non_restored_state = allow_non_restored_state
        return self

    # ---- sources ----------------------------------------------------
    def add_source(self, source_function: SourceFunction,
                   name: str = "source", parallelism: int = 1) -> "DataStream":
        node = self.graph.add_node(StreamNode(
            self.graph.new_node_id(), name,
            _source_factory(source_function, self.time_characteristic),
            parallelism=parallelism,
            max_parallelism=self.max_parallelism,
            is_source=True,
            time_characteristic=self.time_characteristic,
        ))
        return DataStream(self, node)

    def from_collection(self, items: Iterable[Any], timestamped: bool = False) -> "DataStream":
        return self.add_source(
            FromCollectionSource(list(items), timestamped=timestamped),
            name="from_collection")

    def from_elements(self, *items) -> "DataStream":
        return self.from_collection(list(items))

    def socket_text_stream(self, hostname: str, port: int,
                           delimiter: str = "\n", max_retries: int = 0) -> "DataStream":
        return self.add_source(
            SocketTextStreamSource(hostname, port, delimiter, max_retries),
            name="socket_source")

    def read_text_file(self, path: str) -> "DataStream":
        return self.add_source(FileTextSource(path), name="file_source")

    # ---- execution --------------------------------------------------
    def get_stream_graph(self) -> StreamGraph:
        return self.graph

    def get_job_graph(self):
        jg = create_job_graph(self.graph)
        if self.checkpoint_interval is not None:
            jg.checkpoint_config = {
                "interval": self.checkpoint_interval,
                "mode": self.checkpoint_mode,
                "async_persist": getattr(self, "checkpoint_async_persist",
                                         False),
                **self.checkpoint_storage,
            }
            if getattr(self, "checkpoint_timeout_ms", None) is not None:
                jg.checkpoint_config["timeout"] = self.checkpoint_timeout_ms
            if getattr(self, "checkpoint_tolerable_failures",
                       None) is not None:
                jg.checkpoint_config["tolerable_failures"] = \
                    self.checkpoint_tolerable_failures
            if hasattr(self, "alignment_spill_threshold"):
                jg.checkpoint_config["alignment_spill_threshold"] = \
                    self.alignment_spill_threshold
            if hasattr(self, "alignment_abort_limit"):
                jg.checkpoint_config["alignment_abort_limit"] = \
                    self.alignment_abort_limit
        jg.savepoint_restore_path = getattr(
            self, "savepoint_restore_path", None)
        jg.allow_non_restored_state = getattr(
            self, "allow_non_restored_state", False)
        return jg

    def set_latency_tracking_interval(self, interval_ms: Optional[int]
                                      ) -> "StreamExecutionEnvironment":
        """Periodic LatencyMarker emission from sources (ref:
        ExecutionConfig.setLatencyTrackingInterval / the
        metrics.latency.interval config)."""
        self.latency_tracking_interval = interval_ms
        return self

    def get_metric_registry(self):
        """The registry of the last/most recent executor (populated
        after execute()/execute_async())."""
        return self._last_executor.metrics if self._last_executor else None

    def enable_tracing(self, enabled: bool = True
                       ) -> "StreamExecutionEnvironment":
        """Turn the process-global tracer on (or off): spans for
        operator processing, device flush/fire, native kernel
        dispatches, and checkpoint barriers land in the Chrome
        trace-event buffer (runtime.tracing).  Export after the job
        with ``env.get_tracer().write_chrome_trace(path)``."""
        from flink_tpu.runtime.tracing import get_tracer
        get_tracer().enabled = enabled
        return self

    def get_tracer(self):
        """The process-global :class:`~flink_tpu.runtime.tracing.Tracer`."""
        from flink_tpu.runtime.tracing import get_tracer
        return get_tracer()

    def _make_executor(self):
        from flink_tpu.core.config import HistoryServerOptions, MetricOptions
        kw = dict(
            state_backend=self.state_backend,
            max_parallelism=self.max_parallelism,
            restart_strategy=self.restart_strategy,
            processing_time_service=self.processing_time_service,
            latency_interval_ms=getattr(self, "latency_tracking_interval",
                                        None),
            sample_interval_ms=self.config.get_integer(
                MetricOptions.SAMPLE_INTERVAL_MS),
            metrics_history_size=self.config.get_integer(
                MetricOptions.HISTORY_SIZE),
            archive_dir=self.config.get_string(
                HistoryServerOptions.ARCHIVE_DIR),
        )
        if self.remote_address is not None:
            from flink_tpu.runtime.cluster import RemoteExecutor
            kw.pop("processing_time_service", None)
            # cluster mode archives Dispatcher-side (its archive dir is
            # a JobManagerProcess setting, not a per-job one)
            kw.pop("archive_dir", None)
            self._last_executor = RemoteExecutor(
                self.remote_address, secret=self.remote_secret,
                tls=self.remote_tls, **kw)
        elif self.num_task_managers is not None:
            from flink_tpu.runtime.minicluster import MiniCluster
            self._last_executor = MiniCluster(
                num_task_managers=self.num_task_managers, **kw)
        else:
            from flink_tpu.runtime.local import LocalExecutor
            # region failover is a LocalExecutor capability; the
            # distributed tiers restart the full job (the reference's
            # "full" strategy)
            kw["failover_strategy"] = getattr(self, "failover_strategy",
                                              "full")
            self._last_executor = LocalExecutor(**kw)
        return self._last_executor

    # ---- pre-flight validation --------------------------------------
    def validate(self, strict: bool = False, types: bool = False):
        """Run the pre-flight static analysis (graph linter + UDF
        liftability) over the current topology WITHOUT executing it.

        Returns a :class:`flink_tpu.analysis.Diagnostics` report; with
        ``strict=True`` raises
        :class:`flink_tpu.analysis.JobValidationError` when the report
        contains any ERROR diagnostic.  With ``types=True`` the column
        type-flow prover (pass 3) also runs: FT185–FT188 findings land
        in the report and the per-edge schema dump is attached as
        ``report.typeflow``.  See docs/static_analysis.md for the code
        catalog.
        """
        from flink_tpu.analysis import JobValidationError, lint_graph
        report = lint_graph(self.graph, config=self.config, env=self,
                            types=types)
        self._last_validation = report
        if strict and report.has_errors():
            raise JobValidationError(report)
        return report

    def _preflight(self, job_name: str):
        """execute()-time lint gate, controlled by the ``lint.mode``
        config key: ``off`` skips it, ``warn`` (default) logs errors
        and warnings, ``strict`` raises on any ERROR diagnostic.

        ``lint.types.mode`` (default ``off``) arms the column
        type-flow prover the same way: ``warn`` runs it, logs its
        FT185–FT188 findings, and feeds conclusive verdicts into the
        runtime (probe-free map/filter kernels, per-edge codec hints,
        device-state pre-sizing); ``strict`` additionally raises when
        any FT185–FT188 finding fires."""
        from flink_tpu.core.config import LintOptions, lint_mode_of
        mode = lint_mode_of(self.config, LintOptions.MODE)
        tmode = lint_mode_of(self.config, LintOptions.TYPES_MODE)
        if mode == "off" and tmode == "off":
            return None
        self.graph.job_name = job_name
        report = self.validate(strict=(mode == "strict"),
                               types=(tmode != "off"))
        typeflow = getattr(report, "typeflow", None)
        self._last_typeflow = typeflow
        if typeflow is not None:
            from flink_tpu.analysis.typeflow import apply_static
            apply_static(self.graph, typeflow)
            if tmode == "strict":
                findings = [d for d in report
                            if d.code in ("FT185", "FT186", "FT187",
                                          "FT188")]
                if findings:
                    from flink_tpu.analysis import JobValidationError
                    raise JobValidationError(report)
        if len(report):
            report.log()
        return report

    def _publish_lint_metrics(self, report):
        if report is None or self._last_executor is None:
            return
        registry = getattr(self._last_executor, "metrics", None)
        if registry is None:
            return
        try:
            from flink_tpu.runtime.metrics import register_lint_gauges
            register_lint_gauges(registry, self.graph.job_name, report)
        except Exception:
            pass  # metrics are best-effort; never block submission
        typeflow = getattr(report, "typeflow", None)
        if typeflow is None:
            return
        try:
            from flink_tpu.runtime.metrics import (
                register_typeflow_gauges,
            )
            register_typeflow_gauges(registry, self.graph.job_name,
                                     typeflow)
        except Exception:
            pass

    def execute(self, job_name: str = "job"):
        """(ref: execute :1508) — runs on the local executor."""
        report = self._preflight(job_name)
        self.graph.job_name = job_name
        executor = self._make_executor()
        self._publish_lint_metrics(report)
        return executor.execute(self.get_job_graph())

    def execute_async(self, job_name: str = "job"):
        """Submit and return a JobClient with cancel()/wait() — the
        detached-submission shape of ClusterClient.run()."""
        report = self._preflight(job_name)
        self.graph.job_name = job_name
        executor = self._make_executor()
        self._publish_lint_metrics(report)
        return executor.execute_async(self.get_job_graph())


def _source_factory(source_function: SourceFunction, time_characteristic: str):
    import copy

    def factory():
        return StreamSource(copy.deepcopy(source_function), time_characteristic)
    return factory


def _op_factory(cls, fn_factory):
    def factory():
        return cls(fn_factory())
    return factory


class DataStream:
    """(ref: DataStream.java)"""

    def __init__(self, env: StreamExecutionEnvironment, node: StreamNode,
                 partitioner: Optional[StreamPartitioner] = None,
                 side_tag=None):
        self.env = env
        self.node = node
        #: pending partitioner for the NEXT edge out of this stream
        self._partitioner = partitioner
        #: set → edges out of this stream carry this side-output tag
        self._side_tag = side_tag

    # ---- plumbing ---------------------------------------------------
    def _edge_partitioner(self, target_parallelism: int) -> StreamPartitioner:
        if self._partitioner is not None:
            return self._partitioner
        if self.node.parallelism == target_parallelism:
            return ForwardPartitioner()
        return RebalancePartitioner()

    def _add_op(self, name: str, operator_factory, parallelism=None,
                key_selector=None, type_number: int = 0,
                extra_inputs: Optional[List["DataStream"]] = None,
                chaining: str = "always") -> "DataStream":
        # default = the ENVIRONMENT parallelism (ref: every
        # StreamTransformation is created with env.getParallelism and
        # overridden per-operator via setParallelism), not the upstream
        # node's — matching StreamExecutionEnvironment.setParallelism
        p = parallelism if parallelism is not None else self.env.parallelism
        node = self.env.graph.add_node(StreamNode(
            self.env.graph.new_node_id(), name, operator_factory,
            parallelism=p,
            max_parallelism=self.env.max_parallelism,
            key_selector=key_selector,
            chaining_strategy=chaining,
            time_characteristic=self.env.time_characteristic,
        ))
        self.env.graph.add_edge(StreamEdge(
            self.node.id, node.id, self._edge_partitioner(p), type_number,
            side_output_tag=self._side_tag))
        for i, s in enumerate(extra_inputs or [], start=1):
            self.env.graph.add_edge(StreamEdge(
                s.node.id, node.id, s._edge_partitioner(p), i,
                side_output_tag=s._side_tag))
        return DataStream(self.env, node)

    # ---- basic transforms -------------------------------------------
    def map(self, fn, name: str = "map") -> "DataStream":
        f = as_map_function(fn)
        return self._add_op(name, _op_factory(StreamMap, lambda: f))

    def flat_map(self, fn, name: str = "flat_map") -> "DataStream":
        f = as_flat_map_function(fn)
        return self._add_op(name, _op_factory(StreamFlatMap, lambda: f))

    def filter(self, fn, name: str = "filter") -> "DataStream":
        f = as_filter_function(fn)
        return self._add_op(name, _op_factory(StreamFilter, lambda: f))

    def process(self, process_function, name: str = "process") -> "DataStream":
        return self._add_op(name, _op_factory(ProcessOperator, lambda: process_function))

    def set_parallelism(self, parallelism: int) -> "DataStream":
        self.node.parallelism = parallelism
        return self

    def name(self, name: str) -> "DataStream":
        self.node.name = name
        return self

    def uid(self, uid: str) -> "DataStream":
        self.node.uid = uid
        return self

    def disable_chaining(self) -> "DataStream":
        self.node.chaining_strategy = "never"
        return self

    def start_new_chain(self) -> "DataStream":
        self.node.chaining_strategy = "head"
        return self

    # ---- partitioning (ref: DataStream.java :395-410 etc.) ----------
    def key_by(self, key_selector) -> "KeyedStream":
        ks = as_key_selector(key_selector)
        return KeyedStream(self.env, self.node, ks)

    def rebalance(self) -> "DataStream":
        return DataStream(self.env, self.node, RebalancePartitioner())

    def rescale(self) -> "DataStream":
        return DataStream(self.env, self.node, RescalePartitioner())

    def shuffle(self) -> "DataStream":
        return DataStream(self.env, self.node, ShufflePartitioner())

    def broadcast(self, *broadcast_state_descriptors) -> "DataStream":
        """Without arguments: broadcast-partitioned stream (every
        record to every downstream subtask).  With MapStateDescriptors:
        a BroadcastStream for the broadcast state pattern
        (ref: DataStream.broadcast :395-410)."""
        bs = DataStream(self.env, self.node, BroadcastPartitioner())
        if broadcast_state_descriptors:
            return BroadcastStream(bs, broadcast_state_descriptors)
        return bs

    def global_(self) -> "DataStream":
        return DataStream(self.env, self.node, GlobalPartitioner())

    def forward(self) -> "DataStream":
        return DataStream(self.env, self.node, ForwardPartitioner())

    def get_side_output(self, tag) -> "DataStream":
        """Consume a side output of this operator
        (ref: SingleOutputStreamOperator#getSideOutput)."""
        return DataStream(self.env, self.node, side_tag=tag)

    def partition_custom(self, partitioner, key_selector=None) -> "DataStream":
        ks = as_key_selector(key_selector) if key_selector is not None else None
        return DataStream(self.env, self.node,
                          CustomPartitionerWrapper(partitioner, ks))

    # ---- union / connect (ref: union :212, connect :252) ------------
    def union(self, *streams: "DataStream") -> "DataStream":
        """Merge same-type streams: a pass-through node with N inputs."""
        f = as_map_function(lambda x: x)
        node = self.env.graph.add_node(StreamNode(
            self.env.graph.new_node_id(), "union",
            _op_factory(StreamMap, lambda: f),
            parallelism=self.node.parallelism,
            max_parallelism=self.env.max_parallelism,
            chaining_strategy="never",
        ))
        for s in (self,) + streams:
            self.env.graph.add_edge(StreamEdge(
                s.node.id, node.id, s._edge_partitioner(node.parallelism), 0))
        return DataStream(self.env, node)

    def split(self, output_selector) -> "SplitStream":
        """(ref: DataStream.split :238 — deprecated there in favor of
        side outputs, kept for API parity).  `output_selector(value)`
        returns an iterable of route names."""
        return SplitStream(self.env, self.node, output_selector,
                           partitioner=self._partitioner,
                           side_tag=self._side_tag)

    def join(self, other: "DataStream"):
        """(ref: DataStream.join :709) —
        .where(k1).equal_to(k2).window(w).apply(fn)."""
        from flink_tpu.streaming.joining import JoinedStreams
        return JoinedStreams(self, other)

    def interval_join(self, other: "DataStream"):
        """Time-bounded stream-stream join:
        a.interval_join(b).where(k1).equal_to(k2)
         .between(lower_ms, upper_ms).apply(fn) — pairs with
        b.ts - a.ts in [lower, upper] and equal keys (the reference's
        windowed table join bounds, WindowJoinUtil.scala)."""
        from flink_tpu.streaming.joining import IntervalJoinedStreams
        return IntervalJoinedStreams(self, other)

    def co_group(self, other: "DataStream"):
        """(ref: DataStream.coGroup :701)."""
        from flink_tpu.streaming.joining import CoGroupedStreams
        return CoGroupedStreams(self, other)

    def iterate(self) -> "IterativeStream":
        """(ref: DataStream.iterate :514) — returns the iteration head;
        call close_with(feedback) to wire the loop.  Records on the
        feedback edge bypass EOS/barrier propagation (iterations are
        outside the exactly-once guarantee, as in the reference)."""
        head = self._add_op("iteration_head",
                            _op_factory(StreamMap,
                                        lambda: as_map_function(lambda v: v)),
                            chaining="never")
        return IterativeStream(self.env, head.node)

    def connect(self, other) -> "ConnectedStreams":
        if isinstance(other, BroadcastStream):
            return BroadcastConnectedStream(self.env, self, other)
        return ConnectedStreams(self.env, self, other)

    # ---- windows over non-keyed streams -----------------------------
    def window_all(self, assigner: WindowAssigner) -> "AllWindowedStream":
        return AllWindowedStream(self.key_by(lambda x: 0), assigner)

    def count_window_all(self, size: int) -> "AllWindowedStream":
        ws = AllWindowedStream(self.key_by(lambda x: 0), GlobalWindows.create())
        ws._trigger = PurgingTrigger.of(CountTrigger(size))
        return ws

    # ---- timestamps -------------------------------------------------
    def assign_timestamps_and_watermarks(self, assigner,
                                         watermark_interval: int = 1) -> "DataStream":
        return self._add_op(
            "timestamps",
            lambda: TimestampsAndWatermarksOperator(assigner, watermark_interval))

    # ---- sinks ------------------------------------------------------
    def add_sink(self, sink_function, name: str = "sink") -> "DataStreamSink":
        # Table.to_retract_stream marks its result; retract-aware
        # sinks opt into pair decoding here instead of sniffing
        # (bool, x)-shaped values on every stream
        if getattr(self, "carries_retract_pairs", False) and \
                hasattr(sink_function, "enable_retract_decoding"):
            sink_function.enable_retract_decoding()
        node = self._add_op(name, _op_factory(StreamSink, lambda: sink_function))
        return DataStreamSink(node)

    def print_(self, prefix: str = "") -> "DataStreamSink":
        return self.add_sink(PrintSink(prefix), name="print")

    def write_as_text(self, path: str) -> "DataStreamSink":
        return self.add_sink(WriteAsTextSink(path), name="write_text")

    def collect_into(self, target: list) -> "DataStreamSink":
        """Convenience: sink into a Python list (test/driver use)."""
        return self.add_sink(CollectSink(target), name="collect")


class DataStreamSink:
    def __init__(self, stream: DataStream):
        self._stream = stream
        self.node = stream.node

    def set_parallelism(self, parallelism: int) -> "DataStreamSink":
        self.node.parallelism = parallelism
        return self

    def name(self, name: str) -> "DataStreamSink":
        self.node.name = name
        return self


class KeyedStream(DataStream):
    """(ref: KeyedStream.java)"""

    def __init__(self, env, node, key_selector):
        super().__init__(env, node,
                         KeyGroupStreamPartitioner(key_selector, env.max_parallelism))
        self.key_selector = key_selector

    def _add_keyed_op(self, name: str, operator_factory, chaining="always") -> DataStream:
        ks = self.key_selector
        return self._add_op(name, operator_factory, key_selector=ks,
                            chaining=chaining)

    # ---- keyed transforms -------------------------------------------
    def process(self, process_function, name: str = "keyed_process") -> DataStream:
        return self._add_keyed_op(
            name, _op_factory(KeyedProcessOperator, lambda: process_function))

    def reduce(self, fn, name: str = "reduce") -> DataStream:
        f = as_reduce_function(fn)
        return self._add_keyed_op(name, _op_factory(StreamGroupedReduce, lambda: f))

    def sum(self, field=None) -> DataStream:
        return self.reduce(_field_reduce(field, lambda a, b: a + b), name="sum")

    def min(self, field=None) -> DataStream:
        return self.reduce(_field_reduce(field, min), name="min")

    def max(self, field=None) -> DataStream:
        return self.reduce(_field_reduce(field, max), name="max")

    def min_by(self, field) -> DataStream:
        getter = _field_getter(field)
        return self.reduce(lambda a, b: a if getter(a) <= getter(b) else b, name="min_by")

    def max_by(self, field) -> DataStream:
        getter = _field_getter(field)
        return self.reduce(lambda a, b: a if getter(a) >= getter(b) else b, name="max_by")

    # ---- windows (ref: KeyedStream.timeWindow :352-370) -------------
    def window(self, assigner: WindowAssigner) -> "WindowedStream":
        return WindowedStream(self, assigner)

    def time_window(self, size: Time, slide: Optional[Time] = None) -> "WindowedStream":
        if self.env.time_characteristic == "processing":
            assigner = (TumblingProcessingTimeWindows.of(size) if slide is None
                        else SlidingProcessingTimeWindows.of(size, slide))
        else:
            assigner = (TumblingEventTimeWindows.of(size) if slide is None
                        else SlidingEventTimeWindows.of(size, slide))
        return WindowedStream(self, assigner)

    def count_window(self, size: int, slide: Optional[int] = None) -> "WindowedStream":
        ws = WindowedStream(self, GlobalWindows.create())
        if slide is None:
            ws._trigger = PurgingTrigger.of(CountTrigger(size))
        else:
            from flink_tpu.streaming.windowing import CountEvictor
            ws._trigger = CountTrigger(slide)
            ws._evictor = CountEvictor.of(size)
        return ws

    def connect(self, other) -> "ConnectedStreams":
        if isinstance(other, BroadcastStream):
            return BroadcastConnectedStream(self.env, self, other)
        return ConnectedStreams(self.env, self, other)

    def as_queryable_state(self, name: str, descriptor=None):
        """(ref: KeyedStream.asQueryableState :745-788) — registers the
        stream's latest value per key as externally queryable; read it
        with flink_tpu.runtime.queryable.QueryableStateClient
        .get_kv_state(name, key) while the job runs (dirty reads, the
        reference's contract)."""
        from flink_tpu.core.state import ValueStateDescriptor
        from flink_tpu.runtime.queryable import DEFAULT_REGISTRY

        desc = descriptor or ValueStateDescriptor(name)
        desc.set_queryable(name)

        class _QueryableOp(KeyedProcessOperator):
            def open(self):
                super().open()
                self._qstate = self.keyed_backend.get_or_create_keyed_state(desc)
                # the AbstractKeyedStateBackend.java:382-389 hook
                DEFAULT_REGISTRY.register(
                    name, self.keyed_backend.key_group_range,
                    self.keyed_backend, desc)

            def process_element(self, record):
                from flink_tpu.state.backend import VOID_NAMESPACE
                self._qstate.set_current_namespace(VOID_NAMESPACE)
                # ValueState-backed (default): last value wins;
                # aggregating/reducing descriptors accumulate instead
                # (the reference registers any InternalKvState kind)
                if hasattr(self._qstate, "update"):
                    self._qstate.update(record.value)
                else:
                    self._qstate.add(record.value)

            def close(self):
                # device states micro-batch their adds; make the final
                # values visible to queries once the task stops
                flush_all = getattr(self.keyed_backend, "flush_all",
                                    None)
                if flush_all is not None:
                    flush_all()
                super().close()

        class _Noop:
            def process_element(self, value, ctx, out):
                pass

        return self._add_keyed_op(f"queryable-{name}",
                                  lambda: _QueryableOp(_Noop()))


def _field_getter(field):
    if field is None:
        return lambda x: x
    if callable(field):
        return field
    return lambda x: x[field] if isinstance(x, (tuple, list)) else getattr(x, field)


def _field_reduce(field, combine):
    if field is None:
        return lambda a, b: combine(a, b)

    def reducer(a, b):
        if isinstance(a, tuple):
            lst = list(a)
            lst[field] = combine(a[field], b[field])
            return tuple(lst)
        if isinstance(a, list):
            lst = list(a)
            lst[field] = combine(a[field], b[field])
            return lst
        setattr(a, field, combine(getattr(a, field), getattr(b, field)))
        return a

    return reducer


class WindowedStream:
    """(ref: WindowedStream.java :305-850)"""

    def __init__(self, keyed: KeyedStream, assigner: WindowAssigner):
        self._keyed = keyed
        self._assigner = assigner
        self._trigger = None
        self._evictor = None
        self._allowed_lateness = 0
        self._late_tag = None
        self._device_enabled = True

    def disable_device_operator(self) -> "WindowedStream":
        """Force the scalar WindowOperator even for device-eligible
        aggregates (debugging / semantics comparison)."""
        self._device_enabled = False
        return self

    def trigger(self, trigger) -> "WindowedStream":
        self._trigger = trigger
        return self

    def evictor(self, evictor) -> "WindowedStream":
        self._evictor = evictor
        return self

    def allowed_lateness(self, lateness: Union[Time, int]) -> "WindowedStream":
        self._allowed_lateness = (lateness.milliseconds
                                  if isinstance(lateness, Time) else int(lateness))
        return self

    def side_output_late_data(self, tag) -> "WindowedStream":
        self._late_tag = tag
        return self

    def _build(self, name, state_descriptor, window_function,
               single_value=None) -> DataStream:
        assigner = self._assigner
        trigger = self._trigger
        evictor = self._evictor
        lateness = self._allowed_lateness
        late_tag = self._late_tag

        if evictor is not None:
            pre = _pre_aggregator_for(state_descriptor) if single_value else None

            def factory():
                return EvictingWindowOperator(
                    assigner, window_function, trigger, evictor,
                    lateness, late_tag, pre_aggregator=pre)
        else:
            def factory():
                return WindowOperator(
                    assigner, state_descriptor, window_function, trigger,
                    lateness, late_tag, single_value_contents=single_value)
        return self._keyed._add_keyed_op(name, factory, chaining="head")

    # ---- terminal ops -----------------------------------------------
    def aggregate(self, aggregate_function: AggregateFunction,
                  window_function=None, name: str = "window_aggregate") -> DataStream:
        """(ref: WindowedStream.aggregate :687-716).  Device-eligible
        aggregates (DeviceAggregateFunction + event-time tumbling/
        sliding/session, default trigger, no evictor, lateness 0) run
        on the vectorized TPU engines via DeviceWindowOperator; the
        rest stay on the scalar WindowOperator."""
        from flink_tpu.streaming.device_window_operator import (
            DeviceWindowOperator,
            is_device_eligible,
        )
        if (self._device_enabled
                and self._keyed.env.time_characteristic == "event"
                and is_device_eligible(
                    self._assigner, aggregate_function, self._trigger,
                    self._evictor, self._allowed_lateness, self._late_tag,
                    window_function)):
            assigner = self._assigner
            env = self._keyed.env
            mesh, mesh_axis = env.mesh, env.mesh_axis
            from flink_tpu.streaming.windowing import (
                TumblingEventTimeWindows as _Tumbling,
            )
            if mesh is not None and not isinstance(assigner, _Tumbling):
                mesh = None  # only tumbling has a sharded engine so far

            def factory():
                return DeviceWindowOperator(assigner, aggregate_function,
                                            window_function,
                                            mesh=mesh, mesh_axis=mesh_axis)
            from flink_tpu.streaming.device_window_operator import (
                is_mesh_factory,
            )
            if mesh is not None and not is_mesh_factory(mesh):
                # the mesh IS the parallelism: one host subtask drives
                # the SPMD program over all devices; upstream edges
                # still hash-route (to the single subtask) so the
                # operator sees the keyed contract
                return self._keyed._add_op(
                    name, factory, parallelism=1,
                    key_selector=self._keyed.key_selector, chaining="head")
            # a mesh FACTORY runs per subtask (pod topology: the keyed
            # exchange spans processes, each subtask's own mesh spans
            # its local devices)
            return self._keyed._add_keyed_op(name, factory, chaining="head")
        # arbitrary Python aggregates with the same eligible window
        # shapes ride the generic vectorized log tier (sort + diagonal
        # -round fold of the user's add over numpy columns) instead of
        # the per-record scalar WindowOperator
        from flink_tpu.streaming.generic_agg import (
            GenericWindowOperator,
            is_generic_eligible,
        )
        if (self._device_enabled
                and self._keyed.env.time_characteristic == "event"
                and is_generic_eligible(
                    self._assigner, aggregate_function, self._trigger,
                    self._evictor, self._allowed_lateness,
                    self._late_tag, window_function)):
            assigner = self._assigner

            def gfactory():
                return GenericWindowOperator(assigner,
                                             aggregate_function,
                                             window_function)
            return self._keyed._add_keyed_op(name, gfactory,
                                             chaining="head")
        return self._build(
            name,
            AggregatingStateDescriptor("window-contents", aggregate_function),
            window_function,
            single_value=True)

    def reduce(self, fn, window_function=None, name: str = "window_reduce") -> DataStream:
        f = as_reduce_function(fn)
        return self._build(
            name,
            ReducingStateDescriptor("window-contents", f),
            window_function,
            single_value=True)

    def fold(self, initial_value, fold_function, window_function=None) -> DataStream:
        return self._build(
            "window_fold",
            FoldingStateDescriptor("window-contents", initial_value, fold_function),
            window_function,
            single_value=True)

    def apply(self, window_function, name: str = "window_apply") -> DataStream:
        return self._build(
            name, ListStateDescriptor("window-contents"), window_function,
            single_value=False)

    def process(self, process_window_function, name: str = "window_process") -> DataStream:
        return self._build(
            name, ListStateDescriptor("window-contents"),
            process_window_function, single_value=False)

    def sum(self, field=None) -> DataStream:
        return self.reduce(_field_reduce(field, lambda a, b: a + b), name="window_sum")

    def min(self, field=None) -> DataStream:
        return self.reduce(_field_reduce(field, min), name="window_min")

    def max(self, field=None) -> DataStream:
        return self.reduce(_field_reduce(field, max), name="window_max")


def _pre_aggregator_for(state_descriptor):
    """Fire-time aggregation over raw elements for the evictor path
    (ref: the Reduce/Aggregate/FoldApplyWindowFunction wrappers the
    reference's WindowedStream builds when an evictor is set)."""
    if isinstance(state_descriptor, ReducingStateDescriptor):
        reduce = state_descriptor.reduce_function.reduce

        def pre(values):
            it = iter(values)
            acc = next(it)
            for v in it:
                acc = reduce(acc, v)
            return acc
        return pre
    if isinstance(state_descriptor, AggregatingStateDescriptor):
        agg = state_descriptor.aggregate_function

        def pre(values):
            acc = agg.create_accumulator()
            for v in values:
                acc = agg.add(v, acc)
            return agg.get_result(acc)
        return pre
    if isinstance(state_descriptor, FoldingStateDescriptor):
        fold = state_descriptor.fold_function

        def pre(values):
            acc = state_descriptor.get_default_value()
            for v in values:
                acc = fold(acc, v)
            return acc
        return pre
    return None


class AllWindowedStream(WindowedStream):
    """Non-keyed windows — parallelism forced to 1
    (ref: AllWindowedStream.java)."""

    def _build(self, name, state_descriptor, window_function, single_value=None):
        stream = super()._build(name, state_descriptor, window_function, single_value)
        stream.node.parallelism = 1
        return stream


class ConnectedStreams:
    """(ref: ConnectedStreams.java)"""

    def __init__(self, env, first: DataStream, second: DataStream):
        self.env = env
        self.first = first
        self.second = second

    def _add_two_input(self, name, factory) -> DataStream:
        ks1 = getattr(self.first, "key_selector", None)

        def wrapped_factory():
            op = factory()
            if hasattr(op, "key_selector2"):
                op.key_selector2 = getattr(self.second, "key_selector", None)
            return op

        return self.first._add_op(
            name, wrapped_factory,
            key_selector=ks1,
            extra_inputs=[self.second],
            chaining="never")

    def map(self, co_map_function) -> DataStream:
        return self._add_two_input("co_map", lambda: CoStreamMap(co_map_function))

    def flat_map(self, co_flat_map_function) -> DataStream:
        return self._add_two_input("co_flat_map",
                                   lambda: CoStreamFlatMap(co_flat_map_function))

    def process(self, co_process_function) -> DataStream:
        return self._add_two_input("co_process",
                                   lambda: CoProcessOperator(co_process_function))

    def key_by(self, key_selector1, key_selector2) -> "ConnectedStreams":
        return ConnectedStreams(
            self.env,
            self.first.key_by(key_selector1),
            self.second.key_by(key_selector2))


class SplitStream(DataStream):
    """(ref: SplitStream.java) — route names from the output selector;
    select(names) keeps records routed to any of them."""

    def __init__(self, env, node, output_selector, partitioner=None,
                 side_tag=None):
        super().__init__(env, node, partitioner, side_tag)
        self._selector = output_selector

    def select(self, *names: str) -> DataStream:
        wanted = set(names)
        selector = self._selector

        def keep(value):
            routes = selector(value)
            return any(r in wanted for r in (routes or ()))

        return self.filter(keep, name=f"select[{','.join(names)}]")


class IterativeStream(DataStream):
    """(ref: IterativeStream.java) — the iteration head; downstream
    transforms consume it like any stream, and close_with(feedback)
    adds the back edge."""

    def close_with(self, feedback: DataStream) -> DataStream:
        partitioner = (ForwardPartitioner()
                       if feedback.node.parallelism == self.node.parallelism
                       else RebalancePartitioner())
        edge = StreamEdge(feedback.node.id, self.node.id, partitioner,
                          type_number=0)
        edge.is_feedback = True
        self.env.graph.add_edge(edge)
        return feedback


class BroadcastStream:
    """A broadcast-partitioned stream plus the broadcast state
    descriptors its elements update (ref: BroadcastStream.java)."""

    def __init__(self, stream: DataStream, descriptors):
        self.stream = stream
        self.descriptors = tuple(descriptors)


class BroadcastConnectedStream:
    """(ref: BroadcastConnectedStream.java) — process with a
    (Keyed)BroadcastProcessFunction; input 1 is the data side, input 2
    the broadcast side updating broadcast state on every instance."""

    def __init__(self, env, data_stream: DataStream,
                 broadcast: BroadcastStream):
        self.env = env
        self.data = data_stream
        self.broadcast = broadcast

    def process(self, fn, name: str = "broadcast_process") -> DataStream:
        from flink_tpu.streaming.operators import CoBroadcastOperator
        ks = getattr(self.data, "key_selector", None)
        return self.data._add_op(
            name, lambda: CoBroadcastOperator(fn),
            key_selector=ks,
            extra_inputs=[self.broadcast.stream],  # broadcast-partitioned
            chaining="never")


class AsyncDataStream:
    """(ref: AsyncDataStream.java — orderedWait/unorderedWait)."""

    @staticmethod
    def ordered_wait(stream: DataStream, async_function,
                     timeout_ms: Optional[int] = None,
                     capacity: int = 100) -> DataStream:
        return AsyncDataStream._wait(stream, async_function, timeout_ms,
                                     capacity, ordered=True)

    @staticmethod
    def unordered_wait(stream: DataStream, async_function,
                       timeout_ms: Optional[int] = None,
                       capacity: int = 100) -> DataStream:
        return AsyncDataStream._wait(stream, async_function, timeout_ms,
                                     capacity, ordered=False)

    @staticmethod
    def _wait(stream, fn, timeout_ms, capacity, ordered):
        from flink_tpu.streaming.operators import AsyncWaitOperator
        mode = "ordered" if ordered else "unordered"
        return stream._add_op(
            f"async_wait_{mode}",
            lambda: AsyncWaitOperator(fn, capacity=capacity,
                                      timeout_ms=timeout_ms,
                                      ordered=ordered),
            chaining="head")
