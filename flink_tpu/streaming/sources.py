"""Source & sink function contracts + built-in implementations.

Re-designs flink-streaming-java/.../api/functions/source/
SourceFunction.java (SourceContext emission contract: collect /
collectWithTimestamp / emitWatermark), StreamSource.java +
StreamSourceContexts.java:46-285 (per-time-characteristic contexts),
and the sink side (SinkFunction, RichSinkFunction, PrintSinkFunction,
writeAsText).  The reference's "emit under checkpoint lock" contract
maps here to the task's single-owner loop: a source emits only inside
run(), which the task interleaves with barrier handling.
"""

from __future__ import annotations

import abc
import socket as _socket
import time as _time
from typing import Any, Iterable, List, Optional

from flink_tpu.core.functions import RichFunction
from flink_tpu.streaming.elements import StreamRecord, Watermark
from flink_tpu.streaming.operators import AbstractUdfStreamOperator, Output


class SourceContext(abc.ABC):
    """(ref: SourceFunction.SourceContext)"""

    #: set by the task layer for thread-hosted sources (the emission
    #: lock); lazily created otherwise
    _checkpoint_lock = None

    @abc.abstractmethod
    def collect(self, value) -> None: ...

    @abc.abstractmethod
    def collect_with_timestamp(self, value, timestamp: int) -> None: ...

    @abc.abstractmethod
    def emit_watermark(self, watermark: Watermark) -> None: ...

    def collect_batch(self, batch) -> None:
        """Emit a whole RecordBatch element (vectorized sources).
        Contexts that can't forward batches box per row, preserving
        each row's timestamp validity."""
        for v, t in zip(batch.row_values(), batch.timestamps()):
            if t is None:
                self.collect(v)
            else:
                self.collect_with_timestamp(v, t)

    def get_checkpoint_lock(self):
        """(ref: SourceContext.getCheckpointLock) — a thread-hosted
        source MUST advance its replay position inside this lock in the
        same critical section as the emission, or a barrier injected
        between emit and position-update snapshots a stale offset and
        replay duplicates the record.  Reentrant: ctx.collect takes the
        same lock."""
        import threading
        if self._checkpoint_lock is None:
            self._checkpoint_lock = threading.RLock()
        return self._checkpoint_lock

    def mark_as_temporarily_idle(self) -> None:  # noqa: B027
        pass

    def close(self) -> None:  # noqa: B027
        pass


class SourceFunction(abc.ABC):
    """(ref: SourceFunction.java) — run() emits via the context until
    exhausted or cancel() is called."""

    @abc.abstractmethod
    def run(self, ctx: SourceContext) -> None: ...

    def cancel(self) -> None:  # noqa: B027
        pass


class ParallelSourceFunction(SourceFunction):
    """Marker: may run at parallelism > 1 (ref: ParallelSourceFunction)."""


class RichSourceFunction(SourceFunction, RichFunction):
    def __init__(self):
        RichFunction.__init__(self)


class RichParallelSourceFunction(ParallelSourceFunction, RichFunction):
    def __init__(self):
        RichFunction.__init__(self)


class SinkFunction(abc.ABC):
    """(ref: SinkFunction.java)"""

    @abc.abstractmethod
    def invoke(self, value, context=None) -> None: ...


class RichSinkFunction(SinkFunction, RichFunction):
    def __init__(self):
        RichFunction.__init__(self)


# ---------------------------------------------------------------------
# Source contexts per time characteristic (ref: StreamSourceContexts.java)
# ---------------------------------------------------------------------

class NonTimestampContext(SourceContext):
    """Processing time: no timestamps, watermarks ignored
    (ref: NonTimestampContext :46)."""

    def __init__(self, output: Output):
        self._output = output

    def collect(self, value):
        self._output.collect(StreamRecord(value, None))

    def collect_with_timestamp(self, value, timestamp):
        self.collect(value)  # timestamps ignored in processing time

    def collect_batch(self, batch):
        if batch.ts is None:
            self._output.collect_batch(batch)
        else:
            # processing time drops source timestamps — same rows,
            # stampless, exactly what per-row collect() would produce
            from flink_tpu.streaming.elements import RecordBatch
            self._output.collect_batch(RecordBatch(batch.cols))

    def emit_watermark(self, watermark):
        pass


class ManualWatermarkContext(SourceContext):
    """Event time: source provides timestamps + watermarks
    (ref: ManualWatermarkContext :285)."""

    def __init__(self, output: Output):
        self._output = output

    def collect(self, value):
        self._output.collect(StreamRecord(value, None))

    def collect_with_timestamp(self, value, timestamp):
        self._output.collect(StreamRecord(value, timestamp))

    def collect_batch(self, batch):
        self._output.collect_batch(batch)

    def emit_watermark(self, watermark):
        self._output.emit_watermark(watermark)


class AutomaticWatermarkContext(SourceContext):
    """Ingestion time: stamp with processing time, emit periodic
    watermarks (ref: AutomaticWatermarkContext :120)."""

    def __init__(self, output: Output, processing_time_service, interval_ms: int = 200):
        self._output = output
        self._pts = processing_time_service
        self._interval = interval_ms
        self._last_wm = None

    def collect(self, value):
        now = self._pts.get_current_processing_time()
        self._output.collect(StreamRecord(value, now))
        self._maybe_watermark(now)

    def collect_with_timestamp(self, value, timestamp):
        self.collect(value)  # source timestamps overridden in ingestion time

    def emit_watermark(self, watermark):
        pass  # automatic only

    def _maybe_watermark(self, now: int):
        bucket = now - (now % self._interval)
        if self._last_wm is None or bucket > self._last_wm:
            self._last_wm = bucket
            self._output.emit_watermark(Watermark(bucket - 1))


class StreamSource(AbstractUdfStreamOperator):
    """Operator hosting a SourceFunction (ref: StreamSource.java)."""

    COPY_UDF_PER_SUBTASK = False  # the source factory already copies

    def __init__(self, source_function: SourceFunction,
                 time_characteristic: str = "event"):
        super().__init__(source_function)
        self.time_characteristic = time_characteristic

    def make_context(self, output: Optional[Output] = None) -> SourceContext:
        """`output` override lets the task layer interpose the
        emission-lock wrapper for thread-hosted sources."""
        out = output if output is not None else self.output
        if self.time_characteristic == "processing":
            return NonTimestampContext(out)
        if self.time_characteristic == "ingestion":
            return AutomaticWatermarkContext(
                out, self.processing_time_service)
        return ManualWatermarkContext(out)

    def run(self) -> None:
        self.user_function.run(self.make_context())

    def cancel(self) -> None:
        self.user_function.cancel()

    def process_element(self, record):
        raise RuntimeError("sources have no input")

    # The source's read position rides in the operator snapshot via the
    # generic function-state hooks inherited from
    # AbstractUdfStreamOperator: a replayable source implements
    # snapshot_function_state/restore_function_state (ref: the
    # CheckpointedFunction contract, FlinkKafkaConsumerBase
    # .snapshotState) and restore rewinds it.


# ---------------------------------------------------------------------
# Built-in sources
# ---------------------------------------------------------------------

class FromCollectionSource(SourceFunction):
    """(ref: FromElementsFunction.java / fromCollection)
    Items may be plain values or (value, timestamp) pairs when
    `timestamped=True`."""

    def __init__(self, items: Iterable[Any], timestamped: bool = False,
                 final_watermark: bool = True):
        self.items = list(items)
        self.timestamped = timestamped
        self.final_watermark = final_watermark
        self._cancelled = False
        #: resume offset (exactly-once source state)
        self.offset = 0

    def run(self, ctx: SourceContext):
        while self.emit_step(ctx, len(self.items) + 1):
            pass

    def emit_step(self, ctx: SourceContext, max_records: int) -> bool:
        """Cooperative-stepping contract used by the executor loop:
        emit up to `max_records`, return True while more remain.  The
        offset is the exactly-once resume point — snapshots taken at
        step boundaries see only fully-emitted records."""
        from flink_tpu.streaming.elements import MAX_WATERMARK
        n = 0
        while self.offset < len(self.items) and n < max_records:
            if self._cancelled:
                return False
            item = self.items[self.offset]
            if self.timestamped:
                value, ts = item
                ctx.collect_with_timestamp(value, ts)
            else:
                ctx.collect(item)
            self.offset += 1
            n += 1
        if self.offset < len(self.items):
            return True
        if self.final_watermark:
            ctx.emit_watermark(MAX_WATERMARK)
            self.final_watermark = False  # emit once
        return False

    def cancel(self):
        self._cancelled = True

    # checkpoint hooks (the CheckpointedFunction-shaped contract the
    # operator layer snapshots/restores)
    def snapshot_function_state(self, checkpoint_id=None) -> dict:
        return {"offset": self.offset}

    def restore_function_state(self, state: dict) -> None:
        self.offset = state["offset"]


class SocketTextStreamSource(SourceFunction):
    """(ref: SocketTextStreamFunction.java — baseline config #1 source)"""

    def __init__(self, hostname: str, port: int, delimiter: str = "\n",
                 max_retries: int = 0):
        self.hostname = hostname
        self.port = port
        self.delimiter = delimiter
        self.max_retries = max_retries
        self._cancelled = False
        self._sock: Optional[_socket.socket] = None

    def run(self, ctx: SourceContext):
        attempts = 0
        while not self._cancelled:
            try:
                with _socket.create_connection((self.hostname, self.port)) as sock:
                    self._sock = sock
                    buf = ""
                    while not self._cancelled:
                        data = sock.recv(8192)
                        if not data:
                            return
                        buf += data.decode("utf-8", errors="replace")
                        while self.delimiter in buf:
                            line, buf = buf.split(self.delimiter, 1)
                            ctx.collect(line)
            except OSError:
                attempts += 1
                if attempts > self.max_retries:
                    raise
                _time.sleep(0.5)

    def cancel(self):
        self._cancelled = True
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass


class FileTextSource(SourceFunction):
    """(ref: readTextFile → TextInputFormat path)"""

    def __init__(self, path: str):
        self.path = path
        self._cancelled = False

    def run(self, ctx: SourceContext):
        with open(self.path, "r") as f:
            for line in f:
                if self._cancelled:
                    return
                ctx.collect(line.rstrip("\n"))

    def cancel(self):
        self._cancelled = True


# ---------------------------------------------------------------------
# Built-in sinks
# ---------------------------------------------------------------------

class CollectSink(SinkFunction):
    """Accumulates into a shared list (test/driver use).  On a
    distributed cluster the sink instance lives in a TaskExecutor
    process, so the collected values travel back through the
    accumulator channel (ref: DataStreamUtils.collect /
    accumulator-backed collect in the reference); they land in
    `JobExecutionResult.accumulators[accumulator_name]`."""

    def __init__(self, target: Optional[List[Any]] = None,
                 accumulator_name: str = "collected"):
        self.values: List[Any] = target if target is not None else []
        self.accumulator_name = accumulator_name

    def invoke(self, value, context=None):
        self.values.append(value)

    def invoke_batch(self, batch) -> None:
        """Vectorized collect: one extend instead of n invokes."""
        self.values.extend(batch.row_values())

    def accumulators(self):
        return {self.accumulator_name: list(self.values)}


class PrintSink(SinkFunction):
    """(ref: PrintSinkFunction.java)"""

    def __init__(self, prefix: str = ""):
        self.prefix = prefix

    def invoke(self, value, context=None):
        print(f"{self.prefix}{value}" if self.prefix else str(value))


class WriteAsTextSink(RichSinkFunction):
    """(ref: writeAsText — TextOutputFormat)"""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._fh = None

    def open(self, configuration):
        self._fh = open(self.path, "w")

    def invoke(self, value, context=None):
        self._fh.write(str(value) + "\n")

    def close(self):
        if self._fh:
            self._fh.close()
            self._fh = None


# ---------------------------------------------------------------------
# Timestamp / watermark assignment (ref: api/functions/timestamps/)
# ---------------------------------------------------------------------

class AssignerWithPeriodicWatermarks(abc.ABC):
    """(ref: AssignerWithPeriodicWatermarks.java)"""

    @abc.abstractmethod
    def extract_timestamp(self, element, previous_timestamp: Optional[int]) -> int: ...

    @abc.abstractmethod
    def get_current_watermark(self) -> Optional[Watermark]: ...


class AssignerWithPunctuatedWatermarks(abc.ABC):
    """(ref: AssignerWithPunctuatedWatermarks.java)"""

    @abc.abstractmethod
    def extract_timestamp(self, element, previous_timestamp: Optional[int]) -> int: ...

    @abc.abstractmethod
    def check_and_get_next_watermark(self, element, extracted_timestamp: int) -> Optional[Watermark]: ...


class AscendingTimestampExtractor(AssignerWithPeriodicWatermarks):
    """(ref: AscendingTimestampExtractor.java) — timestamps are
    monotonically increasing per subtask; watermark = last - 1."""

    def __init__(self, extractor):
        self._extract = extractor
        self._current = None

    def extract_timestamp(self, element, previous_timestamp):
        ts = self._extract(element)
        if self._current is None or ts >= self._current:
            self._current = ts
        # on violation the element keeps its own (late) timestamp; only
        # the watermark stays monotonic (ref: the log-and-ignore
        # MonotonyViolationHandler returns the extracted timestamp)
        return ts

    def get_current_watermark(self):
        return None if self._current is None else Watermark(self._current - 1)


class BoundedOutOfOrdernessTimestampExtractor(AssignerWithPeriodicWatermarks):
    """(ref: BoundedOutOfOrdernessTimestampExtractor.java)"""

    def __init__(self, max_out_of_orderness_ms: int, extractor):
        self.delay = max_out_of_orderness_ms
        self._extract = extractor
        self._max_ts = None

    def extract_timestamp(self, element, previous_timestamp):
        ts = self._extract(element)
        if self._max_ts is None or ts > self._max_ts:
            self._max_ts = ts
        return ts

    def get_current_watermark(self):
        if self._max_ts is None:
            return None
        return Watermark(self._max_ts - self.delay - 1)


class TimestampsAndWatermarksOperator(AbstractUdfStreamOperator):
    """Operator applying an assigner
    (ref: TimestampsAndPeriodicWatermarksOperator.java /
    TimestampsAndPunctuatedWatermarksOperator.java).  Periodic
    assigners emit on a watermark interval measured in elements here
    (the single-process runtime has no timer thread between elements);
    `watermark_interval` counts elements between watermark probes."""

    def __init__(self, assigner, watermark_interval: int = 1):
        super().__init__(assigner)
        self.watermark_interval = max(1, watermark_interval)
        self._since_last = 0
        self._last_emitted = None

    def process_element(self, record):
        ts = self.user_function.extract_timestamp(record.value, record.timestamp)
        self.output.collect(StreamRecord(record.value, ts))
        if isinstance(self.user_function, AssignerWithPunctuatedWatermarks):
            wm = self.user_function.check_and_get_next_watermark(record.value, ts)
            if wm is not None and (self._last_emitted is None
                                   or wm.timestamp > self._last_emitted):
                self._last_emitted = wm.timestamp
                self.output.emit_watermark(wm)
        else:
            self._since_last += 1
            if self._since_last >= self.watermark_interval:
                self._since_last = 0
                wm = self.user_function.get_current_watermark()
                if wm is not None and (self._last_emitted is None
                                       or wm.timestamp > self._last_emitted):
                    self._last_emitted = wm.timestamp
                    self.output.emit_watermark(wm)

    def process_watermark(self, watermark):
        """Upstream watermarks are swallowed except the final flush
        (ref: TimestampsAndPeriodicWatermarksOperator.processWatermark
        — only Long.MAX_VALUE passes)."""
        from flink_tpu.streaming.elements import MAX_TIMESTAMP
        if watermark.timestamp == MAX_TIMESTAMP:
            super().process_watermark(watermark)
