"""Whole-graph column type-flow prover (pass 3).

Propagates a per-edge :class:`ColumnSchema` — value dtype(s), tuple
arity, string columns, timestamp nullability, and ahead-of-time value
ranges — from every source through map/filter/keyBy/window/sink over a
:class:`~flink_tpu.streaming.graph.StreamGraph`, without running the
job.  Source schemas are read straight off
:class:`~flink_tpu.streaming.columnar.VectorizedCollectionSource`
payloads (the columns already exist AOT) or rebuilt from declared
collection elements; UDF output dtypes come from a linear abstract
interpretation of the ``dis`` bytecode that runs ONLY after the
liftability analyzer (pass 2) proved the function branch-free and
fully modelled.

The conservatism contract is the same as pass 2's: any unmodelled
opcode, call, dtype combination, or value range degrades to an
INCONCLUSIVE schema — never to a conclusive verdict the runtime could
contradict.  Conclusive verdicts feed the runtime three ways (all via
:func:`apply_static`, the PR 4 ``decided_by=static`` discipline):

- statically proven map/filter kernels skip the first-batch probe
  (``_ColumnKernelMixin``): the operator is stamped
  ``_static_kernel=True`` and records ``decided_by=static``; the
  output-shape validation stays armed, so a wrong kernel still
  demotes boxed with a recorded reason,
- exchange edges learn their predicted wire-codec tier
  (``StreamEdge.predicted_codec_tier`` → netchannel skips the doomed
  columnar encode attempt for proven pickle-tier edges),
- device window operators learn their predicted slot count
  (``_predicted_slots``) so engines pre-size instead of grow-doubling,
  and the footprint estimate is checked against
  ``state.backend.tpu.max-device-slots`` (FT187).

Findings surface as linter diagnostics:

``FT185``  exchange edge conclusively demotes to the pickle wire tier
           (names the column dtype and the operator that forces it)
``FT186``  dtype-overflow hazard in an otherwise liftable kernel
           (int64 wraparound the runtime probe currently catches) —
           the kernel keeps its probe
``FT187``  predicted device state footprint exceeds the configured
           slot budget (the estimate is a LOWER bound: distinct keys
           read AOT from the bounded source, so over-budget here is
           over-budget at runtime)
``FT188``  conclusive schema conflict at a union/merge point
"""

from __future__ import annotations

import dis
import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.analysis.diagnostics import Diagnostics
from flink_tpu.analysis.liftability import (
    LIFTABLE,
    analyze_udf,
    unwrap_udf,
)

log = logging.getLogger("flink_tpu.typeflow")

#: dtype tokens: the vocabulary of the schema lattice
I8, F8, F4, I4, BOOL, STR, OBJ = "i8", "f8", "f4", "i4", "bool", "str", "obj"

#: tokens with a columnar wire tier (netchannel._encode_value_column);
#: everything else rides per-batch pickle
_WIRE_TOKENS = frozenset({I8, F8, STR})

_INT_TOKENS = frozenset({I8, I4})
_FLOAT_TOKENS = frozenset({F8, F4})
_NUMERIC_TOKENS = _INT_TOKENS | _FLOAT_TOKENS

_I64_MIN, _I64_MAX = -(2 ** 63), 2 ** 63 - 1


# ---------------------------------------------------------------------
# schema model
# ---------------------------------------------------------------------


@dataclass(frozen=True)
class Col:
    """One column: name, dtype token, and AOT value bounds (numeric
    columns only; bounds flow through interval arithmetic so the
    prover can rule out int64 wraparound)."""

    name: str
    token: str
    lo: Optional[float] = None
    hi: Optional[float] = None

    def describe(self) -> str:
        return self.token


@dataclass(frozen=True)
class ColumnSchema:
    """The element schema of one edge: column dtypes in order, tuple
    arity (``scalar`` means rows are the single "v" column's cells),
    and timestamp nullability.  ``conclusive=False`` means the prover
    gave up — the runtime probe/codec decides, as today."""

    cols: Tuple[Col, ...] = ()
    ts: str = "none"  # "none" | "all" | "masked"
    conclusive: bool = False
    note: str = ""

    @property
    def scalar(self) -> bool:
        return len(self.cols) == 1 and self.cols[0].name == "v"

    def tokens(self) -> Tuple[str, ...]:
        return tuple(c.token for c in self.cols)

    def describe(self) -> str:
        if not self.conclusive:
            return f"inconclusive ({self.note})" if self.note \
                else "inconclusive"
        if self.scalar:
            body = self.cols[0].token
        else:
            body = "(" + ", ".join(
                f"{c.name}:{c.token}" for c in self.cols) + ")"
        return f"{body} ts={self.ts}"

    def to_dict(self) -> dict:
        return {
            "conclusive": self.conclusive,
            "cols": [{"name": c.name, "dtype": c.token,
                      "lo": c.lo, "hi": c.hi} for c in self.cols],
            "scalar": self.scalar,
            "ts": self.ts,
            "note": self.note,
        }


def _unknown(note: str) -> ColumnSchema:
    return ColumnSchema(conclusive=False, note=note)


def codec_tier(schema: ColumnSchema) -> Tuple[Optional[str], str]:
    """Predicted wire-codec tier for elements of this schema:
    ``("col", "")``, ``("pickle", offending_dtype)``, or
    ``(None, "")`` when the schema is inconclusive."""
    if not schema.conclusive or not schema.cols:
        return None, ""
    for c in schema.cols:
        if c.token not in _WIRE_TOKENS:
            return "pickle", c.token
    return "col", ""


# ---------------------------------------------------------------------
# source schemas (read off the AOT payload)
# ---------------------------------------------------------------------


def _token_of_dtype(dtype) -> str:
    if dtype == np.int64:
        return I8
    if dtype == np.float64:
        return F8
    if dtype == np.float32:
        return F4
    if dtype == np.int32:
        return I4
    if dtype == np.bool_:
        return BOOL
    if dtype == object:
        return STR  # pipeline convention: object columns hold str cells
    return OBJ


def _col_of_array(name: str, arr: np.ndarray) -> Col:
    tok = _token_of_dtype(arr.dtype)
    lo = hi = None
    if tok in _NUMERIC_TOKENS and arr.size:
        lo, hi = float(arr.min()), float(arr.max())
    elif tok in _NUMERIC_TOKENS:
        lo = hi = 0.0
    return Col(name, tok, lo, hi)


def _schema_of_batch(batch) -> ColumnSchema:
    """Schema of a materialized RecordBatch (a vectorized source's
    master batch IS the whole input, so the bounds are exact)."""
    cols = tuple(_col_of_array(name, arr)
                 for name, arr in batch.cols.items())
    if batch.ts is None:
        ts = "none"
    elif batch.ts_mask is not None:
        ts = "masked"
    else:
        ts = "all"
    return ColumnSchema(cols, ts, conclusive=True)


#: AOT row cap for schema/key extraction from declared collections
_MAX_AOT_ROWS = 1 << 20


def _source_schema(op) -> Tuple[ColumnSchema, Optional[Any]]:
    """(schema, source_function_or_None) for a StreamSource.  The
    source function is returned so the footprint pass can read its
    rows for the distinct-key estimate."""
    from flink_tpu.streaming.columnar import (
        ColumnarSource,
        VectorizedCollectionSource,
        batch_from_records,
    )
    from flink_tpu.streaming.sources import FromCollectionSource

    fn = getattr(op, "user_function", None)
    if isinstance(fn, VectorizedCollectionSource):
        if fn._batch is None:
            return _unknown("empty vectorized source"), fn
        return _schema_of_batch(fn._batch), fn
    if isinstance(fn, ColumnarSource):
        cols = tuple(_col_of_array(name, np.asarray(arr))
                     for name, arr in fn.cols.items())
        return ColumnSchema(cols, "all", conclusive=True), fn
    if isinstance(fn, FromCollectionSource):
        items = fn.items
        if not items or len(items) > _MAX_AOT_ROWS:
            return _unknown("collection empty or too large for AOT "
                            "schema"), fn
        if fn.timestamped:
            try:
                raw = [v for v, _ in items]
                ts = [t for _, t in items]
            except Exception:
                return _unknown("malformed (value, ts) pairs"), fn
        else:
            raw, ts = list(items), None
        batch = batch_from_records(raw, ts)
        if batch is None:
            return _unknown("collection does not fit the columnar "
                            "convention"), fn
        return _schema_of_batch(batch), fn
    return _unknown(
        f"source {type(fn).__name__ if fn is not None else '?'} has no "
        f"declared element schema"), fn


# ---------------------------------------------------------------------
# UDF output-dtype inference (linear abstract interpretation)
# ---------------------------------------------------------------------


class _DV:
    """Abstract dtype value on the simulated stack.

    ``tok`` is a dtype token for element-derived columns, "const" for
    a Python constant (value in ``const``), "tuple" for a built tuple
    (``fields``), "obj" for a resolved non-element Python object
    (value in ``const``; used to classify calls), or None = unknown.
    Numeric columns carry interval bounds in (lo, hi)."""

    __slots__ = ("tok", "const", "fields", "lo", "hi")

    def __init__(self, tok=None, const=None, fields=None,
                 lo=None, hi=None):
        self.tok = tok
        self.const = const
        self.fields = fields
        self.lo = lo
        self.hi = hi

    @property
    def is_col(self):
        return self.tok in (I8, F8, F4, I4, BOOL, STR)


def _const_dv(value) -> _DV:
    if type(value) is bool:
        return _DV("const", const=value)
    if type(value) in (int, float):
        return _DV("const", const=value, lo=float(value),
                   hi=float(value))
    return _DV("const", const=value)


_LEGACY_BINOP = {
    "BINARY_ADD": "+", "INPLACE_ADD": "+",
    "BINARY_SUBTRACT": "-", "INPLACE_SUBTRACT": "-",
    "BINARY_MULTIPLY": "*", "INPLACE_MULTIPLY": "*",
    "BINARY_TRUE_DIVIDE": "/", "INPLACE_TRUE_DIVIDE": "/",
    "BINARY_FLOOR_DIVIDE": "//", "INPLACE_FLOOR_DIVIDE": "//",
    "BINARY_MODULO": "%", "INPLACE_MODULO": "%",
    "BINARY_POWER": "**", "INPLACE_POWER": "**",
    "BINARY_LSHIFT": "<<", "INPLACE_LSHIFT": "<<",
    "BINARY_RSHIFT": ">>", "INPLACE_RSHIFT": ">>",
    "BINARY_AND": "&", "INPLACE_AND": "&",
    "BINARY_OR": "|", "INPLACE_OR": "|",
    "BINARY_XOR": "^", "INPLACE_XOR": "^",
}

_NOP_OPS = {"NOP", "EXTENDED_ARG", "RESUME", "CACHE", "PRECALL",
            "SETUP_ANNOTATIONS", "MAKE_CELL", "COPY_FREE_VARS",
            "GEN_START"}

#: float-returning elementwise ufuncs (numpy promotes int inputs to
#: float64; float32 stays float32)
_FLOAT_UFUNCS = {
    "sqrt", "exp", "exp2", "expm1", "log", "log2", "log10", "log1p",
    "sin", "cos", "tan", "arcsin", "arccos", "arctan", "sinh", "cosh",
    "tanh", "floor", "ceil", "trunc", "rint",
}
#: dtype-preserving elementwise ufuncs
_PRESERVE_UFUNCS = {"abs", "absolute", "negative", "positive",
                    "fabs", "conjugate"}
_PROMOTE_UFUNCS = {"maximum", "minimum", "fmax", "fmin"}

_NP_CASTS = {
    np.int64: I8, np.int32: I4, np.float64: F8, np.float32: F4,
    np.bool_: BOOL,
}


class _DtypeSim:
    """Linear dtype walk over straight-line bytecode.

    Precondition: pass 2 returned LIFTABLE for this function, so the
    code is branch-free, loop-free and fully modelled by the taint
    sim.  This walk re-executes the same instruction stream tracking
    numpy result dtypes and value intervals instead of taint.  It is
    allowed to model FEWER opcodes than pass 2: anything it cannot
    model yields an unknown value and, if that reaches the return, an
    inconclusive output schema (the kernel keeps its probe)."""

    def __init__(self, fn, skip_first: bool, param: _DV):
        self.fn = fn
        self.code = fn.__code__
        argc = (self.code.co_argcount
                + getattr(self.code, "co_kwonlyargcount", 0))
        params = list(self.code.co_varnames[:argc])
        if skip_first and params and params[0] in ("self", "cls"):
            params = params[1:]
        self.ok = len(params) == 1
        self.param_name = params[0] if params else None
        self.param = param
        self.locals: Dict[str, _DV] = {}
        if self.param_name is not None:
            self.locals[self.param_name] = param
        self.hazards: List[str] = []
        self.note = ""
        self.ret: Optional[_DV] = None
        self._closure = self._closure_map()

    def _closure_map(self):
        out = {}
        try:
            for name, cell in zip(self.code.co_freevars,
                                  self.fn.__closure__ or ()):
                try:
                    out[name] = cell.cell_contents
                except ValueError:
                    pass
        except Exception:
            pass
        return out

    def _bail(self, note: str) -> None:
        self.note = note
        self.ret = None
        self.ok = False

    # ---- interval helpers -------------------------------------------
    def _int_guard(self, lo, hi, sym: str) -> Tuple[Optional[float],
                                                    Optional[float]]:
        """Check an int-token result interval against int64; record a
        hazard (FT186) on possible wraparound."""
        if lo is None or hi is None:
            self.hazards.append(
                f"'{sym}' on int64 columns with unbounded value range")
            return None, None
        if lo < _I64_MIN or hi > _I64_MAX:
            self.hazards.append(
                f"'{sym}' can overflow int64 (value range "
                f"[{lo:.3g}, {hi:.3g}])")
        return lo, hi

    def _binary(self, a: _DV, b: _DV, sym: str) -> _DV:
        # const ⊗ const folds (pure arithmetic on literals)
        if a.tok == "const" and b.tok == "const":
            import operator as _op
            fns = {"+": _op.add, "-": _op.sub, "*": _op.mul,
                   "/": _op.truediv, "//": _op.floordiv, "%": _op.mod,
                   "**": _op.pow, "<<": _op.lshift, ">>": _op.rshift,
                   "&": _op.and_, "|": _op.or_, "^": _op.xor}
            try:
                return _const_dv(fns[sym](a.const, b.const))
            except Exception:
                return _DV()
        col, other = (a, b) if a.is_col else (b, a)
        if not col.is_col:
            return _DV()
        if other.tok not in ("const",) and not other.is_col:
            return _DV()
        toks = {a.tok if a.is_col else _const_token(a),
                b.tok if b.is_col else _const_token(b)}
        if None in toks:
            return _DV()
        if STR in toks:
            # object str columns: only '+' (concat) with str operands
            if sym == "+" and toks == {STR}:
                return _DV(STR)
            return _DV()
        if BOOL in toks:
            if sym in ("&", "|", "^") and toks == {BOOL}:
                return _DV(BOOL)
            return _DV()  # bool arithmetic: numpy semantics diverge
        # numeric promotion (numpy 2 / NEP 50: python scalars are weak)
        a_lo, a_hi = a.lo, a.hi
        b_lo, b_hi = b.lo, b.hi
        tok = _promote_tokens(a, b, sym)
        if tok is None:
            return _DV()
        lo, hi = _interval(sym, a_lo, a_hi, b_lo, b_hi)
        if tok in _INT_TOKENS:
            if sym in ("/",):
                raise AssertionError  # '/' always promotes to float
            if sym in ("//", "%") and _spans_zero(b_lo, b_hi):
                self.hazards.append(
                    f"'{sym}' divisor range includes zero (numpy "
                    f"yields 0, the scalar path raises)")
            if sym in ("<<", "**", "*", "+", "-"):
                lo, hi = self._int_guard(lo, hi, sym)
        return _DV(tok, lo=lo, hi=hi)

    # ---- call classification ----------------------------------------
    def _call(self, callee: _DV, args: List[_DV]) -> _DV:
        obj = callee.const if callee.tok == "obj" else None
        if obj is None:
            return _DV()
        if isinstance(obj, type) and obj in _NP_CASTS:
            tok = _NP_CASTS[obj]
            src = args[0] if args else _DV()
            lo, hi = (src.lo, src.hi)
            if tok in _INT_TOKENS:
                # casts wrap identically on the scalar and vectorized
                # paths (both go through numpy), so no hazard — but
                # the bounds are no longer trustworthy after a wrap
                if lo is not None and (lo < _I64_MIN or hi > _I64_MAX):
                    lo = hi = None
            return _DV(tok, lo=lo, hi=hi)
        if obj is abs:
            src = args[0] if args else _DV()
            if src.is_col and src.tok in _NUMERIC_TOKENS:
                lo, hi = _abs_interval(src.lo, src.hi)
                return _DV(src.tok, lo=lo, hi=hi)
            return _DV()
        if isinstance(obj, np.ufunc):
            name = obj.__name__
            srcs = [s for s in args if s.is_col]
            if not srcs:
                return _DV()
            if name in _FLOAT_UFUNCS:
                tok = F4 if all(s.tok == F4 for s in srcs) else F8
                return _DV(tok)
            if name in _PRESERVE_UFUNCS:
                s = srcs[0]
                if name in ("abs", "absolute", "fabs"):
                    lo, hi = _abs_interval(s.lo, s.hi)
                    return _DV(s.tok, lo=lo, hi=hi)
                if name in ("negative",):
                    lo = -s.hi if s.hi is not None else None
                    hi = -s.lo if s.lo is not None else None
                    return _DV(s.tok, lo=lo, hi=hi)
                return _DV(s.tok, lo=s.lo, hi=s.hi)
            if name in _PROMOTE_UFUNCS and len(args) == 2:
                return self._binary(args[0], args[1], "+") \
                    ._with_minmax(args)
            return _DV()
        fname = getattr(obj, "__name__", "")
        mod = (getattr(obj, "__module__", None) or "").split(".")[0]
        if mod == "numpy" and fname in ("where", "clip"):
            cands = [s for s in args[1:] if s.is_col or s.tok == "const"] \
                if fname == "where" else \
                [s for s in args if s.is_col or s.tok == "const"]
            out = None
            for c in cands:
                out = c if out is None else self._binary(out, c, "+")
            if out is not None and out.is_col:
                # bounds of a select/clamp stay within the operands'
                # combined range; '+' above overshoots, so recompute
                los = [c.lo for c in cands]
                his = [c.hi for c in cands]
                if all(v is not None for v in los + his):
                    return _DV(out.tok, lo=min(los), hi=max(his))
                return _DV(out.tok)
            return _DV()
        return _DV()

    # ---- main walk --------------------------------------------------
    def run(self) -> "_DtypeSim":
        if not self.ok:
            self._bail("UDF does not take exactly one element "
                       "parameter")
            return self
        stack: List[_DV] = []
        try:
            for ins in dis.get_instructions(self.code):
                op, argval, arg = ins.opname, ins.argval, ins.arg
                if op in _NOP_OPS:
                    continue
                if op == "LOAD_FAST":
                    stack.append(self.locals.get(argval, _DV()))
                elif op == "STORE_FAST":
                    self.locals[argval] = stack.pop()
                elif op == "DELETE_FAST":
                    self.locals.pop(argval, None)
                elif op == "LOAD_CONST":
                    stack.append(_const_dv(argval))
                elif op in ("LOAD_GLOBAL", "LOAD_NAME"):
                    g = self.fn.__globals__
                    obj = g.get(argval, getattr(
                        __import__("builtins"), str(argval), None))
                    stack.append(_DV("obj", const=obj)
                                 if obj is not None else _DV())
                elif op in ("LOAD_DEREF", "LOAD_CLOSURE"):
                    if argval in self._closure:
                        stack.append(_DV("obj",
                                         const=self._closure[argval]))
                    else:
                        stack.append(_DV())
                elif op in ("LOAD_ATTR", "LOAD_METHOD"):
                    base = stack.pop()
                    if base.tok == "obj":
                        try:
                            stack.append(_DV("obj", const=getattr(
                                base.const, argval)))
                        except Exception:
                            stack.append(_DV())
                    else:
                        stack.append(_DV())
                elif op == "PUSH_NULL":
                    stack.append(_DV("null"))
                elif op in _LEGACY_BINOP:
                    b, a = stack.pop(), stack.pop()
                    stack.append(self._binary(a, b, _LEGACY_BINOP[op]))
                elif op == "BINARY_OP":  # 3.11+
                    b, a = stack.pop(), stack.pop()
                    sym = ins.argrepr
                    if sym.endswith("=") and sym not in ("<=", ">=",
                                                         "==", "!="):
                        sym = sym[:-1]
                    stack.append(self._binary(a, b, sym))
                elif op == "BINARY_SUBSCR":
                    idx, base = stack.pop(), stack.pop()
                    if base.tok == "tuple" and idx.tok == "const" \
                            and isinstance(idx.const, int) \
                            and -len(base.fields) <= idx.const \
                            < len(base.fields):
                        stack.append(base.fields[idx.const])
                    else:
                        stack.append(_DV())
                elif op in ("UNARY_NEGATIVE",):
                    a = stack.pop()
                    if a.is_col and a.tok in _NUMERIC_TOKENS:
                        lo = -a.hi if a.hi is not None else None
                        hi = -a.lo if a.lo is not None else None
                        stack.append(_DV(a.tok, lo=lo, hi=hi))
                    elif a.tok == "const":
                        stack.append(_const_dv(-a.const)
                                     if isinstance(a.const, (int, float))
                                     else _DV())
                    else:
                        stack.append(_DV())
                elif op in ("UNARY_POSITIVE",):
                    pass  # identity: leave the operand in place
                elif op == "UNARY_INVERT":
                    a = stack.pop()
                    stack.append(_DV(BOOL) if a.tok == BOOL else _DV())
                elif op == "UNARY_NOT":
                    stack.pop()
                    stack.append(_DV())  # `not column` raises; probe path
                elif op == "COMPARE_OP":
                    b, a = stack.pop(), stack.pop()
                    stack.append(self._compare(a, b))
                elif op in ("IS_OP", "CONTAINS_OP"):
                    stack.pop(), stack.pop()
                    stack.append(_DV())
                elif op == "BUILD_TUPLE":
                    n = arg or 0
                    parts = [stack.pop() for _ in range(n)][::-1]
                    stack.append(_DV("tuple", fields=tuple(parts)))
                elif op == "UNPACK_SEQUENCE":
                    v = stack.pop()
                    n = arg or 0
                    if v.tok == "tuple" and len(v.fields) == n:
                        stack.extend(reversed(v.fields))
                    else:
                        stack.extend(_DV() for _ in range(n))
                elif op in ("CALL_FUNCTION", "CALL_METHOD"):
                    n = arg or 0
                    args = [stack.pop() for _ in range(n)][::-1]
                    callee = stack.pop()
                    stack.append(self._call(callee, args))
                elif op == "CALL_FUNCTION_KW":
                    stack.pop()
                    n = arg or 0
                    args = [stack.pop() for _ in range(n)][::-1]
                    callee = stack.pop()
                    stack.append(self._call(callee, args))
                elif op == "CALL":  # 3.11+
                    n = arg or 0
                    args = [stack.pop() for _ in range(n)][::-1]
                    callee = stack.pop()
                    if stack and stack[-1].tok == "null":
                        stack.pop()
                    stack.append(self._call(callee, args))
                elif op == "POP_TOP":
                    stack.pop()
                elif op == "DUP_TOP":
                    stack.append(stack[-1])
                elif op == "COPY":
                    stack.append(stack[-(arg or 1)])
                elif op == "SWAP":
                    i = arg or 2
                    stack[-1], stack[-i] = stack[-i], stack[-1]
                elif op == "ROT_TWO":
                    stack[-1], stack[-2] = stack[-2], stack[-1]
                elif op == "ROT_THREE":
                    stack[-1], stack[-2], stack[-3] = \
                        stack[-2], stack[-3], stack[-1]
                elif op in ("RETURN_VALUE", "RETURN_CONST"):
                    self.ret = (stack.pop() if op == "RETURN_VALUE"
                                else _const_dv(argval))
                    return self
                else:
                    self._bail(f"bytecode '{op}' not dtype-modelled")
                    return self
        except Exception as e:  # never break the pipeline
            self._bail(f"dtype walk failed: {e!r}")
            return self
        self._bail("no return reached")
        return self

    def _compare(self, a: _DV, b: _DV) -> _DV:
        def comparable(v):
            return (v.is_col and v.tok in
                    (_NUMERIC_TOKENS | {STR, BOOL})) \
                or (v.tok == "const"
                    and isinstance(v.const, (int, float, str, bool)))
        if comparable(a) and comparable(b):
            ta = a.tok if a.is_col else _const_token(a)
            tb = b.tok if b.is_col else _const_token(b)
            # numeric vs numeric or str vs str compare elementwise;
            # mixed kinds diverge (numpy broadcasts, python raises or
            # compares by type) — conservative
            num = _NUMERIC_TOKENS | {BOOL, "pyint", "pyfloat"}
            str_like = {STR}
            if (ta in num and tb in num) or \
                    (ta in str_like and tb in str_like):
                return _DV(BOOL)
        return _DV()


# monkey-free helper: _DV needs a small combinator for promote ufuncs
def _with_minmax(self, args):
    los = [a.lo for a in args]
    his = [a.hi for a in args]
    if self.is_col and all(v is not None for v in los + his):
        return _DV(self.tok, lo=min(los), hi=max(his))
    return self


_DV._with_minmax = _with_minmax


def _const_token(v: _DV) -> Optional[str]:
    if v.tok != "const":
        return None
    if type(v.const) is bool:
        return BOOL
    if type(v.const) is int:
        return "pyint"
    if type(v.const) is float:
        return "pyfloat"
    if type(v.const) is str:
        return STR
    return None


def _promote_tokens(a: _DV, b: _DV, sym: str) -> Optional[str]:
    """Numpy-2 result dtype for a binary op over numeric operands
    (python consts are weak per NEP 50).  None = not provable."""
    ta = a.tok if a.is_col else _const_token(a)
    tb = b.tok if b.is_col else _const_token(b)
    weak = {"pyint", "pyfloat"}
    if ta in weak and tb in weak:
        return None  # const·const handled upstream
    col_toks = [t for t in (ta, tb) if t in _NUMERIC_TOKENS]
    if not col_toks:
        return None
    consts = [t for t in (ta, tb) if t in weak]
    if sym == "/":
        if any(t in (F4,) for t in col_toks) \
                and all(t == F4 for t in col_toks):
            return F4
        if any(t == F4 for t in col_toks) and len(col_toks) == 1:
            return F4  # f4 / weak-const
        return F8
    has_float = any(t in _FLOAT_TOKENS for t in col_toks) \
        or "pyfloat" in consts
    if sym in ("<<", ">>", "&", "|", "^"):
        if has_float:
            return None
        return I8 if I8 in col_toks else I4
    if not has_float:
        if sym == "**":
            return I8 if I8 in col_toks else I4
        return I8 if I8 in col_toks else I4
    # float result: f4 only when no f8/i8/i4 column widens it
    if all(t == F4 for t in col_toks):
        return F4
    if F4 in col_toks and any(t in (F8, I8, I4) for t in col_toks):
        return F8
    if F4 in col_toks:
        return F4
    return F8


def _spans_zero(lo, hi) -> bool:
    if lo is None or hi is None:
        return True
    return lo <= 0 <= hi


def _abs_interval(lo, hi):
    if lo is None or hi is None:
        return None, None
    if lo >= 0:
        return lo, hi
    if hi <= 0:
        return -hi, -lo
    return 0.0, max(-lo, hi)


def _interval(sym, a_lo, a_hi, b_lo, b_hi):
    """Interval arithmetic for the value-range lattice; (None, None)
    when a bound cannot be proven."""
    if None in (a_lo, a_hi, b_lo, b_hi):
        return None, None
    try:
        if sym == "+":
            return a_lo + b_lo, a_hi + b_hi
        if sym == "-":
            return a_lo - b_hi, a_hi - b_lo
        if sym == "*":
            prods = (a_lo * b_lo, a_lo * b_hi, a_hi * b_lo, a_hi * b_hi)
            return min(prods), max(prods)
        if sym == "/":
            return None, None  # float result: no wraparound to guard
        if sym in ("//", "%"):
            if _spans_zero(b_lo, b_hi):
                return None, None
            qs = (a_lo / b_lo, a_lo / b_hi, a_hi / b_lo, a_hi / b_hi)
            if sym == "//":
                return min(qs) - 1, max(qs) + 1
            m = max(abs(b_lo), abs(b_hi))
            return -m, m
        if sym == "<<":
            if b_lo != b_hi or b_lo < 0 or b_lo > 63:
                return None, None
            f = float(2 ** int(b_lo))
            return a_lo * f, a_hi * f
        if sym == ">>":
            return (min(a_lo, 0), max(a_hi, 0))
        if sym == "**":
            if b_lo != b_hi or b_lo < 0 or b_lo != int(b_lo):
                return None, None
            e = int(b_lo)
            cands = [a_lo ** e, a_hi ** e]
            if _spans_zero(a_lo, a_hi):
                cands.append(0.0)
            return min(cands), max(cands)
    except OverflowError:
        return float("-inf"), float("inf")
    return None, None


# ---------------------------------------------------------------------
# kernel verdicts
# ---------------------------------------------------------------------


@dataclass
class KernelVerdict:
    """Type-flow verdict for one map/filter column kernel."""

    node_id: int
    name: str
    kind: str                   # "map" | "filter"
    proven: bool
    out_schema: ColumnSchema
    hazards: List[str] = field(default_factory=list)
    note: str = ""

    def describe(self) -> str:
        state = "proven" if self.proven else "not proven"
        extra = f"; hazards: {'; '.join(self.hazards)}" \
            if self.hazards else ""
        if self.note and not self.proven:
            extra += f"; {self.note}"
        return (f"{self.kind} kernel {state} "
                f"-> {self.out_schema.describe()}{extra}")

    def to_dict(self) -> dict:
        return {"node_id": self.node_id, "name": self.name,
                "kind": self.kind, "proven": self.proven,
                "out_schema": self.out_schema.to_dict(),
                "hazards": list(self.hazards), "note": self.note}


def _param_dv(schema: ColumnSchema) -> _DV:
    if schema.scalar:
        c = schema.cols[0]
        return _DV(c.token, lo=c.lo, hi=c.hi)
    return _DV("tuple", fields=tuple(
        _DV(c.token, lo=c.lo, hi=c.hi) for c in schema.cols))


def _schema_from_ret(ret: _DV, in_schema: ColumnSchema
                     ) -> ColumnSchema:
    """Map-kernel output value → output ColumnSchema (timestamps pass
    through map/filter unchanged)."""
    def col_of(name: str, v: _DV) -> Optional[Col]:
        if v.is_col and v.tok != OBJ:
            return Col(name, v.tok, v.lo, v.hi)
        if v.tok == "const":
            t = _const_token(v)
            if t == "pyint":
                return Col(name, I8, float(v.const), float(v.const))
            if t == "pyfloat":
                return Col(name, F8, float(v.const), float(v.const))
            if t == BOOL:
                return Col(name, BOOL)
            # const str broadcasts to a <U array, which has no wire
            # tier — track it as "obj"
            if t == STR:
                return Col(name, OBJ)
        return None

    if ret is None:
        return _unknown("return value not dtype-provable")
    if ret.tok == "tuple":
        cols = []
        for i, f in enumerate(ret.fields):
            c = col_of(f"f{i}", f)
            if c is None:
                return _unknown(f"tuple field {i} not dtype-provable")
            cols.append(c)
        if not cols:
            return _unknown("empty tuple return")
        return ColumnSchema(tuple(cols), in_schema.ts, conclusive=True)
    c = col_of("v", ret)
    if c is None:
        return _unknown("return dtype not provable")
    return ColumnSchema((c,), in_schema.ts, conclusive=True)


def _kernel_udf(op, attr: str):
    """The raw Python function behind a map/filter operator's UDF
    (same unwrap discipline as the linter's liftability check)."""
    uf = getattr(op, "user_function", None)
    fn = getattr(uf, "_fn", None)
    if not callable(fn):
        fn = getattr(uf, attr, uf)
    return fn


def analyze_map_kernel(node_id: int, name: str, fn,
                       in_schema: ColumnSchema) -> KernelVerdict:
    rep = analyze_udf(fn, name=name)
    if rep.verdict != LIFTABLE:
        return KernelVerdict(
            node_id, name, "map", False,
            _unknown(f"UDF {rep.verdict}"),
            note=f"liftability: {rep.verdict}")
    if not in_schema.conclusive:
        return KernelVerdict(node_id, name, "map", False,
                             _unknown("input schema inconclusive"),
                             note="input schema inconclusive")
    raw, skip_first = unwrap_udf(fn)
    if raw is None:
        return KernelVerdict(node_id, name, "map", False,
                             _unknown("no Python bytecode"),
                             note="no Python bytecode")
    sim = _DtypeSim(raw, skip_first, _param_dv(in_schema)).run()
    out = _schema_from_ret(sim.ret, in_schema)
    if sim.note and not out.conclusive:
        out = _unknown(sim.note or out.note)
    proven = out.conclusive and not sim.hazards
    return KernelVerdict(node_id, name, "map", proven, out,
                         hazards=sim.hazards, note=sim.note)


def analyze_filter_kernel(node_id: int, name: str, fn,
                          in_schema: ColumnSchema) -> KernelVerdict:
    # a filter NEVER changes values, so its output schema is the
    # input schema whether or not the kernel is proven
    out = in_schema
    rep = analyze_udf(fn, name=name)
    if rep.verdict != LIFTABLE:
        return KernelVerdict(node_id, name, "filter", False, out,
                             note=f"liftability: {rep.verdict}")
    if not in_schema.conclusive:
        return KernelVerdict(node_id, name, "filter", False, out,
                             note="input schema inconclusive")
    raw, skip_first = unwrap_udf(fn)
    if raw is None:
        return KernelVerdict(node_id, name, "filter", False, out,
                             note="no Python bytecode")
    sim = _DtypeSim(raw, skip_first, _param_dv(in_schema)).run()
    is_bool = sim.ret is not None and (
        sim.ret.tok == BOOL
        or (sim.ret.tok == "const" and type(sim.ret.const) is bool))
    proven = is_bool and not sim.hazards
    note = sim.note if sim.note else \
        ("" if is_bool else "predicate not proven to yield a bool mask")
    return KernelVerdict(node_id, name, "filter", proven, out,
                         hazards=sim.hazards, note=note)


# ---------------------------------------------------------------------
# graph propagation
# ---------------------------------------------------------------------


@dataclass
class EdgeFlow:
    """Type-flow facts for one StreamGraph edge."""

    edge_index: int
    source_id: int
    target_id: int
    source_name: str
    target_name: str
    exchange: bool              # non-forward partitioner
    schema: ColumnSchema
    tier: Optional[str] = None  # "col" | "pickle" | None
    tier_blocker: str = ""      # offending dtype token for "pickle"

    def to_dict(self) -> dict:
        return {
            "edge": self.edge_index,
            "from": self.source_name, "to": self.target_name,
            "from_id": self.source_id, "to_id": self.target_id,
            "exchange": self.exchange,
            "schema": self.schema.to_dict(),
            "codec_tier": self.tier,
            "tier_blocker": self.tier_blocker,
        }


@dataclass
class FootprintEstimate:
    """AOT device state footprint for one device window operator.
    ``slots`` is a LOWER bound (distinct keys read off the bounded
    source; (key, window) slot tables only grow from there)."""

    node_id: int
    name: str
    slots: Optional[int]
    bytes_per_slot: int
    budget_slots: Optional[int]
    note: str = ""

    @property
    def total_bytes(self) -> Optional[int]:
        if self.slots is None:
            return None
        return self.slots * self.bytes_per_slot

    @property
    def over_budget(self) -> bool:
        return (self.slots is not None and self.budget_slots is not None
                and self.slots > self.budget_slots)

    def to_dict(self) -> dict:
        return {"node_id": self.node_id, "name": self.name,
                "slots": self.slots,
                "bytes_per_slot": self.bytes_per_slot,
                "total_bytes": self.total_bytes,
                "budget_slots": self.budget_slots,
                "over_budget": self.over_budget, "note": self.note}


@dataclass
class TypeflowReport:
    """Everything the prover learned about one StreamGraph."""

    node_schemas: Dict[int, ColumnSchema] = field(default_factory=dict)
    edges: List[EdgeFlow] = field(default_factory=list)
    kernels: Dict[int, KernelVerdict] = field(default_factory=dict)
    footprints: Dict[int, FootprintEstimate] = field(
        default_factory=dict)
    diagnostics: Diagnostics = field(default_factory=Diagnostics)

    def edge_schema(self, source_id: int, target_id: int
                    ) -> Optional[ColumnSchema]:
        for f in self.edges:
            if f.source_id == source_id and f.target_id == target_id:
                return f.schema
        return None

    def summary(self) -> dict:
        kernels = list(self.kernels.values())
        slots = [fp.total_bytes for fp in self.footprints.values()
                 if fp.total_bytes is not None]
        return {
            "edges_total": len(self.edges),
            "edges_conclusive": sum(
                1 for f in self.edges if f.schema.conclusive),
            "kernels_total": len(kernels),
            "kernels_proven": sum(1 for k in kernels if k.proven),
            "pickle_edges": sum(
                1 for f in self.edges
                if f.exchange and f.tier == "pickle"),
            "predicted_state_bytes": int(sum(slots)),
        }

    def to_dict(self) -> dict:
        return {
            "edges": [f.to_dict() for f in self.edges],
            "kernels": {str(k): v.to_dict()
                        for k, v in self.kernels.items()},
            "footprints": {str(k): v.to_dict()
                           for k, v in self.footprints.items()},
            "summary": self.summary(),
            "diagnostics": self.diagnostics.to_dict(),
        }


def _toposort(graph) -> List[int]:
    indeg = {nid: 0 for nid in graph.nodes}
    for e in graph.edges:
        if not e.is_feedback and e.target_id in indeg:
            indeg[e.target_id] += 1
    from collections import deque
    work = deque(nid for nid, d in indeg.items() if d == 0)
    order = []
    while work:
        nid = work.popleft()
        order.append(nid)
        for e in graph.out_edges(nid):
            if e.is_feedback:
                continue
            indeg[e.target_id] -= 1
            if indeg[e.target_id] == 0:
                work.append(e.target_id)
    # cycles (FT160 territory) simply get no schema
    return order


def _merge_schemas(schemas: List[ColumnSchema]
                   ) -> Tuple[ColumnSchema, bool]:
    """Join the in-edge schemas of a multi-input node.  Returns
    (schema, conflict): conflict is True when two CONCLUSIVE schemas
    disagree on dtypes/arity (FT188)."""
    if not schemas:
        return _unknown("no input"), False
    if len(schemas) == 1:
        return schemas[0], False
    conclusive = [s for s in schemas if s.conclusive]
    if len(conclusive) != len(schemas):
        return _unknown("inconclusive merge input"), False
    sigs = {(s.tokens(), s.scalar) for s in conclusive}
    if len(sigs) > 1:
        return _unknown("schema conflict at merge"), True
    # same dtypes: union the value intervals, weaken the ts mode
    base = conclusive[0]
    cols = []
    for i, c in enumerate(base.cols):
        los = [s.cols[i].lo for s in conclusive]
        his = [s.cols[i].hi for s in conclusive]
        lo = min(los) if all(v is not None for v in los) else None
        hi = max(his) if all(v is not None for v in his) else None
        cols.append(Col(c.name, c.token, lo, hi))
    ts_modes = {s.ts for s in conclusive}
    ts = ts_modes.pop() if len(ts_modes) == 1 else "masked"
    return ColumnSchema(tuple(cols), ts, conclusive=True), False


def _bytes_per_slot(agg) -> Optional[int]:
    try:
        specs = agg.state_specs()
        total = 0
        for spec in specs.values():
            n = 1
            for d in spec.shape:
                n *= int(d)
            total += int(np.dtype(spec.dtype).itemsize) * n
        return total
    except Exception:
        return None


def _aot_rows(src_fn) -> Optional[list]:
    """The source's row values, read AOT (bounded collections only)."""
    from flink_tpu.streaming.columnar import VectorizedCollectionSource
    from flink_tpu.streaming.sources import FromCollectionSource
    try:
        if isinstance(src_fn, VectorizedCollectionSource):
            if src_fn._batch is None or len(src_fn._batch) > _MAX_AOT_ROWS:
                return None
            return src_fn._batch.row_values()
        if isinstance(src_fn, FromCollectionSource):
            items = src_fn.items
            if not items or len(items) > _MAX_AOT_ROWS:
                return None
            if src_fn.timestamped:
                return [v for v, _ in items]
            return list(items)
    except Exception:
        return None
    return None


def _distinct_keys(rows: list, key_selector) -> Optional[int]:
    try:
        if key_selector is None:
            return None
        get = getattr(key_selector, "get_key", key_selector)
        return len({get(v) for v in rows})
    except Exception:
        return None


def analyze_graph(graph, config=None, ops: Optional[Dict[int, Any]]
                  = None) -> TypeflowReport:
    """Run the type-flow pass over a StreamGraph.  ``ops`` lets the
    graph linter share its already-instantiated operators; otherwise
    the node factories run here (fault-isolated per node)."""
    from flink_tpu.streaming.columnar import (
        BatchKeyGroupSplitOperator,
        ColumnarSource,
        ColumnarWindowOperator,
    )
    from flink_tpu.streaming.operators import StreamFilter, StreamMap
    from flink_tpu.streaming.partitioners import ForwardPartitioner
    from flink_tpu.streaming.sources import (
        StreamSource,
        TimestampsAndWatermarksOperator,
    )

    report = TypeflowReport(
        diagnostics=Diagnostics(job_name=getattr(graph, "job_name",
                                                 None)))
    if ops is None:
        ops = {}
        for nid, node in graph.nodes.items():
            try:
                ops[nid] = node.operator_factory()
            except Exception:
                ops[nid] = None

    src_fns: Dict[int, Any] = {}
    conflict_nodes = set()

    for nid in _toposort(graph):
        node = graph.nodes[nid]
        op = ops.get(nid)
        if op is None:
            report.node_schemas[nid] = _unknown(
                "operator construction failed")
            continue
        in_edges = [e for e in graph.in_edges(nid) if not e.is_feedback]
        in_schemas = [report.node_schemas.get(e.source_id,
                                              _unknown("no schema"))
                      for e in in_edges]
        in_schema, conflict = _merge_schemas(in_schemas)
        if conflict:
            conflict_nodes.add(nid)
            ups = ", ".join(
                f"'{graph.nodes[e.source_id].name}' "
                f"({report.node_schemas[e.source_id].describe()})"
                for e in in_edges)
            report.diagnostics.add(
                "FT188",
                f"schema conflict at merge point '{node.name}': "
                f"inputs disagree — {ups}; the merged stream loses "
                f"its columnar schema (pickle codec, boxed kernels)",
                operator_id=nid, operator_name=node.name,
                hint="map the branches to one common element shape "
                     "before union()")

        if isinstance(op, StreamSource):
            schema, src_fn = _source_schema(op)
            src_fns[nid] = src_fn
            report.node_schemas[nid] = schema
            continue
        if isinstance(op, StreamMap):
            fn = _kernel_udf(op, "map")
            verdict = analyze_map_kernel(nid, node.name, fn, in_schema)
            report.kernels[nid] = verdict
            report.node_schemas[nid] = verdict.out_schema \
                if verdict.proven else _unknown(
                    verdict.note or "map kernel not proven")
            for hz in verdict.hazards:
                report.diagnostics.add(
                    "FT186",
                    f"map '{node.name}' has a dtype-overflow hazard: "
                    f"{hz} — the kernel keeps its first-batch probe",
                    operator_id=nid, operator_name=node.name,
                    hint="cast to float64, or keep values inside "
                         "int64 — python scalars don't wrap, int64 "
                         "columns do")
            continue
        if isinstance(op, StreamFilter):
            fn = _kernel_udf(op, "filter")
            verdict = analyze_filter_kernel(nid, node.name, fn,
                                            in_schema)
            report.kernels[nid] = verdict
            # values pass through a filter untouched either way
            report.node_schemas[nid] = in_schema
            for hz in verdict.hazards:
                report.diagnostics.add(
                    "FT186",
                    f"filter '{node.name}' has a dtype-overflow "
                    f"hazard: {hz} — the kernel keeps its probe",
                    operator_id=nid, operator_name=node.name)
            continue
        if isinstance(op, TimestampsAndWatermarksOperator):
            if in_schema.conclusive:
                report.node_schemas[nid] = ColumnSchema(
                    in_schema.cols, "all", conclusive=True)
            else:
                report.node_schemas[nid] = in_schema
            continue
        if isinstance(op, BatchKeyGroupSplitOperator):
            # routing wrapper: sub-batches keep the element schema
            report.node_schemas[nid] = in_schema
            continue
        from flink_tpu.streaming.operators import StreamSink
        if isinstance(op, StreamSink):
            report.node_schemas[nid] = in_schema
            continue
        report.node_schemas[nid] = _unknown(
            f"no type-flow rule for {type(op).__name__}")

    # ---- per-edge flows + FT185 -------------------------------------
    for i, e in enumerate(graph.edges):
        up = graph.nodes[e.source_id]
        down = graph.nodes[e.target_id]
        schema = report.node_schemas.get(e.source_id,
                                         _unknown("no schema"))
        exchange = not isinstance(e.partitioner, ForwardPartitioner) \
            and not e.is_feedback
        tier, blocker = codec_tier(schema)
        flow = EdgeFlow(i, e.source_id, e.target_id, up.name,
                        down.name, exchange, schema, tier, blocker)
        report.edges.append(flow)
        if exchange and tier == "pickle":
            report.diagnostics.add(
                "FT185",
                f"exchange edge '{up.name}' -> '{down.name}' "
                f"conclusively demotes to the pickle wire codec: "
                f"column dtype '{blocker}' (produced by '{up.name}') "
                f"has no columnar tier",
                operator_id=e.source_id, operator_name=up.name,
                hint="int64/float64/str columns ride the zero-copy "
                     "tier; cast bools and narrow dtypes before the "
                     "exchange")

    # ---- device state footprints + FT187 ----------------------------
    budget = None
    if config is not None:
        try:
            from flink_tpu.core.config import StateBackendOptions
            budget = config.get_integer(
                StateBackendOptions.TPU_MAX_DEVICE_SLOTS)
        except Exception:
            budget = None

    from flink_tpu.ops.device_agg import DeviceAggregateFunction
    for nid, node in graph.nodes.items():
        op = ops.get(nid)
        agg = getattr(op, "agg", None)
        if not isinstance(agg, DeviceAggregateFunction):
            continue
        bps = _bytes_per_slot(agg)
        if bps is None:
            continue
        slots = None
        note = ""
        upstream_sources = [u for u in _upstream_ids(graph, nid)
                            if u in src_fns]
        if isinstance(op, ColumnarWindowOperator):
            for u in upstream_sources:
                fn = src_fns[u]
                if isinstance(fn, ColumnarSource) \
                        and op.key_col in fn.cols:
                    try:
                        slots = int(np.unique(
                            np.asarray(fn.cols[op.key_col])).size)
                        note = f"distinct '{op.key_col}' keys AOT"
                    except Exception:
                        slots = None
                    break
        if slots is None:
            selector = getattr(node, "key_selector", None)
            for u in upstream_sources:
                rows = _aot_rows(src_fns[u])
                if rows is None:
                    continue
                n = _distinct_keys(rows, selector)
                if n is not None:
                    slots = n
                    note = "distinct keys via key selector AOT"
                    break
        fp = FootprintEstimate(nid, node.name, slots, bps, budget,
                               note=note)
        report.footprints[nid] = fp
        if fp.over_budget:
            report.diagnostics.add(
                "FT187",
                f"device window '{node.name}' needs at least "
                f"{fp.slots} state slots x {bps} B/slot = "
                f"{fp.total_bytes} B, over the configured "
                f"state.backend.tpu.max-device-slots budget of "
                f"{budget} — the backend will spill to host at "
                f"runtime",
                operator_id=nid, operator_name=node.name,
                hint="raise state.backend.tpu.max-device-slots, or "
                     "reduce key cardinality before the window")
    return report


def _upstream_ids(graph, nid) -> List[int]:
    from collections import deque
    seen, work = set(), deque([nid])
    while work:
        cur = work.popleft()
        for e in graph.in_edges(cur):
            if e.is_feedback or e.source_id in seen:
                continue
            seen.add(e.source_id)
            work.append(e.source_id)
    return list(seen)


# ---------------------------------------------------------------------
# feeding verdicts into the runtime
# ---------------------------------------------------------------------


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


#: pre-size cap: never allocate more than this many slots AOT, even
#: for a huge predicted cardinality (the engine still grows on demand)
_MAX_PRESIZE_SLOTS = 1 << 20


def _wrap_factory(node, attrs: dict) -> None:
    """Re-wrap the node's operator factory so every built instance
    carries the static verdict attributes.  Idempotent: re-applying
    replaces the previous wrap instead of stacking."""
    orig = getattr(node.operator_factory, "_typeflow_orig",
                   node.operator_factory)

    def factory(_orig=orig, _attrs=dict(attrs)):
        op = _orig()
        for k, v in _attrs.items():
            if k == "_presize_slots":
                cap = getattr(op, "initial_capacity", None)
                if isinstance(cap, int):
                    op.initial_capacity = max(
                        cap, _next_pow2(min(v, _MAX_PRESIZE_SLOTS)))
                continue
            setattr(op, k, v)
        return op

    factory._typeflow_orig = orig
    node.operator_factory = factory


def apply_static(graph, report: TypeflowReport) -> dict:
    """Feed conclusive type-flow verdicts into the runtime (the PR 4
    ``decided_by=static`` discipline, graph-wide):

    - proven map/filter kernels get ``_static_kernel=True`` (the
      ``_ColumnKernelMixin`` skips the first-batch probe; the output
      shape validation still demotes on any runtime mismatch),
    - exchange edges with a conclusive codec tier get
      ``predicted_codec_tier`` (carried onto the JobEdge and into
      netchannel's per-edge hint table),
    - device window operators with an AOT slot estimate get
      ``_predicted_slots`` and a pre-sized ``initial_capacity``.

    Returns ``{"kernels_proven", "edges_predicted", "footprints"}``.
    """
    kernels = 0
    for nid, verdict in report.kernels.items():
        node = graph.nodes.get(nid)
        if node is None:
            continue
        if verdict.proven:
            _wrap_factory(node, {
                "_static_kernel": True,
                "_typeflow_verdict": verdict.describe(),
            })
            kernels += 1
        else:
            # record the verdict so the runtime fallback warning can
            # name it even when the kernel was not proven
            _wrap_factory(node, {
                "_typeflow_verdict": verdict.describe(),
            })

    edges = 0
    for flow in report.edges:
        if flow.exchange and flow.tier is not None \
                and flow.edge_index < len(graph.edges):
            graph.edges[flow.edge_index].predicted_codec_tier = \
                flow.tier
            edges += 1

    footprints = 0
    for nid, fp in report.footprints.items():
        node = graph.nodes.get(nid)
        if node is None or fp.slots is None:
            continue
        _wrap_factory(node, {
            "_predicted_slots": fp.slots,
            "_presize_slots": fp.slots,
        })
        footprints += 1
    return {"kernels_proven": kernels, "edges_predicted": edges,
            "footprints": footprints}
