"""Lint a job script without running it.

``flink_tpu lint <script.py>`` imports the script the same way
``flink_tpu run`` does (runpy, ``__main__``), but with
``StreamExecutionEnvironment`` patched so that

- every environment the script constructs is captured, and
- ``execute()`` / ``execute_async()`` build the graph and return a
  permissive stand-in result instead of running the job.

After the script finishes (or dies — a script crash is reported, not
fatal), every captured environment is validated with the pre-flight
linter, including environments the script built but never executed.
"""

from __future__ import annotations

import runpy
import sys
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

from flink_tpu.analysis.diagnostics import Diagnostics


class _FakeResult:
    """Stands in for JobExecutionResult: common fields are real,
    anything else resolves to None rather than AttributeError."""

    def __init__(self, job_name: str):
        self.job_name = job_name
        self.accumulators: dict = {}
        self.checkpoints_completed = 0
        self.restarts = 0
        self.region_restarts = 0
        self.cancelled = False

    def __getattr__(self, name):
        return None


class _FakeClient:
    """Stands in for JobClient (execute_async)."""

    def __init__(self, job_name: str):
        self.job_name = job_name
        self.job_id = f"lint-{job_name}"

    def wait(self, timeout: Optional[float] = None):
        return _FakeResult(self.job_name)

    def cancel(self) -> None:
        pass

    def stop_with_savepoint(self, path: str) -> str:
        return path

    def trigger_savepoint(self, path: str) -> str:
        return path

    def __getattr__(self, name):
        return lambda *a, **kw: None


@dataclass
class ScriptLintResult:
    path: str
    #: (job_name, report) per captured environment, in creation order
    reports: List[Tuple[str, Diagnostics]] = field(default_factory=list)
    #: exception the script itself raised while building graphs, if any
    script_error: Optional[BaseException] = None

    def has_errors(self) -> bool:
        return any(r.has_errors() for _, r in self.reports)

    def counts(self) -> dict:
        total = {"error": 0, "warning": 0, "info": 0}
        for _, r in self.reports:
            for k, v in r.counts().items():
                total[k] = total.get(k, 0) + v
        return total


def lint_script(path: str, argv: Optional[List[str]] = None,
                types: bool = False) -> ScriptLintResult:
    """Capture-and-validate run of one job script (see module doc).

    With ``types=True`` (``flink_tpu lint --types``) the column
    type-flow prover also runs per environment: FT185–FT188 findings
    join each report and the per-edge schema dump rides along as
    ``report.typeflow`` (surfaced by the CLI's ``--json``)."""
    from flink_tpu.streaming.datastream import StreamExecutionEnvironment

    captured: List[Any] = []
    orig_init = StreamExecutionEnvironment.__init__
    orig_execute = StreamExecutionEnvironment.execute
    orig_execute_async = StreamExecutionEnvironment.execute_async

    def lint_init(self, *a, **kw):
        orig_init(self, *a, **kw)
        captured.append(self)

    def lint_execute(self, job_name: str = "job"):
        self.graph.job_name = job_name
        return _FakeResult(job_name)

    def lint_execute_async(self, job_name: str = "job"):
        self.graph.job_name = job_name
        return _FakeClient(job_name)

    result = ScriptLintResult(path=path)
    old_argv = sys.argv
    StreamExecutionEnvironment.__init__ = lint_init
    StreamExecutionEnvironment.execute = lint_execute
    StreamExecutionEnvironment.execute_async = lint_execute_async
    try:
        sys.argv = [path] + list(argv or [])
        try:
            runpy.run_path(path, run_name="__main__")
        except SystemExit:
            pass
        except BaseException as e:  # noqa: BLE001 — report, don't die
            result.script_error = e
    finally:
        sys.argv = old_argv
        StreamExecutionEnvironment.__init__ = orig_init
        StreamExecutionEnvironment.execute = orig_execute
        StreamExecutionEnvironment.execute_async = orig_execute_async

    for env in captured:
        if not env.graph.nodes:
            continue  # constructed but never populated
        try:
            report = env.validate(types=types)
        except Exception as e:  # noqa: BLE001
            report = Diagnostics(job_name=env.graph.job_name)
            report.add("FT199", f"validation crashed: {e!r}")
        result.reports.append((env.graph.job_name, report))
    return result
