"""Structured diagnostics shared by the pre-flight analysis passes.

Every finding carries a stable ``FTxxx`` code, a severity, the operator
it points at, a best-effort source location and a fix hint — the same
shape whether it came from the graph linter (pass 1) or the liftability
analyzer (pass 2), and whether it surfaces through ``env.validate()``,
``execute()`` (warn/strict) or ``flink_tpu lint``.

The code catalog is the documentation contract: docs/static_analysis.md
lists every code below with examples, and tests assert specific codes
for deliberately broken jobs.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

ERROR = "error"
WARNING = "warning"
INFO = "info"

_SEVERITY_ORDER = {ERROR: 0, WARNING: 1, INFO: 2}

#: code -> (default severity, one-line title). The single source of
#: truth for which codes exist; emitting an unknown code is a bug.
CODES: Dict[str, tuple] = {
    # --- keys / serialization ---------------------------------------
    "FT101": (ERROR, "key selector returns an unhashable value"),
    "FT102": (WARNING, "function is not serializable for remote submission"),
    # --- windows / triggers / lateness ------------------------------
    "FT110": (ERROR, "window operator rejected its trigger/assigner combination"),
    "FT111": (ERROR, "non-positive window size, slide or session gap"),
    "FT112": (WARNING, "allowed lateness exceeds the window size"),
    "FT113": (INFO, "window shape falls off the vectorized generic tier"),
    "FT115": (ERROR, "event-time window but no upstream path assigns timestamps"),
    # --- state ------------------------------------------------------
    "FT120": (WARNING, "state descriptor serializer fails a round-trip"),
    "FT140": (WARNING, "unbounded keyed state without a window or TTL"),
    # --- chaining / parallelism -------------------------------------
    "FT130": (INFO, "forward edge not chained"),
    "FT131": (ERROR, "forward partitioner across a parallelism change"),
    # --- topology ---------------------------------------------------
    "FT150": (WARNING, "branch ends without a sink"),
    "FT151": (WARNING, "operator unreachable from any source"),
    "FT160": (ERROR, "cycle outside a declared iteration"),
    "FT170": (ERROR, "duplicate operator uid"),
    "FT171": (INFO, "duplicate operator name"),
    # --- UDF liftability (pass 2) -----------------------------------
    "FT180": (ERROR, "aggregate function is impure"),
    "FT181": (WARNING, "aggregate is conclusively scalar-only (perf footgun)"),
    "FT182": (INFO, "aggregate proven liftable; runtime probe will be skipped"),
    "FT183": (WARNING, "impure map/filter/reduce function"),
    "FT184": (INFO, "columnar batch eligibility of an operator chain"),
    # --- column type flow (pass 3) ----------------------------------
    "FT185": (WARNING, "exchange edge conclusively demotes to the pickle wire tier"),
    "FT186": (WARNING, "dtype-overflow hazard in a lifted kernel"),
    "FT187": (WARNING, "predicted device state footprint exceeds the slot budget"),
    "FT188": (WARNING, "schema conflict at a union/merge point"),
    # --- pre-flight construction / linter self-errors ---------------
    "FT190": (ERROR, "operator factory raised during pre-flight construction"),
    "FT199": (INFO, "linter check skipped (internal error)"),
}


@dataclass
class Diagnostic:
    code: str
    message: str
    severity: Optional[str] = None          # default: catalog severity
    operator_id: Optional[int] = None       # StreamNode id
    operator_name: Optional[str] = None
    location: Optional[str] = None          # "file.py:42"
    hint: Optional[str] = None

    def __post_init__(self):
        if self.severity is None:
            self.severity = CODES.get(self.code, (WARNING, ""))[0]

    def render(self) -> str:
        op = ""
        if self.operator_name is not None:
            op = f" [{self.operator_name}" + (
                f"#{self.operator_id}]" if self.operator_id is not None
                else "]")
        loc = f" ({self.location})" if self.location else ""
        hint = f"\n        hint: {self.hint}" if self.hint else ""
        return (f"{self.severity.upper():7s} {self.code}{op} "
                f"{self.message}{loc}{hint}")

    def to_dict(self) -> dict:
        return {
            "code": self.code,
            "severity": self.severity,
            "message": self.message,
            "operator_id": self.operator_id,
            "operator_name": self.operator_name,
            "location": self.location,
            "hint": self.hint,
        }


@dataclass
class Diagnostics:
    """An ordered report of :class:`Diagnostic` findings for one job."""

    job_name: Optional[str] = None
    _diags: List[Diagnostic] = field(default_factory=list)

    # ---- building ---------------------------------------------------
    def append(self, diag: Diagnostic) -> None:
        self._diags.append(diag)

    def add(self, code: str, message: str, **kw) -> Diagnostic:
        d = Diagnostic(code=code, message=message, **kw)
        self.append(d)
        return d

    def extend(self, other: "Diagnostics") -> None:
        self._diags.extend(other._diags)

    # ---- reading ----------------------------------------------------
    def __iter__(self) -> Iterator[Diagnostic]:
        return iter(sorted(
            self._diags, key=lambda d: _SEVERITY_ORDER.get(d.severity, 3)))

    def __len__(self) -> int:
        return len(self._diags)

    def errors(self) -> List[Diagnostic]:
        return [d for d in self._diags if d.severity == ERROR]

    def warnings(self) -> List[Diagnostic]:
        return [d for d in self._diags if d.severity == WARNING]

    def infos(self) -> List[Diagnostic]:
        return [d for d in self._diags if d.severity == INFO]

    def has_errors(self) -> bool:
        return any(d.severity == ERROR for d in self._diags)

    def by_code(self, code: str) -> List[Diagnostic]:
        return [d for d in self._diags if d.code == code]

    def codes(self) -> List[str]:
        return sorted({d.code for d in self._diags})

    def counts(self) -> Dict[str, int]:
        c = {ERROR: 0, WARNING: 0, INFO: 0}
        for d in self._diags:
            c[d.severity] = c.get(d.severity, 0) + 1
        return c

    # ---- presentation -----------------------------------------------
    def render(self, min_severity: str = INFO) -> str:
        cut = _SEVERITY_ORDER[min_severity]
        lines = [d.render() for d in self
                 if _SEVERITY_ORDER.get(d.severity, 3) <= cut]
        counts = self.counts()
        head = (f"{counts[ERROR]} error(s), {counts[WARNING]} warning(s), "
                f"{counts[INFO]} info")
        if self.job_name:
            head = f"{self.job_name}: {head}"
        return "\n".join([head] + lines)

    def to_dict(self) -> dict:
        return {
            "job_name": self.job_name,
            "counts": self.counts(),
            "diagnostics": [d.to_dict() for d in self],
        }

    def log(self, logger: Optional[logging.Logger] = None,
            limit: int = 25) -> None:
        """Log errors/warnings (warn mode of execute())."""
        logger = logger or logging.getLogger("flink_tpu.lint")
        shown = 0
        for d in self:
            if d.severity == INFO:
                continue
            if shown >= limit:
                logger.warning("... %d more diagnostics suppressed",
                               len(self.errors()) + len(self.warnings())
                               - shown)
                break
            fn = logger.error if d.severity == ERROR else logger.warning
            fn("%s", d.render())
            shown += 1


class JobValidationError(Exception):
    """Raised by strict-mode validation when the report has errors."""

    def __init__(self, report: Diagnostics):
        self.report = report
        super().__init__(
            "job failed pre-flight validation:\n" + report.render())
