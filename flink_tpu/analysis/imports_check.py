"""Pyflakes-lite: unused-import detection over the repo's own sources.

The container has no pyflakes, so ``flink_tpu lint --check-imports``
ships a deliberately conservative AST checker: an import is flagged
only when its bound name appears exactly once in the whole source text
(the import statement itself).  Any other occurrence — code, a
docstring example, ``__all__``, a comment — keeps it.  That trades
recall for a near-zero false-positive rate, which is the right trade
for a checker whose findings people are expected to fix.

``__init__.py`` files are skipped unless they declare ``__all__``
(re-export modules), and ``# noqa`` on the import line always wins.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import List, Optional


@dataclass
class UnusedImport:
    path: str
    line: int
    name: str       # the bound name, e.g. "np" for `import numpy as np`
    statement: str  # e.g. "import numpy as np"

    def render(self) -> str:
        return f"{self.path}:{self.line}: unused import '{self.name}'"


def _bound_names(node) -> List[tuple]:
    """(bound_name, statement_text) pairs for one import node."""
    out = []
    if isinstance(node, ast.Import):
        for alias in node.names:
            bound = alias.asname or alias.name.split(".")[0]
            stmt = f"import {alias.name}" + (
                f" as {alias.asname}" if alias.asname else "")
            out.append((bound, stmt))
    elif isinstance(node, ast.ImportFrom):
        for alias in node.names:
            if alias.name == "*":
                continue  # cannot reason about star imports
            bound = alias.asname or alias.name
            stmt = (f"from {'.' * node.level}{node.module or ''} "
                    f"import {alias.name}"
                    + (f" as {alias.asname}" if alias.asname else ""))
            out.append((bound, stmt))
    return out


def check_file(path: str, source: Optional[str] = None
               ) -> List[UnusedImport]:
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return []

    if path.endswith("__init__.py") and "__all__" not in source:
        return []  # bare re-export package: imports ARE the API

    lines = source.splitlines()
    findings: List[UnusedImport] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        line_text = (lines[node.lineno - 1]
                     if node.lineno - 1 < len(lines) else "")
        if "noqa" in line_text:
            continue
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            continue
        for bound, stmt in _bound_names(node):
            if bound == "_":
                continue
            uses = len(re.findall(rf"\b{re.escape(bound)}\b", source))
            if uses == 1:
                findings.append(UnusedImport(
                    path=path, line=node.lineno, name=bound,
                    statement=stmt))
    return findings


def check_tree(root: str) -> List[UnusedImport]:
    import os
    findings: List[UnusedImport] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                findings.extend(check_file(os.path.join(dirpath, fn)))
    return findings
