"""Pre-flight static analysis: graph linter + UDF liftability.

Two passes over a job before any record flows:

- **Pass 1 — graph linter** (:mod:`flink_tpu.analysis.graph_linter`):
  walks the StreamGraph/JobGraph and checks key selectors, window
  configurations, state serializers, chaining, reachability and
  cycles.  Every finding is a :class:`Diagnostic` with a stable
  ``FTxxx`` code from :data:`CODES`.
- **Pass 2 — liftability analyzer**
  (:mod:`flink_tpu.analysis.liftability`): bytecode analysis of
  AggregateFunction implementations and map/filter/reduce UDFs,
  classifying each as LIFTABLE / SCALAR_ONLY / IMPURE / INCONCLUSIVE.
  Conclusive verdicts pre-decide the generic tier's lift mode so the
  runtime probe is skipped.

Entry points: ``env.validate()``, ``execute()`` with the ``lint.mode``
config key (``off`` | ``warn`` | ``strict``), and the ``flink_tpu
lint`` CLI subcommand.  See docs/static_analysis.md.
"""

from flink_tpu.analysis.diagnostics import (  # noqa: F401
    CODES,
    ERROR,
    INFO,
    WARNING,
    Diagnostic,
    Diagnostics,
    JobValidationError,
)
from flink_tpu.analysis.graph_linter import lint_graph  # noqa: F401
from flink_tpu.analysis.liftability import (  # noqa: F401
    IMPURE,
    INCONCLUSIVE,
    LIFTABLE,
    SCALAR_ONLY,
    AggregateReport,
    UdfReport,
    analyze_aggregate,
    analyze_udf,
)

__all__ = [
    "CODES", "ERROR", "WARNING", "INFO",
    "Diagnostic", "Diagnostics", "JobValidationError",
    "lint_graph",
    "LIFTABLE", "SCALAR_ONLY", "IMPURE", "INCONCLUSIVE",
    "AggregateReport", "UdfReport",
    "analyze_aggregate", "analyze_udf",
]
